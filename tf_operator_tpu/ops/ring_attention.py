"""Ring attention: exact attention over a sequence sharded on the `sp`
mesh axis.

Long-context is first-class in this framework (the reference has no
sequence-length story at all — SURVEY.md §2b calls it absent).  Design
is the standard ring schedule (Liu et al.-style, re-derived here):

- Each sp shard holds Q/K/V for its contiguous sequence chunk.
- K/V blocks rotate around the ring via `lax.ppermute` (neighbour
  ICI hops only — no all-gather, so KV memory stays O(S/n) per chip).
- Each hop combines the local block with a *streaming softmax*
  (flash-attention-style running max / normaliser in float32), so the
  result is exact attention, not an approximation.
- Causal masking is computed from global chunk offsets; fully-masked
  blocks still flow through the ring (uniform control flow — XLA needs
  every device to execute the same program) but contribute zero weight.

Communication pattern: n-1 ppermute hops of the K/V block, overlapping
with compute under XLA's async collectives.

flash x sp: with ``use_flash`` (auto on the TPU backend when shapes
tile), each block is computed by the pallas flash kernel
(ops/flash_attention.py, with_lse=True) and block results merge by
logsumexp — so the forward never materialises a score matrix even per
block, and causally-masked blocks skip their FLOPs entirely via
lax.cond.  Sliding windows compose with the flash ring by hop
classification in global coordinates (diagonal hop → the kernel's
banded grid; fully-in-band hops → plain kernel; the <=2 band-boundary
hops → XLA blocks with the exact global-offset mask merged into the
lse carry; band-out hops → skipped like future blocks).  The backward is a pallas ring too (`_ring_flash_backward`):
the dq/dkv kernels run per hop against the forward's GLOBAL lse, with
dk/dv accumulators riding the ring back to their owners — training
memory is O(S/n · block) end to end (TPU_OPERATOR_FLASH_BWD=0 falls
back to XLA recompute).  Verified block-exact against full attention,
forward and grads, in interpret mode — real multi-chip sp validation
awaits multi-chip hardware (this box has one chip).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.ops.attention import (
    dot_product_attention,
    repeat_kv_heads as _rep_kv,
    validate_window,
)

_NEG = float(jnp.finfo(jnp.float32).min)


def _ring_block(
    q: jax.Array,  # [B,H,Sq,D] local queries (f32 scores below)
    k: jax.Array,
    v: jax.Array,
    m: jax.Array,  # [B,H,Sq,1] running max
    l: jax.Array,  # [B,H,Sq,1] running normaliser
    o: jax.Array,  # [B,H,Sq,D] running (unnormalised) output, f32
    q_off: jax.Array,  # scalar: global offset of the local Q chunk
    k_off: jax.Array,  # scalar: global offset of the current K/V block
    causal: bool,
    window=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[-2])[:, None]
        kpos = k_off + jnp.arange(k.shape[-2])[None, :]
        visible = qpos >= kpos
        if window is not None:
            # global offsets make the sliding band exact across chunks
            visible = jnp.logical_and(visible, qpos - kpos < window)
        s = jnp.where(visible, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    # guard: a fully-masked row has m_new == _NEG; exp(_NEG - _NEG)=1
    # would pollute l, so clamp the shift for masked rows
    p = jnp.exp(s - m_new)
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1, keepdims=True)
    o = o * corr + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return m_new, l, o


def _band_hop_class(my, src, sq: int, window: int):
    """(in_band, fully_in) for a visiting past chunk at offset delta =
    (my - src)·sq.  Shared by the flash ring forward AND backward so
    the two can never disagree on the band predicates:

    - fully_in: every (q, k) pair of the hop satisfies qpos - kpos <
      window → plain non-causal kernel, no mask needed.
    - in_band and not fully_in: the band edge crosses this hop (at most
      2 such hops, deltas being multiples of sq) → XLA boundary block.
    - not in_band: every pair is behind the band → skip.
    """

    delta = (my - src) * sq
    in_band = jnp.logical_and(src < my, delta < window + sq - 1)
    fully_in = delta + sq - 1 < window
    return in_band, fully_in


def _global_band_mask(sq: int, sk: int, q_off, k_off, window):
    """[1,1,Sq,Sk] bool: causal ∧ sliding-band visibility in GLOBAL
    coordinates — the one mask both boundary-block functions use."""

    qpos = q_off + jnp.arange(sq)[:, None]
    kpos = k_off + jnp.arange(sk)[None, :]
    return jnp.logical_and(qpos >= kpos, qpos - kpos < window)[None, None]


def _window_block_fwd(q, k, v, q_off, k_off, window):
    """One off-diagonal block at the sliding band's boundary, masked in
    GLOBAL coordinates, returned in the flash merge domain
    (normalised out [B,H,Sq,D] f32, row lse [B,H,Sq,1] f32).  Only the
    <=2 hops the band edge crosses pay this XLA score matrix; every
    fully-in-band hop stays on the pallas kernel."""

    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    vis = _global_band_mask(q.shape[-2], k.shape[-2], q_off, k_off, window)
    s = jnp.where(vis, s, _NEG)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.where(s <= _NEG / 2, 0.0, jnp.exp(s - m))
    l = p.sum(axis=-1, keepdims=True)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ) / jnp.maximum(l, 1e-30)
    return o.astype(jnp.float32), lse


def _ring_attention_local_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    window=None,
    group: int = 1,
    with_residuals: bool = False,
):
    """Ring schedule with the pallas flash kernel computing each block.
    Returns the output array; with_residuals=True returns (out, lse)
    where lse is the global [B,H,Sq,1] f32 row logsumexp.

    flash x sp — the long-context composition: within a shard each
    K/V block is consumed by the flash forward (with_lse=True), and the
    normalised block results merge by logsumexp:

        lse' = logaddexp(lse, blk_lse)
        out' = out * e^(lse - lse') + blk_out * e^(blk_lse - lse')

    Causality by block position: the diagonal block (hop 0, own shard)
    runs the kernel's causal path; earlier-sequence blocks run full
    (non-causal) attention; later-sequence blocks are skipped entirely
    via lax.cond — unlike the XLA ring path, masked blocks cost no
    FLOPs here.

    window x flash (ADVICE r3): the sliding band composes by hop
    classification in global coordinates.  With chunk offset delta =
    (my - src)·Sq, a visiting past block is either fully inside the
    band (delta + Sq - 1 < window: plain non-causal flash kernel, no
    mask needed), fully behind it (delta >= window + Sq - 1: skipped
    like a future block), or one of the <=2 BOUNDARY hops the band edge
    crosses — those run `_window_block_fwd`, an XLA block with the
    exact global-offset mask, merged into the same lse carry.  The
    diagonal hop passes window straight to the kernel's banded grid.
    """

    from tf_operator_tpu.ops.flash_attention import _flash_forward

    my = lax.axis_index(axis_name)
    sq = q.shape[-2]
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    flash = functools.partial(
        _flash_forward,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        with_lse=True,
    )

    # hop 0: the local (diagonal) block — causal iff the caller is.
    # The kernel emits lse lane-broadcast [..., LANES]; one lane is the
    # truth, so the carry keeps [..., :1] (128x less state per hop)
    # flash kernels are GQA-native (index-mapped K/V heads) — hkv-width
    # blocks go straight in, no repeat anywhere
    out0, lse0 = flash(q, k, v, causal=causal, window=window)
    o = out0.astype(jnp.float32)
    lse = lse0[..., :1]

    def merge(o, lse, blk_out, blk_lse):
        new_lse = jnp.logaddexp(lse, blk_lse)
        w_old = jnp.exp(lse - new_lse)
        w_new = jnp.exp(blk_lse - new_lse)
        return o * w_old + blk_out.astype(jnp.float32) * w_new, new_lse

    def body(carry, i):
        k_blk, v_blk, o, lse = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        # after i+1 permutes this device holds the block that started
        # (my - (i+1)) shards back
        src = (my - (i + 1)) % axis_size

        def visible(operands):
            qq, kk, vv = operands
            bo, bl = flash(qq, kk, vv, causal=False)
            return bo.astype(jnp.float32), bl[..., :1]

        def boundary(operands):
            qq, kk, vv = operands
            return _window_block_fwd(
                qq, _rep_kv(kk, group), _rep_kv(vv, group),
                my * sq, src * sq, window,
            )

        def masked(operands):
            return (
                jnp.zeros(q.shape, jnp.float32),
                jnp.full(lse.shape, _NEG, jnp.float32),
            )

        if not causal:
            bo, bl = visible((q, k_blk, v_blk))
        elif window is None:
            bo, bl = lax.cond(src < my, visible, masked, (q, k_blk, v_blk))
        else:
            in_band, fully_in = _band_hop_class(my, src, sq, window)

            def banded_dispatch(operands):
                return lax.cond(fully_in, visible, boundary, operands)

            bo, bl = lax.cond(in_band, banded_dispatch, masked, (q, k_blk, v_blk))
        o, lse = merge(o, lse, bo, bl)
        return (k_blk, v_blk, o, lse), None

    (k, v, o, lse), _ = lax.scan(body, (k, v, o, lse), jnp.arange(axis_size - 1))
    if with_residuals:
        # lse here is the GLOBAL row logsumexp (merged over every hop),
        # [B,H,Sq,1] f32 — exactly what the backward kernels need
        return o.astype(q.dtype), lse
    return o.astype(q.dtype)


def _window_block_bwd(q, k_hkv, v_hkv, g, lse, delta_rows, q_off, k_off, window, group):
    """Gradients of one band-boundary block (global-offset mask), the
    XLA mirror of `_window_block_fwd`.  With the GLOBAL lse and
    delta = rowsum(dO·O), each block's contribution is independent:
    p = e^(s - lse); dv = pᵀg; ds = p(gVᵀ - delta); dq += ds·K;
    dk/dv fold back to Hkv width by group-sum (inverse of _rep_kv's
    consecutive repeat)."""

    b, h, sq, d = q.shape
    hkv = k_hkv.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    k_exp, v_exp = _rep_kv(k_hkv, group), _rep_kv(v_hkv, group)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_exp, preferred_element_type=jnp.float32) * scale
    vis = _global_band_mask(sq, k_exp.shape[-2], q_off, k_off, window)
    p = jnp.where(vis, jnp.exp(s - lse), 0.0)
    gf = g.astype(jnp.float32)
    dv_full = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum(
        "bhqd,bhkd->bhqk", gf, v_exp.astype(jnp.float32)
    )
    ds = p * (dp - delta_rows)
    dq = jnp.einsum(
        "bhqk,bhkd->bhqd", ds, k_exp.astype(jnp.float32)
    ) * scale
    dk_full = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale

    def fold(x):  # [B, H, Sk, D] -> [B, Hkv, Sk, D]
        if group == 1:
            return x
        return x.reshape(b, hkv, group, x.shape[-2], d).sum(axis=2)

    return dq, fold(dk_full), fold(dv_full)


def _ring_flash_backward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,  # [B,H,Sq,1] GLOBAL row logsumexp from the forward
    g: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    window=None,
    group: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Ring backward with the pallas flash backward kernels per block.

    The key identity: with the GLOBAL lse and delta = rowsum(dO·O), the
    backward contribution of each (q-chunk, kv-block) pair is
    independent — exactly what `_flash_backward_blocks` computes.  So
    the schedule mirrors the forward ring: K/V blocks rotate via
    ppermute, each hop runs the dq/dkv kernels for the visiting block
    (O(block) memory — no [S/n, S/n] score matrix ever exists), dq
    accumulates locally, and dk/dv accumulators TRAVEL with their block
    around the ring, arriving home after the final hop.  Causally
    masked hops (src > my) skip their kernels entirely via lax.cond.
    """

    from tf_operator_tpu.ops.flash_attention import _LANES, _flash_backward_blocks

    my = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    b, h, sq, d = q.shape
    lse_b = jnp.broadcast_to(lse, (b, h, sq, _LANES))
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )
    delta_b = jnp.broadcast_to(delta, (b, h, sq, _LANES))
    blocks = functools.partial(
        _flash_backward_blocks,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        # partials come out f32 so bf16 inputs aren't re-quantized per
        # hop — one rounding at the very end, like the single-chip path
        grad_dtype=jnp.float32,
    )

    # hop 0: the local (diagonal) block — causal iff the caller is.
    # GQA: the backward kernels are GQA-native (dk/dv come out at Hkv
    # width from the grouped kv-major grid), so the traveling
    # accumulators stay at Hkv width with no repeat or group-sum here
    dq, dk, dv = blocks(q, k, v, g, lse_b, delta_b, causal=causal, window=window)

    def body(carry, i):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        # the gradient accumulators rotate WITH their block
        k_blk, v_blk, dk_blk, dv_blk = (
            lax.ppermute(t, axis_name, perm) for t in (k_blk, v_blk, dk_blk, dv_blk)
        )
        src = (my - (i + 1)) % axis_size

        def visible(operands):
            kk, vv = operands
            return blocks(q, kk, vv, g, lse_b, delta_b, causal=False)

        def boundary(operands):
            kk, vv = operands
            return _window_block_bwd(
                q, kk, vv, g, lse, delta, my * sq, src * sq, window, group
            )

        def masked(operands):
            return (
                jnp.zeros(q.shape, jnp.float32),
                jnp.zeros(k.shape, jnp.float32),
                jnp.zeros(v.shape, jnp.float32),
            )

        if not causal:
            dqi, dki, dvi = visible((k_blk, v_blk))
        elif window is None:
            dqi, dki, dvi = lax.cond(src < my, visible, masked, (k_blk, v_blk))
        else:
            in_band, fully_in = _band_hop_class(my, src, sq, window)

            def banded_dispatch(operands):
                return lax.cond(fully_in, visible, boundary, operands)

            dqi, dki, dvi = lax.cond(in_band, banded_dispatch, masked, (k_blk, v_blk))
        dq = dq + dqi
        dk_blk = dk_blk + dki
        dv_blk = dv_blk + dvi
        return (k_blk, v_blk, dk_blk, dv_blk, dq), None

    (k_blk, v_blk, dk, dv, dq), _ = lax.scan(
        body, (k, v, dk, dv, dq), jnp.arange(axis_size - 1)
    )
    # after n-1 hops each accumulator sits one hop short of its owner —
    # one final forward hop brings dk/dv home
    dk = lax.ppermute(dk, axis_name, perm)
    dv = lax.ppermute(dv, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _make_flash_ring_local(
    axis_name: str,
    axis_size: int,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    group: int = 1,
    window=None,
):
    """The flash-ring local fn with a training-complete VJP.

    Forward: flash kernels per block (no [Sq,Sk] matrix, masked blocks
    skipped), saving (out, global lse) as residuals.  Backward: the
    pallas ring backward (`_ring_flash_backward`) — flash dq/dkv
    kernels per hop with gradient accumulators riding the ring, so
    training memory is O(S/n · block) end to end.  TPU_OPERATOR_FLASH_BWD=0
    falls back to recomputing the gradient through the XLA ring graph
    (same exact-attention math, materialises per-block score matrices)
    — the same escape hatch the single-chip kernel honours.
    """

    from tf_operator_tpu.ops.flash_attention import _use_pallas_bwd

    flash_impl = functools.partial(
        _ring_attention_local_flash,
        axis_name=axis_name,
        axis_size=axis_size,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        window=window,
        group=group,
    )
    xla_impl = functools.partial(
        _ring_attention_local,
        axis_name=axis_name,
        axis_size=axis_size,
        causal=causal,
        group=group,
        window=window,
    )
    pallas_bwd = _use_pallas_bwd()

    @jax.custom_vjp
    def f(q, k, v):
        return flash_impl(q, k, v)

    def fwd(q, k, v):
        if pallas_bwd:
            out, lse = flash_impl(q, k, v, with_residuals=True)
            return out, (q, k, v, out, lse)
        return flash_impl(q, k, v), (q, k, v)

    def bwd(residuals, g):
        if pallas_bwd:
            q, k, v, out, lse = residuals
            return _ring_flash_backward(
                q, k, v, out, lse, g,
                axis_name=axis_name,
                axis_size=axis_size,
                causal=causal,
                block_q=block_q,
                block_k=block_k,
                interpret=interpret,
                window=window,
                group=group,
            )
        q, k, v = residuals
        _, vjp = jax.vjp(xla_impl, q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    group: int = 1,
    window=None,
) -> jax.Array:
    """Runs inside shard_map: q is the local [B,H,Sq,D] shard; k/v are
    [B,H/group,Sq,D] (GQA) and expand per block compute.  Gradients of
    the repeat (autodiff through the scan) are the group-sum.  With a
    sliding window the per-block mask uses global offsets, so the band
    is exact across chunk boundaries.

    Hop skipping (causal): a visiting chunk that is entirely in the
    future — or, with a window, entirely behind the band — contributes
    zero weight; `lax.cond` skips its matmuls outright while the block
    still rides the ring (the ppermute stays outside the cond, so
    every device keeps the same collective schedule)."""

    my = lax.axis_index(axis_name)
    sq = q.shape[-2]
    qf = q  # keep native dtype for the MXU; scores accumulate f32
    # carries derived from q so they inherit its varying manual axes
    # (shard_map VMA checking rejects unvarying scan carries)
    m0 = jnp.full_like(q[..., :1], _NEG, dtype=jnp.float32)
    l0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32)
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    q_off = my * sq
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def chunk_visible(src):
        vis = src <= my
        if window is not None:
            # chunks more than ceil((window-1)/sq) behind hold only
            # keys with qpos - kpos >= window for every local q row
            vis = jnp.logical_and(vis, (my - src - 1) * sq <= window - 2)
        return vis

    def hop(k_blk, v_blk, m, l, o, src):
        def visible(args):
            m, l, o = args
            return _ring_block(
                qf, _rep_kv(k_blk, group), _rep_kv(v_blk, group), m, l, o,
                q_off, src * sq, causal, window,
            )

        if not causal:  # every chunk visible: no conditional staged
            return visible((m, l, o))
        return lax.cond(chunk_visible(src), visible, lambda args: args, (m, l, o))

    def body(carry, i):
        k_blk, v_blk, m, l, o = carry
        # after i hops we hold the block that started (my - i) shards back
        src = (my - i) % axis_size
        m, l, o = hop(k_blk, v_blk, m, l, o, src)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, o), None

    # n-1 hops inside the scan; the last block needs no onward permute
    (k_blk, v_blk, m, l, o), _ = lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(axis_size - 1)
    )
    last_src = (my - (axis_size - 1)) % axis_size
    m, l, o = hop(k_blk, v_blk, m, l, o, last_src)
    # causal rows always attend to at least themselves, so l > 0; the
    # maximum guards the (non-causal, all-masked) degenerate case
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def _flash_ring_applicable(
    q: jax.Array, axis_size: int, block_q: int, block_k: int
) -> bool:
    """Per-shard shapes must tile the flash kernel's blocks."""

    s, d = q.shape[-2], q.shape[-1]
    if s % axis_size:
        return False
    local = s // axis_size
    return local % block_q == 0 and local % block_k == 0 and d % 8 == 0


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis_name: str = "sp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    heads_axis: Optional[str] = "tp",
    use_flash: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Exact attention with sequence sharded over `axis_name`.

    q: GLOBAL [B, H, S, D]; k/v: [B, H, S, D] or [B, Hkv, S, D] with
    H % Hkv == 0 (GQA — K/V travel the ring at Hkv width and expand
    only inside each block compute, so ICI traffic and KV residency
    keep the h/hkv saving).  jit-traced values are fine — shard_map
    re-shards per the specs.  When the sp axis is 1 this degrades to
    plain fused attention with identical semantics.

    ``use_flash``: compute each ring block with the pallas flash kernel
    (flash x sp).  None = auto: on the TPU backend when the per-shard
    shapes tile the kernel blocks (TPU_OPERATOR_FLASH=0 disables).
    """

    h, hkv = q.shape[1], k.shape[1]
    if h % hkv:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads ({hkv})")
    group = h // hkv
    validate_window(window, causal)

    if mesh.shape[axis_name] <= 1:
        return dot_product_attention(q, k, v, causal=causal, window=window)

    n = mesh.shape[axis_name]
    if group > 1 and heads_axis and hkv % mesh.shape.get(heads_axis, 1):
        # kv heads don't divide the tp axis: fall back to full width
        k, v = _rep_kv(k, group), _rep_kv(v, group)
        group = 1

    from tf_operator_tpu.ops.flash_attention import (
        resolve_flash_blocks,
        resolve_use_flash,
    )

    if q.shape[-2] % n:
        # a non-divisible sequence has NO per-shard length to size
        # blocks against (ADVICE r5 #3: resolving against the global S
        # here produced blocks for a length no shard ever sees) —
        # short-circuit use_flash instead of consulting the kernel
        if use_flash:
            raise ValueError(
                f"use_flash=True but seq {q.shape[-2]} does not divide "
                f"over {n} '{axis_name}' shards — flash ring needs a "
                f"whole per-shard sequence to tile"
            )
        use_flash = False
    else:
        # blocks size against the PER-SHARD sequence (each ring hop's
        # kernel call sees S/n); unpinned dims take the tuned defaults,
        # shrunk until they tile the shard
        local_s = q.shape[-2] // n
        block_q, block_k = resolve_flash_blocks(
            block_q, block_k, local_s, local_s, head_dim=q.shape[-1]
        )
        use_flash = resolve_use_flash(
            use_flash,
            _flash_ring_applicable(q, n, block_q, block_k),
            f"use_flash=True but per-shard shapes don't tile the kernel: "
            f"seq {q.shape[-2]} over {n} shards with blocks "
            f"({block_q},{block_k})",
        )

    spec = P(batch_axes, heads_axis, axis_name, None)
    if use_flash:
        # window x flash composes by hop classification (ADVICE r3):
        # the diagonal hop uses the kernel's banded grid, fully-in-band
        # hops the plain kernel, band-out hops are skipped, and the
        # <=2 boundary hops run an XLA block with the global-offset
        # mask merged into the lse carry
        local = _make_flash_ring_local(
            axis_name, n, causal, block_q, block_k, interpret,
            group=group, window=window,
        )
    else:
        local = functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            axis_size=n,
            causal=causal,
            group=group,
            window=window,
        )
    from tf_operator_tpu.utils.jax_compat import shard_map_unchecked

    return shard_map_unchecked(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
