"""Ring attention: exact attention over a sequence sharded on the `sp`
mesh axis.

Long-context is first-class in this framework (the reference has no
sequence-length story at all — SURVEY.md §2b calls it absent).  Design
is the standard ring schedule (Liu et al.-style, re-derived here):

- Each sp shard holds Q/K/V for its contiguous sequence chunk.
- K/V blocks rotate around the ring via `lax.ppermute` (neighbour
  ICI hops only — no all-gather, so KV memory stays O(S/n) per chip).
- Each hop combines the local block with a *streaming softmax*
  (flash-attention-style running max / normaliser in float32), so the
  result is exact attention, not an approximation.
- Causal masking is computed from global chunk offsets; fully-masked
  blocks still flow through the ring (uniform control flow — XLA needs
  every device to execute the same program) but contribute zero weight.

Communication pattern: n-1 ppermute hops of the K/V block, overlapping
with compute under XLA's async collectives.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.ops.attention import dot_product_attention

_NEG = float(jnp.finfo(jnp.float32).min)


def _ring_block(
    q: jax.Array,  # [B,H,Sq,D] local queries (f32 scores below)
    k: jax.Array,
    v: jax.Array,
    m: jax.Array,  # [B,H,Sq,1] running max
    l: jax.Array,  # [B,H,Sq,1] running normaliser
    o: jax.Array,  # [B,H,Sq,D] running (unnormalised) output, f32
    q_off: jax.Array,  # scalar: global offset of the local Q chunk
    k_off: jax.Array,  # scalar: global offset of the current K/V block
    causal: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[-2])[:, None]
        kpos = k_off + jnp.arange(k.shape[-2])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    # guard: a fully-masked row has m_new == _NEG; exp(_NEG - _NEG)=1
    # would pollute l, so clamp the shift for masked rows
    p = jnp.exp(s - m_new)
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1, keepdims=True)
    o = o * corr + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return m_new, l, o


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
) -> jax.Array:
    """Runs inside shard_map: q,k,v are the local [B,H,Sq,D] shards."""

    my = lax.axis_index(axis_name)
    sq = q.shape[-2]
    qf = q  # keep native dtype for the MXU; scores accumulate f32
    # carries derived from q so they inherit its varying manual axes
    # (shard_map VMA checking rejects unvarying scan carries)
    m0 = jnp.full_like(q[..., :1], _NEG, dtype=jnp.float32)
    l0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32)
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    q_off = my * sq
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(carry, i):
        k_blk, v_blk, m, l, o = carry
        # after i hops we hold the block that started (my - i) shards back
        src = (my - i) % axis_size
        m, l, o = _ring_block(qf, k_blk, v_blk, m, l, o, q_off, src * sq, causal)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, o), None

    # n-1 hops inside the scan; the last block needs no onward permute
    (k_blk, v_blk, m, l, o), _ = lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(axis_size - 1)
    )
    last_src = (my - (axis_size - 1)) % axis_size
    m, l, o = _ring_block(qf, k_blk, v_blk, m, l, o, q_off, last_src * sq, causal)
    # causal rows always attend to at least themselves, so l > 0; the
    # maximum guards the (non-causal, all-masked) degenerate case
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis_name: str = "sp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    heads_axis: Optional[str] = "tp",
) -> jax.Array:
    """Exact attention with sequence sharded over `axis_name`.

    q,k,v: GLOBAL [B, H, S, D] arrays (jit-traced values are fine —
    shard_map re-shards per the specs).  When the sp axis is 1 this
    degrades to plain fused attention with identical semantics.
    """

    if mesh.shape[axis_name] <= 1:
        return dot_product_attention(q, k, v, causal=causal)

    spec = P(batch_axes, heads_axis, axis_name, None)
    local = functools.partial(
        _ring_attention_local,
        axis_name=axis_name,
        axis_size=mesh.shape[axis_name],
        causal=causal,
    )
    from tf_operator_tpu.utils.jax_compat import shard_map_unchecked

    return shard_map_unchecked(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
