"""TPU-native ops: distributed attention and (later) pallas kernels.

The reference contains no kernels (it is a control plane; SURVEY.md §0)
— this package is where the rebuild's first-class long-context and
distributed compute path lives (ring attention over the sp mesh axis,
fused attention for single-chip hot paths).
"""

from tf_operator_tpu.ops.attention import dot_product_attention
from tf_operator_tpu.ops.ring_attention import ring_attention

__all__ = ["dot_product_attention", "ring_attention"]
