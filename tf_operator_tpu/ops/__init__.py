"""TPU-native ops: distributed attention and pallas kernels.

The reference contains no kernels (it is a control plane; SURVEY.md §0)
— this package is where the rebuild's first-class long-context and
distributed compute path lives: exact ring attention over the sp mesh
axis, a pallas flash-attention kernel for the single-chip hot path, and
the XLA-fused reference both fall back to.
"""

from tf_operator_tpu.ops.attention import dot_product_attention
from tf_operator_tpu.ops.flash_attention import attention, flash_attention
from tf_operator_tpu.ops.fused_batchnorm import fused_batchnorm, fusedbn_available
from tf_operator_tpu.ops.paged_attention import paged_attention
from tf_operator_tpu.ops.quant import materialize_tree, quantize_tree
from tf_operator_tpu.ops.ring_attention import ring_attention
from tf_operator_tpu.ops.ulysses_attention import ulysses_attention

__all__ = [
    "attention",
    "dot_product_attention",
    "flash_attention",
    "fused_batchnorm",
    "fusedbn_available",
    "materialize_tree",
    "paged_attention",
    "quantize_tree",
    "ring_attention",
    "ulysses_attention",
]
