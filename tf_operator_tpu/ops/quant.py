"""Weights-only int8 quantization for the serving/decode path.

Decode at small batch is weight-bandwidth-bound: every generated token
re-reads each projection matrix from HBM while the MXU idles
(arithmetic intensity ~ batch rows).  Halving the bytes per weight
therefore nearly halves the per-token HBM time, which dominates the
step.  The scheme here:

- **Quantize OUTSIDE jit** (`quantize_tree`): selected param leaves
  become :class:`QTensor` — int8 values + a per-output-channel
  symmetric scale (max-abs / 127, reduced over all axes but the last).
  QTensor is a registered pytree node, so the quantized tree passes
  through ``jax.jit`` argument plumbing unchanged.
- **Consume int8 DIRECTLY at the matmul** (`ops/quant_matmul` via
  `QDenseGeneral`): QDense-stack families take the quantized tree
  straight into `apply`; each projection computes the output-scale
  form `(x @ q.astype(bf16)) · s` as one dot inside XLA's fusions, so
  the weight crosses HBM as int8 and no bf16 copy is written back.
  Measured on v5e: llama-wide (~700M) decode 1.63× bf16 at batch 1
  (PROFILE.md "int8 decode").
- **materialize_tree** remains for apply sites that need plain arrays
  (MoE expert einsums).  NOTE (measured, r5): materializing *per decode
  step* is an anti-pattern — XLA does not fuse the convert into the
  dot's operand read inside the scan, and the materialized form ran
  0.55× bf16 on v5e.

Training stays bf16; this is a serving-side transform applied after
`load_params` (see ``examples/serve_lm.py --quantize int8``).  The
decode loops pass the quantized tree straight to ``apply`` —
`QDenseGeneral`/`Embed` handle both plain and QTensor leaves, so
quantized and plain trees share one code path with no materialization
in between.

The reference (SURVEY.md §0) has no quantized-serving story — this is
a beyond-reference capability.  On-chip numbers: ``bench.py``'s llama
child measures decode tokens/s bf16 vs int8 (``llama_decode_tokens_
per_sec`` / ``llama_decode_int8_tokens_per_sec``; gate off with
``BENCH_QUANT=0``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

# leaves smaller than this stay bf16: scales + a second HBM round trip
# buy nothing on tiny tensors, and biases/norms are accuracy-critical
DEFAULT_MIN_SIZE = 4096


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """int8 values + broadcastable per-channel scale."""

    q: jax.Array  # int8, original shape
    scale: jax.Array  # float32, shape (1, ..., 1, out_features)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.scale.nbytes

    def materialize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _is_q(leaf: Any) -> bool:
    return isinstance(leaf, QTensor)


def quantize_array(w: jax.Array, reduce_axes=None) -> QTensor:
    """Symmetric int8.  The scale must be constant along the CONTRACTED
    axes of the consuming dot; by default all axes but the last are
    reduced (safe for DenseGeneral kernels, whose leading axes are the
    input side).  Callers with batch-like leading axes (MoE expert
    stacks) pass the true contraction axes to keep per-expert scales.
    """

    if reduce_axes is None:
        reduce_axes = tuple(range(w.ndim - 1))
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def quantize_tree(
    params,
    *,
    min_size: int = DEFAULT_MIN_SIZE,
    quantize_embed: bool = False,
):
    """Quantize the projection kernels of a params pytree.

    A leaf is quantized when its path ends in ``kernel``, it has >= 2
    dims, and it holds at least ``min_size`` elements.  The embedding
    table (which doubles as the logits head via ``Embed.attend``) is
    accuracy-critical and stays bf16 unless ``quantize_embed=True``.
    """

    def f(path, leaf):
        # params may be boxed (flax Partitioned / axis metadata), so the
        # path can end in attribute keys like `.value` — the param NAME
        # is the last dict key on the path
        name = ""
        for entry in reversed(path):
            k = getattr(entry, "key", None)
            if isinstance(k, str):
                name = k
                break
        eligible = name == "kernel" or (quantize_embed and name == "embedding")
        # MoE expert stacks (models/moe.py): [expert, in, out] with the
        # expert axis batch-like — contract only `in` so each expert
        # keeps its own scales
        moe_expert = name in ("wi", "wo") and getattr(leaf, "ndim", 0) == 3
        if moe_expert and leaf.size >= min_size:
            return quantize_array(leaf, reduce_axes=(1,))
        if eligible and hasattr(leaf, "ndim") and leaf.ndim >= 2 and leaf.size >= min_size:
            return quantize_array(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(f, params)


def materialize_tree(params, dtype=jnp.bfloat16):
    """Dequantize QTensor leaves (bf16), pass everything else through.

    Call this INSIDE jit, immediately before ``model.apply`` — that is
    what lets XLA fuse the convert into the consuming dot.  On a tree
    with no QTensor leaves this is an identity tree_map.
    """

    return jax.tree_util.tree_map(
        lambda l: l.materialize(dtype) if _is_q(l) else l, params, is_leaf=_is_q
    )


def materialize_fn(*models):
    """The ONE apply-site policy for quantized trees: identity when
    EVERY given model's dense stack consumes QTensor leaves natively
    (``SUPPORTS_QTENSOR`` — QDenseGeneral/Embed route
    ``ops/quant_matmul``, the weight crosses HBM as int8), else
    :func:`materialize_tree`.  Shared by decode.generate,
    ChunkedServingDecoder, ContinuousBatchingDecoder, and
    SpeculativeDecoder so the selection can't drift between them.
    NOTE (measured, r5): materializing per decode step is the 0.55×
    anti-pattern — this helper exists so only non-QDense families
    (MoE expert einsums) ever pay it."""

    if all(
        getattr(type(m), "SUPPORTS_QTENSOR", False) for m in models
    ):
        return lambda t: t
    return materialize_tree


def is_quantized(params) -> bool:
    return any(
        _is_q(l) for l in jax.tree_util.tree_leaves(params, is_leaf=_is_q)
    )


def tree_bytes(params) -> int:
    return sum(
        l.nbytes for l in jax.tree_util.tree_leaves(params, is_leaf=_is_q)
    )
