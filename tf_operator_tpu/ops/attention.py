"""Plain (single-device / XLA-fused) scaled dot-product attention.

Reference semantics for ring_attention and the fallback path when the
mesh's sp axis is 1.  float32 softmax accumulation regardless of input
dtype (bf16-safe), additive-mask + causal support, no data-dependent
shapes — XLA fuses this whole block into the surrounding matmuls.

Layout contract (all attention in this framework): [batch, heads, seq,
head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def validate_window(window, causal: bool) -> None:
    """Shared precondition for every attention entry point that takes
    ``window`` (reference, flash, ring, ulysses)."""

    if window is None:
        return
    if not causal:
        raise ValueError("window attention requires causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    bias: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """q,k,v: [B, H, S, D] (k/v seq may differ for cross-attention;
    k/v heads may be H/group for GQA — handled by a grouped einsum, no
    materialised repeat).

    `bias`: broadcastable to [B, H, Sq, Sk], added to logits (T5 relative
    position bias).  `mask`: broadcastable boolean, True = attend.
    `window`: sliding-window (mistral-style) local attention — position
    i attends to [i - window + 1, i]; requires causal=True.
    """

    validate_window(window, causal)

    b, h, sq, d = q.shape
    hkv = k.shape[1]
    if h != hkv:
        if h % hkv:
            raise ValueError(f"q heads ({h}) must be a multiple of kv heads ({hkv})")
        group = h // hkv
        qg = q.reshape(b, hkv, group, sq, d)
        logits = jnp.einsum(
            "bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32
        ).reshape(b, h, sq, k.shape[-2])
    else:
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        )
    sk = k.shape[-2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = logits * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, neg)
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        visible = qpos >= kpos
        if window is not None:
            visible &= qpos - kpos < window
        logits = jnp.where(visible, logits, neg)
    weights = jax.nn.softmax(logits, axis=-1)
    if h != hkv:
        wg = weights.reshape(b, hkv, h // hkv, sq, sk)
        out = jnp.einsum(
            "bhgqk,bhkd->bhgqd", wg.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        ).reshape(b, h, sq, d)
        return out.astype(v.dtype)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", weights.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(v.dtype)


def repeat_kv_heads(a: jax.Array, group: int) -> jax.Array:
    """GQA: expand [B,Hkv,S,D] K/V to the full query-head width.  Only
    the fallback paths use this (kv heads not divisible by the tp axis;
    ulysses when kv heads don't split the sp axis) — the attention
    impls themselves are GQA-native and consume Hkv width directly."""

    return a if group == 1 else jnp.repeat(a, group, axis=1)


