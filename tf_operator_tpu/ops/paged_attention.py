"""Pallas paged-attention decode — single-query attention straight off
the block arena (ISSUE 10 tentpole).

The paged pool (models/batching.PagedContinuousBatchingDecoder) keeps
every seat's KV in fixed-size token blocks over one pre-allocated
arena, addressed by per-seat block tables.  PR 8's decode step
EMULATED that layout: gather the seat's blocks into a contiguous
[1, Hkv, max_len, D] view, run the unchanged attention math, scatter
the written window back.  Correct — and roughly double the KV traffic
of what decode actually needs, on the phase that is memory-bandwidth
bound (BASELINE.md int8/wide decode rows).  This kernel removes the
round trip: each grid program walks ONE seat's block table (scalar-
prefetched, so the table drives the DMA index map), streams that
seat's K/V blocks HBM→VMEM tile by tile, and runs an online-softmax
accumulation against the seat's single query.  No contiguous view
ever exists; the arena is read exactly once.

Layout contract:

- ``q``        [S, H, D]        one query per seat (decode s_new == 1)
- ``k_arena``  [NB, Hkv, bs, D] the per-layer arena leaf
- ``v_arena``  [NB, Hkv, bs, D]
- ``tables``   [S, MB] int32    logical block -> physical arena block
- ``lengths``  [S] int32        valid positions per seat INCLUDING the
                                just-appended token (attend to
                                positions 0 .. lengths[s]-1)
- returns      [S, H, D] in v_arena.dtype

Multi-query verify (ISSUE 18 — speculative decoding): the same grid
also serves K queries per seat in ONE dispatch via
``paged_attention_multi``:

- ``q``        [S, K, H, D]     the K draft tokens' queries, oldest
                                first
- ``lengths``  [S] int32        INCLUDING all K just-appended tokens
                                (so query row t sits at absolute
                                position lengths[s]-K+t and attends to
                                positions 0 .. lengths[s]-K+t — the
                                causal band falls out of the length
                                convention, no second mask input)
- returns      [S, K, H, D]

With K == 1 the band collapses to the single-query rule exactly; the
single-query entry point is the K == 1 slice of the same code path, so
PR 10's bit-identity pins carry over unchanged.

Masking rules (the kernel contract, docs/ARCHITECTURE.md):

- per-seat length mask: position p contributes to query row t iff
  p < lengths[s] - (K-1-t)  (K == 1: p < lengths[s]);
- scratch-block-0: unused table entries point at the scratch block —
  they sit at logical positions >= lengths[s], so the length mask IS
  the scratch mask (one rule, not two) — and speculative rollback
  relies on exactly this: rejected appends stay in the arena but sit
  past the rewound length, so they are unobservable garbage, identical
  in status to scratch;
- tiles fully past the length skip their compute via @pl.when (their
  DMA still lands — the table clamps them to scratch/reserved blocks,
  never to another seat's live data).

Tile size: ``resolve_flash_blocks`` (ops/flash_attention.py — the
head-dim-capped VMEM-ceiling resolver) picks the kv tile class; the
tile is then shrunk until it divides ``block_size`` so every grid step
reads within one arena block (``_resolve_paged_tile``).  Grid:
(seats, kv_heads, MB, block_size/tile), scalar-prefetched tables in
the K/V index maps, fp32 online-softmax carry in VMEM scratch
persisting across the two innermost (sequential) dims — the classic
flash layout, re-gridded for paged decode.

Impls (the ``impl`` arg — callers resolve "auto" themselves so an
explicit request can FAIL instead of silently downgrading):

- ``"xla"``              gather the table's blocks and run
                         ops.attention.dot_product_attention — BIT-
                         IDENTICAL to the contiguous pool's decode
                         math (same einsum, same mask shape), the
                         reference the kernel is property-tested
                         against and the CPU fallback;
- ``"pallas"``           the TPU kernel;
- ``"pallas-interpret"`` the same kernel in interpreter mode — how the
                         CI (JAX_PLATFORMS=cpu) exercises the real
                         kernel path end to end.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tf_operator_tpu.ops.attention import dot_product_attention
from tf_operator_tpu.ops.flash_attention import resolve_flash_blocks

_NEG_INF = float(jnp.finfo(jnp.float32).min)
#: lane width — the online-softmax carries pad to full lanes, exactly
#: like the flash kernel's scratch
_LANES = 128

PAGED_IMPLS = ("xla", "pallas", "pallas-interpret")


def _resolve_paged_tile(block_size: int, head_dim: int) -> int:
    """KV positions per grid step: the resolve_flash_blocks block_k
    class (head-dim capped at the measured VMEM ceiling), shrunk until
    it divides ``block_size`` so a tile never straddles two arena
    blocks (arena blocks are only contiguous within themselves)."""

    _, bk = resolve_flash_blocks(
        None, None, 1, block_size, head_dim=head_dim
    )
    tile = min(int(block_size), int(bk))
    while tile > 1 and block_size % tile:
        tile //= 2
    return max(1, tile)


def paged_kernel_available(
    head_dim: int, block_size: int, *, interpret: bool = False
) -> Tuple[bool, str]:
    """(ok, why_not) — can the Pallas kernel serve this config HERE?

    The honesty contract (ISSUE 10): ``--paged-kernel on`` callers must
    FAIL on (False, why) rather than silently run the gather emulation.
    ``interpret=True`` waives the backend requirement (the interpreter
    runs the real kernel anywhere — the CI path)."""

    if head_dim < 1 or block_size < 1:
        return False, f"degenerate shape (head_dim={head_dim}, block_size={block_size})"
    if not interpret and jax.default_backend() != "tpu":
        return (
            False,
            "the paged-attention kernel needs the TPU backend (got "
            f"{jax.default_backend()!r}); the gather emulation serves "
            "CPU, or pass paged_kernel='interpret' for kernel-path tests",
        )
    return True, ""


def _paged_attention_xla(q, k_arena, v_arena, tables, lengths):
    """Reference: gather the table's blocks into the contiguous view
    and run the one true attention math (ops.attention).  Bit-identical
    to the contiguous pool's decode branch — masked positions zero out
    exactly, so scratch/unwritten content is unobservable."""

    s, mb = tables.shape
    nb, hkv, bs, d = k_arena.shape

    def view(a):
        g = jnp.take(a, tables, axis=0)  # [S, MB, Hkv, bs, D]
        g = jnp.transpose(g, (0, 2, 1, 3, 4))
        return g.reshape(s, hkv, mb * bs, d)

    mask = (jnp.arange(mb * bs)[None, :] < lengths[:, None])[
        :, None, None, :
    ]  # [S, 1, 1, MB*bs]
    out = dot_product_attention(
        q[:, :, None, :], view(k_arena), view(v_arena), mask=mask
    )
    return out[:, :, 0, :]


def _paged_attention_multi_xla(q, k_arena, v_arena, tables, lengths):
    """Multi-query reference: the same gathered view, with the causal
    band mask derived from the length convention (module docstring) —
    query row t of seat s sees position p iff p < lengths[s]-(K-1-t)."""

    s, k_new, h, d = q.shape
    nb, hkv, bs, _ = k_arena.shape
    mb = tables.shape[1]

    def view(a):
        g = jnp.take(a, tables, axis=0)  # [S, MB, Hkv, bs, D]
        g = jnp.transpose(g, (0, 2, 1, 3, 4))
        return g.reshape(s, hkv, mb * bs, d)

    # qend[s, t] = lengths[s] - (K-1-t): one more visible position per
    # later query row — the in-window causal band
    qend = lengths[:, None] - (
        k_new - 1 - jnp.arange(k_new, dtype=lengths.dtype)
    )[None, :]  # [S, K]
    mask = (
        jnp.arange(mb * bs)[None, None, :] < qend[:, :, None]
    )[:, None, :, :]  # [S, 1, K, MB*bs]
    out = dot_product_attention(
        jnp.transpose(q, (0, 2, 1, 3)), view(k_arena), view(v_arena),
        mask=mask,
    )  # [S, H, K, D]
    return jnp.transpose(out, (0, 2, 1, 3))


def _paged_attn_kernel(
    tables_ref,  # scalar-prefetch [S, MB]
    lengths_ref,  # scalar-prefetch [S]
    q_ref,  # [1, G, D]   (G = K*group: query rows ordered (K, group))
    k_ref,  # [1, 1, tile, D]
    v_ref,
    o_ref,  # [1, G, D]
    m_ref,  # VMEM [G, LANES] fp32
    l_ref,
    acc_ref,  # VMEM [G, D] fp32
    *,
    block_size: int,
    tile: int,
    scale: float,
    k_new: int,
    group: int,
):
    s = pl.program_id(0)
    j = pl.program_id(2)
    c = pl.program_id(3)
    nj = pl.num_programs(2)
    nc = pl.num_programs(3)
    length = lengths_ref[s]
    base = j * block_size + c * tile

    @pl.when((j == 0) & (c == 0))
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # tiles fully past the seat's length contribute nothing: skip the
    # compute (their DMA lands in scratch/reserved blocks — the table
    # guarantees no other seat's live data is ever addressed)
    @pl.when(base < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [G, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [tile, D]
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, tile]
        # per-seat length mask == scratch mask (module docstring);
        # multi-query (k_new > 1): query row r belongs to draft token
        # t = r // group and sees one fewer trailing position per
        # earlier t — the causal band.  k_new == 1 collapses qend to
        # `length` exactly, so the single-query math is the K == 1
        # slice of this code, not a separate path.
        kpos = base + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        row = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
        qend = length - (k_new - 1 - row // group)
        logits = jnp.where(kpos < qend, logits, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(logits, -1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, -1, keepdims=True), l_ref.shape
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when((j == nj - 1) & (c == nc - 1))
    def _finalize():
        # a fully-masked seat divides safely (cannot happen live: the
        # new token was appended before the call, so length >= 1)
        l = jnp.maximum(l_ref[:, :1], 1e-37)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _paged_attention_multi_pallas(
    q, k_arena, v_arena, tables, lengths, *, interpret: bool
):
    """The kernel path for q [S, K, H, D].  K query rows ride the same
    grid as PR 10's single-query kernel: per (seat, kv-head) the block
    carries G = K*group rows (ordered K-major within the head group) so
    the whole verify window is ONE dispatch — the online-softmax
    carries just grow G rows tall.  K == 1 reproduces the single-query
    kernel bit for bit (same grid, same block shapes, same mask)."""

    s, k_new, h, d = q.shape
    nb, hkv, bs, _ = k_arena.shape
    mb = tables.shape[1]
    if h % hkv:
        raise ValueError(
            f"q heads ({h}) must be a multiple of kv heads ({hkv})"
        )
    group = h // hkv
    g = k_new * group
    # rows ordered (hkv, K, group): each kv head's G rows are
    # contiguous, so one BlockSpec slice feeds the whole head group
    qr = jnp.transpose(
        q.reshape(s, k_new, hkv, group, d), (0, 2, 1, 3, 4)
    ).reshape(s, hkv * g, d)
    tile = _resolve_paged_tile(bs, d)
    scale = 1.0 / (d**0.5)
    kernel = functools.partial(
        _paged_attn_kernel, block_size=bs, tile=tile, scale=scale,
        k_new=k_new, group=group,
    )

    def kv_idx(si, hi, j, c, tables_ref, lengths_ref):
        # the scalar-prefetched block table IS the DMA schedule: grid
        # step (seat, head, logical block j, chunk c) streams physical
        # block tables[seat, j] — never a contiguous view
        return (tables_ref[si, j], hi, c, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, hkv, mb, bs // tile),
        in_specs=[
            pl.BlockSpec(
                (1, g, d), lambda si, hi, j, c, t, L: (si, hi, 0)
            ),
            pl.BlockSpec((1, 1, tile, d), kv_idx),
            pl.BlockSpec((1, 1, tile, d), kv_idx),
        ],
        out_specs=pl.BlockSpec(
            (1, g, d), lambda si, hi, j, c, t, L: (si, hi, 0)
        ),
        scratch_shapes=[
            # carries persist across the two innermost (sequential)
            # grid dims — the flash-kernel pattern
            pltpu.VMEM((g, _LANES), jnp.float32),
            pltpu.VMEM((g, _LANES), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "arbitrary", "arbitrary",
            )
        )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((s, hkv * g, d), v_arena.dtype),
        grid_spec=grid_spec,
        compiler_params=compiler_params,
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), qr,
      k_arena, v_arena)
    return jnp.transpose(
        out.reshape(s, hkv, k_new, group, d), (0, 2, 1, 3, 4)
    ).reshape(s, k_new, h, d)


def _paged_attention_pallas(
    q, k_arena, v_arena, tables, lengths, *, interpret: bool
):
    # single-query == the K = 1 slice of the multi-query kernel (the
    # reshapes are no-ops at K = 1, so PR 10 bit-identity is preserved)
    return _paged_attention_multi_pallas(
        q[:, None], k_arena, v_arena, tables, lengths,
        interpret=interpret,
    )[:, 0]


def paged_attention(
    q: jax.Array,
    k_arena: jax.Array,
    v_arena: jax.Array,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    impl: str = "xla",
) -> jax.Array:
    """Single-query attention against the block arena (module
    docstring for the layout/masking contract).  ``impl`` is explicit
    by design — "auto" lives in the CALLER (the pool / serve_lm flag)
    where refusing to downgrade is possible; this function just runs
    what it is told."""

    if impl not in PAGED_IMPLS:
        raise ValueError(
            f"impl must be one of {PAGED_IMPLS}, got {impl!r}"
        )
    if q.ndim != 3 or k_arena.ndim != 4 or tables.ndim != 2:
        raise ValueError(
            f"paged_attention layout: q [S,H,D], arena [NB,Hkv,bs,D], "
            f"tables [S,MB]; got q{q.shape}, k{k_arena.shape}, "
            f"tables{tables.shape}"
        )
    if impl == "xla":
        return _paged_attention_xla(q, k_arena, v_arena, tables, lengths)
    return _paged_attention_pallas(
        q, k_arena, v_arena, tables, lengths,
        interpret=(impl == "pallas-interpret"),
    )


def paged_attention_multi(
    q: jax.Array,
    k_arena: jax.Array,
    v_arena: jax.Array,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    impl: str = "xla",
) -> jax.Array:
    """K-query-per-seat attention against the block arena — the
    speculative VERIFY primitive (ISSUE 18).  ``q`` is [S, K, H, D]
    (K draft tokens, oldest first), ``lengths`` INCLUDES all K
    appended tokens, and the in-window causal band is derived from
    that convention (module docstring) — no extra mask input.  One
    dispatch scores the whole window; ``impl`` semantics are identical
    to :func:`paged_attention` (the caller resolves "auto" so explicit
    requests can fail instead of silently downgrading)."""

    if impl not in PAGED_IMPLS:
        raise ValueError(
            f"impl must be one of {PAGED_IMPLS}, got {impl!r}"
        )
    if q.ndim != 4 or k_arena.ndim != 4 or tables.ndim != 2:
        raise ValueError(
            f"paged_attention_multi layout: q [S,K,H,D], arena "
            f"[NB,Hkv,bs,D], tables [S,MB]; got q{q.shape}, "
            f"k{k_arena.shape}, tables{tables.shape}"
        )
    if impl == "xla":
        return _paged_attention_multi_xla(
            q, k_arena, v_arena, tables, lengths
        )
    return _paged_attention_multi_pallas(
        q, k_arena, v_arena, tables, lengths,
        interpret=(impl == "pallas-interpret"),
    )
