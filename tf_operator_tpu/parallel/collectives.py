"""Hierarchical (slice-aware) gradient collectives — ISSUE 14 tentpole.

A multi-slice job's gradient all-reduce is the one large collective
forced across DCN, the fabric with orders of magnitude less bandwidth
than intra-slice ICI.  A topology-flat psum moves every gradient byte
across DCN; the standard fix (t5x/maxtext lineage) is the two-stage
hierarchical reduction this module implements over the slice-aware mesh
(`parallel/mesh.make_mesh(slices=)`, dp = the only DCN axis):

1. **reduce-scatter over ICI**: each slice reduces its local gradient
   and splits it into 1/n_ici fragments across the intra-slice axes
   (for fsdp-sharded params the gradient already IS the fragment —
   ZeRO sharding and hierarchy compose for free; for replicated params
   the intra-slice reduction is XLA's automatic ICI all-reduce and the
   split is a local slice under a sharding constraint);
2. **cross-slice all-reduce over dp**: only the fragment crosses DCN —
   1/n_ici of the bytes a flat psum would move;
3. **all-gather over ICI**: replicated params get their full gradient
   back (sharded params skip this — their optimizer shard only needs
   the fragment it owns).

`psum_hierarchical` / `GradSyncPlan.apply` run INSIDE a shard_map that
is manual over the DCN axis and auto over the intra-slice axes
(`utils/jax_compat.shard_map_partial_auto`) — `parallel/trainer.py`
builds that region around its loss/grad computation whenever the mesh
spans slices.  Replicated leaves are BUCKETED (flattened, concatenated,
padded to the fragment divisor) so the cross-slice phase launches a
handful of fused psums that overlap with backward compute instead of
one collective per tensor; leaves already sharded over an ICI axis are
reduced directly (they are their own fragments, and XLA fuses adjacent
all-reduces on real hardware).

Byte accounting convention (the `train_dcn_*` metric families and the
`--section multislice` bench): PAYLOAD bytes per device per step — a
stage-2 psum of an F-byte fragment counts F toward `fabric="dcn"`; a
stage-3 gather counts (full − fragment) toward `fabric="ici"`.  The
intra-slice reduction XLA inserts automatically is not counted (it is
identical in the flat and hierarchical programs).  TWO baselines,
reported separately because they answer different questions:

- **topology-blind** (`flat_blind_dcn_bytes`, the headline
  `dcn_bytes_ratio`): every gradient byte at full parameter width —
  the pre-ISSUE-14 state, where the mesh knew no slice boundary, so
  nothing guaranteed the (dp × fsdp) reduction ring kept fsdp hops on
  ICI; full width crossing DCN is the upper bound that blind layout
  permits and the motivation this module removes;
- **same-mesh flat** (`flat_mesh_dcn_bytes`,
  `dcn_bytes_ratio_vs_flat_mesh`): the `grad_sync="flat"` program on
  the SAME slice-aware mesh — there XLA's dp-psum of an already
  fsdp-sharded gradient moves only the shard, so sharded leaves tie
  the hierarchy and only replicated leaves win.  This is the baseline
  the measured A/B walls correspond to, and on fsdp-heavy models it
  is close to 1.0: once the mesh itself is slice-aware, ZeRO sharding
  already does most of the hierarchy's work for sharded params.

Counts are platform-independent (the same program structure runs
everywhere); the CPU smoke pins the ratios, the chip window measures
the walls.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tf_operator_tpu.parallel.mesh import (
    AXIS_DP,
    FABRIC_ICI,
    mesh_axis_links,
)

#: bucket capacity for fused cross-slice psums: big enough to amortize
#: per-collective latency, small enough that buckets finish (and their
#: DCN transfer starts) while the backward is still producing later
#: gradients
DEFAULT_BUCKET_BYTES = 4 << 20


def ici_axes(mesh: Mesh, dcn_axis: str = AXIS_DP) -> Tuple[str, ...]:
    """The mesh axes whose collectives stay intra-slice (size > 1 and
    not the DCN axis) — the fragment dimension of stage 1/3."""

    links = mesh_axis_links(mesh)
    return tuple(
        ax
        for ax in mesh.axis_names
        if ax != dcn_axis and mesh.shape[ax] > 1 and links[ax] == FABRIC_ICI
    )


def _spec_divisor(spec: Optional[PartitionSpec], mesh: Mesh, ici: Tuple[str, ...]) -> int:
    """How many ways an already-sharded leaf's gradient is split across
    intra-slice axes (1 = replicated: needs the bucket route)."""

    if spec is None:
        return 1
    div = 1
    for entry in spec:
        if entry is None:
            continue
        for ax in entry if isinstance(entry, tuple) else (entry,):
            if ax in ici:
                div *= mesh.shape[ax]
    return div


@dataclasses.dataclass
class _Bucket:
    indices: List[int]
    sizes: List[int]
    shapes: List[Tuple[int, ...]]
    dtype: Any
    padded: int  # total flattened length, padded to a multiple of n_ici


@dataclasses.dataclass
class GradSyncPlan:
    """Host-side compilation of one gradient tree's hierarchical sync:
    per-leaf routes, fused buckets, and the byte/collective ledger the
    `train_dcn_*` families export.  Built once per trainer (shapes are
    static); `apply` is called inside the manual-over-dcn shard_map."""

    mesh: Mesh
    dcn_axis: str
    ici: Tuple[str, ...]
    n_ici: int
    #: per flattened leaf: ("direct", divisor) — already ici-sharded,
    #: psum the fragment as-is; ("bucket", bucket_index, slot_index)
    routes: List[Tuple]
    buckets: List[_Bucket]
    #: payload bytes per device per step, two baselines — see module
    #: docstring ("Byte accounting convention")
    flat_blind_dcn_bytes: int
    flat_mesh_dcn_bytes: int
    dcn_bytes: int
    ici_bytes: int
    dcn_collectives: int
    ici_collectives: int

    @property
    def dcn_bytes_ratio(self) -> float:
        """hierarchical / topology-blind full-width cross-slice payload
        — the acceptance number (≤ 1/n_ici + padding epsilon) against
        the pre-slice-aware state."""

        return (
            self.dcn_bytes / self.flat_blind_dcn_bytes
            if self.flat_blind_dcn_bytes
            else 0.0
        )

    @property
    def dcn_bytes_ratio_vs_flat_mesh(self) -> float:
        """hierarchical / same-mesh flat-program cross-slice payload —
        what the measured grad_sync=flat A/B corresponds to (≈1.0 on
        fsdp-heavy models: sharded grads are already fragments there)."""

        return (
            self.dcn_bytes / self.flat_mesh_dcn_bytes
            if self.flat_mesh_dcn_bytes
            else 0.0
        )

    def apply(self, grads: Any) -> Any:
        """Sum `grads` across the DCN axis, two-stage.  Call inside a
        shard_map manual over `dcn_axis` with the ici axes auto.  The
        caller divides by the dcn extent if it wants the mean."""

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if len(leaves) != len(self.routes):
            raise ValueError(
                f"grad tree has {len(leaves)} leaves, plan was built for "
                f"{len(self.routes)}"
            )
        out: List[Any] = [None] * len(leaves)
        for i, route in enumerate(self.routes):
            if route[0] == "direct":
                out[i] = jax.lax.psum(leaves[i], self.dcn_axis)
        for b, bucket in enumerate(self.buckets):
            pieces = [leaves[i].reshape(-1) for i in bucket.indices]
            total = sum(bucket.sizes)
            if bucket.padded > total:
                # pad via an extra zeros piece — jnp.pad inside the
                # partial-auto region trips an XLA sharding-propagation
                # check on this jax (hard process abort, not an error)
                pieces.append(
                    jnp.zeros((bucket.padded - total,), bucket.dtype)
                )
            flat = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
            if self.n_ici > 1:
                # stage 1: scatter the fragment across the ICI axes — a
                # local slice (the value is replicated over them after
                # XLA's automatic intra-slice reduction)
                flat = jax.lax.with_sharding_constraint(
                    flat, NamedSharding(self.mesh, PartitionSpec(self.ici))
                )
            # stage 2: only the fragment crosses DCN
            flat = jax.lax.psum(flat, self.dcn_axis)
            if self.n_ici > 1:
                # stage 3: all-gather the full gradient back over ICI
                flat = jax.lax.with_sharding_constraint(
                    flat, NamedSharding(self.mesh, PartitionSpec(None))
                )
            offset = 0
            for idx, size, shape in zip(
                bucket.indices, bucket.sizes, bucket.shapes
            ):
                out[idx] = jax.lax.dynamic_slice_in_dim(
                    flat, offset, size
                ).reshape(shape)
                offset += size
        return jax.tree_util.tree_unflatten(treedef, out)

    def ledger(self) -> Dict[str, Any]:
        """Machine-readable accounting — what measure.py embeds and
        examples print in the MULTICHIP tail."""

        return {
            "dcn_axis": self.dcn_axis,
            "ici_axes": list(self.ici),
            "intra_slice_size": self.n_ici,
            "flat_dcn_bytes_per_step": self.flat_blind_dcn_bytes,
            "flat_mesh_dcn_bytes_per_step": self.flat_mesh_dcn_bytes,
            "hier_dcn_bytes_per_step": self.dcn_bytes,
            "hier_ici_bytes_per_step": self.ici_bytes,
            "dcn_bytes_ratio": round(self.dcn_bytes_ratio, 6),
            "dcn_bytes_ratio_vs_flat_mesh": round(
                self.dcn_bytes_ratio_vs_flat_mesh, 6
            ),
            "dcn_collectives_per_step": self.dcn_collectives,
            "ici_collectives_per_step": self.ici_collectives,
            "buckets": len(self.buckets),
        }


def build_grad_sync_plan(
    abstract_params: Any,
    param_shardings: Any,
    mesh: Mesh,
    *,
    dcn_axis: str = AXIS_DP,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
) -> GradSyncPlan:
    """Route every gradient leaf and precompute the byte ledger.

    `abstract_params`: tree of shape/dtype carriers (possibly
    flax-Partitioned-boxed — unboxed here, the clamp_overranked rule);
    `param_shardings`: the matching NamedSharding tree (None = treat
    every leaf as replicated)."""

    ici = ici_axes(mesh, dcn_axis)
    n_ici = 1
    for ax in ici:
        n_ici *= mesh.shape[ax]

    ab_leaves = [
        getattr(leaf, "value", leaf)
        for leaf in jax.tree_util.tree_leaves(abstract_params)
    ]
    if param_shardings is None:
        specs: List[Optional[PartitionSpec]] = [None] * len(ab_leaves)
    else:
        sh_leaves = jax.tree_util.tree_leaves(param_shardings)
        if len(sh_leaves) != len(ab_leaves):
            raise ValueError(
                f"params/shardings leaf mismatch: {len(ab_leaves)} vs "
                f"{len(sh_leaves)}"
            )
        specs = [getattr(s, "spec", None) for s in sh_leaves]

    routes: List[Tuple] = [()] * len(ab_leaves)
    flat_blind_bytes = 0
    flat_mesh_bytes = 0
    dcn_bytes = 0
    ici_bytes = 0
    direct = 0
    # bucket replicated leaves by dtype (concatenation needs one dtype)
    open_buckets: Dict[Any, _Bucket] = {}
    buckets: List[_Bucket] = []

    def close(dtype) -> None:
        b = open_buckets.pop(dtype, None)
        if b is not None:
            total = sum(b.sizes)
            b.padded = -(-total // n_ici) * n_ici
            buckets.append(b)

    for i, leaf in enumerate(ab_leaves):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = jnp.dtype(getattr(leaf, "dtype", jnp.float32))
        size = int(np.prod(shape)) if shape else 1
        nbytes = size * dtype.itemsize
        flat_blind_bytes += nbytes
        # non-float leaves (int counters in exotic states) never reach
        # the grad tree; guard anyway by routing them direct
        div = _spec_divisor(specs[i], mesh, ici)
        # same-mesh flat program: an ici-sharded grad's dp-psum already
        # moves only its shard over DCN, so flat ties the hierarchy on
        # direct leaves (see module docstring, "same-mesh flat")
        flat_mesh_bytes += nbytes // div
        if div > 1 or not jnp.issubdtype(dtype, jnp.floating):
            routes[i] = ("direct", div)
            direct += 1
            dcn_bytes += nbytes // div
            continue
        b = open_buckets.get(dtype)
        if b is None:
            b = open_buckets[dtype] = _Bucket([], [], [], dtype, 0)
        b.indices.append(i)
        b.sizes.append(size)
        b.shapes.append(shape)
        routes[i] = ("bucket", None, None)
        if sum(s * dtype.itemsize for s in b.sizes) >= bucket_bytes:
            close(dtype)
    for dtype in list(open_buckets):
        close(dtype)
    for b_idx, b in enumerate(buckets):
        for slot, leaf_idx in enumerate(b.indices):
            routes[leaf_idx] = ("bucket", b_idx, slot)
        frag = (b.padded // n_ici) * jnp.dtype(b.dtype).itemsize
        dcn_bytes += frag
        ici_bytes += b.padded * jnp.dtype(b.dtype).itemsize - frag

    return GradSyncPlan(
        mesh=mesh,
        dcn_axis=dcn_axis,
        ici=ici,
        n_ici=n_ici,
        routes=routes,
        buckets=buckets,
        flat_blind_dcn_bytes=flat_blind_bytes,
        flat_mesh_dcn_bytes=flat_mesh_bytes,
        dcn_bytes=dcn_bytes,
        ici_bytes=ici_bytes,
        dcn_collectives=len(buckets) + direct,
        ici_collectives=len(buckets) if n_ici > 1 else 0,
    )


def psum_hierarchical(
    x: Any,
    mesh: Mesh,
    *,
    shardings: Any = None,
    dcn_axis: str = AXIS_DP,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
) -> Any:
    """Drop-in two-stage psum over the DCN axis — sum semantics,
    allclose-pinned against `jax.lax.psum(x, dcn_axis)`.

    Call INSIDE a shard_map manual over `dcn_axis` (ici axes auto);
    trace-time shapes build the plan, so the first call per shape pays
    the routing walk and compiled programs reuse it for free."""

    plan = build_grad_sync_plan(
        x, shardings, mesh, dcn_axis=dcn_axis, bucket_bytes=bucket_bytes
    )
    return plan.apply(x)


def measure_sync_seconds(
    mesh: Mesh,
    nbytes: int = DEFAULT_BUCKET_BYTES,
    *,
    dcn_axis: str = AXIS_DP,
    metrics: Any = None,
    repeats: int = 5,
) -> Dict[str, float]:
    """Time the hierarchical reduction's two phases as standalone
    programs and observe them into the ``train_dcn_sync_seconds``
    histogram with the ``fabric`` label — the measured-seconds half of
    the byte ledger.  ``fabric="dcn"`` times the cross-slice psum of
    one fragment; ``fabric="ici"`` times the scatter+gather reshard
    pair.  Also times the FLAT full-width psum for the comparison row.
    On CPU sim worlds both fabrics are shared memory, so the absolute
    numbers are smoke-grade; the program structure (and the chip
    window's walls) are the signal."""

    from tf_operator_tpu.parallel.trainer import hard_sync
    from tf_operator_tpu.utils.jax_compat import shard_map_partial_auto

    ici = ici_axes(mesh, dcn_axis)
    n_ici = 1
    for ax in ici:
        n_ici *= mesh.shape[ax]
    n = max(n_ici, (nbytes // 4 // max(1, n_ici)) * max(1, n_ici))
    auto = frozenset(set(mesh.axis_names) - {dcn_axis})

    full = jax.device_put(
        jnp.ones((n,), jnp.float32), NamedSharding(mesh, PartitionSpec())
    )
    frag_sharding = NamedSharding(
        mesh, PartitionSpec(ici) if ici else PartitionSpec()
    )
    frag = jax.device_put(jnp.ones((n,), jnp.float32), frag_sharding)

    # ONE jitted psum serves both timings — jit specializes per operand
    # sharding, so psum_prog(frag) times the fragment-width DCN phase
    # and psum_prog(full) the full-width flat reduction
    psum_prog = jax.jit(
        shard_map_partial_auto(
            lambda v: jax.lax.psum(v, dcn_axis),
            mesh=mesh,
            in_specs=PartitionSpec(),
            out_specs=PartitionSpec(),
            auto=auto,
        )
    )

    def ici_pair(v):
        v = jax.lax.with_sharding_constraint(v, frag_sharding)
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, PartitionSpec())
        )

    ici_prog = jax.jit(ici_pair)

    def timed(fn, arg) -> float:
        hard_sync(fn(arg))  # compile outside the wall
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            hard_sync(fn(arg))
            best = min(best, time.perf_counter() - t0)
        return best

    out = {
        "dcn_fragment_s": timed(psum_prog, frag),
        "ici_reshard_s": timed(ici_prog, full),
        "flat_full_s": timed(psum_prog, full),
        "probe_bytes": n * 4,
        "intra_slice_size": n_ici,
    }
    if metrics is not None:
        metrics.observe_histogram(
            "train_dcn_sync_seconds", out["dcn_fragment_s"], fabric="dcn"
        )
        metrics.observe_histogram(
            "train_dcn_sync_seconds", out["ici_reshard_s"], fabric="ici"
        )
    return out
