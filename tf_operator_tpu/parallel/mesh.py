"""Named device meshes.

The reference scales by creating more pods and letting TF/NCCL discover
peers (SURVEY.md §2c); here scale is a `jax.sharding.Mesh` whose axes
name the parallelism dimensions, and every collective is an XLA op laid
out over ICI/DCN.  One mesh serves single-chip, single-slice multi-chip,
and (via `jax.distributed` + megascale env from the operator's bootstrap
injection, bootstrap/tpu_env.py) multi-slice jobs unchanged.
"""

from __future__ import annotations

import math
from typing import Dict, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_PP = "pp"
AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"

#: Canonical axis order.  Data-parallel-ish axes go first so that
#: neighbouring devices (fastest-varying, best ICI locality) end up on
#: the model axes (tp/sp) where collectives are in the critical path.
#: pp is outermost: stage boundaries move one small activation per tick
#: (point-to-point ppermute), the only traffic cheap enough for the
#: slowest links (DCN between hosts).
AXIS_ORDER = (AXIS_PP, AXIS_DP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)

#: The global batch is sharded over every data-ish axis.
BATCH_AXES = (AXIS_DP, AXIS_FSDP)


def make_mesh(
    shape: Optional[Mapping[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with the canonical named axes.

    `shape` maps axis name → size; exactly one size may be -1 ("use all
    remaining devices").  Missing axes get size 1, so downstream
    PartitionSpecs can always name any canonical axis.  Default: all
    devices on `dp`.
    """

    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    shape = dict(shape or {AXIS_DP: ndev})
    unknown = set(shape) - set(AXIS_ORDER)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXIS_ORDER}")

    sizes: Dict[str, int] = {ax: int(shape.get(ax, 1)) for ax in AXIS_ORDER}
    wild = [ax for ax, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one axis may be -1")
    if wild:
        known = math.prod(s for s in sizes.values() if s != -1)
        if ndev % known:
            raise ValueError(f"{ndev} devices not divisible by {known}")
        sizes[wild[0]] = ndev // known
    if math.prod(sizes.values()) != ndev:
        raise ValueError(f"mesh shape {sizes} != {ndev} devices")

    dims = tuple(sizes[ax] for ax in AXIS_ORDER)
    if ndev == 1:
        dev_array = np.array(devices).reshape(dims)
    else:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                dims, devices=np.asarray(devices, dtype=object)
            )
        except Exception:
            # On TPU a topology-aware layout is correctness-adjacent
            # (tp/sp collectives must ride neighbouring ICI links) —
            # never silently degrade there.
            if devices[0].platform == "tpu":
                raise
            dev_array = np.array(devices).reshape(dims)
    return Mesh(dev_array, AXIS_ORDER)


def batch_spec(extra: Sequence[Optional[str]] = ()) -> PartitionSpec:
    """PartitionSpec for a [batch, ...] array: batch over dp+fsdp."""
    return PartitionSpec(BATCH_AXES, *extra)


def batch_sharding(mesh: Mesh, extra: Sequence[Optional[str]] = ()) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape[AXIS_DP] * mesh.shape[AXIS_FSDP]


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    n = data_parallel_size(mesh)
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by dp size {n}")
    return global_batch // n
