"""Named device meshes.

The reference scales by creating more pods and letting TF/NCCL discover
peers (SURVEY.md §2c); here scale is a `jax.sharding.Mesh` whose axes
name the parallelism dimensions, and every collective is an XLA op laid
out over ICI/DCN.  One mesh serves single-chip, single-slice multi-chip,
and (via `jax.distributed` + megascale env from the operator's bootstrap
injection, bootstrap/tpu_env.py) multi-slice jobs unchanged.
"""

from __future__ import annotations

import math
from typing import Dict, List, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_PP = "pp"
AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"

#: Canonical axis order.  Data-parallel-ish axes go first so that
#: neighbouring devices (fastest-varying, best ICI locality) end up on
#: the model axes (tp/sp) where collectives are in the critical path.
#: pp is outermost: stage boundaries move one small activation per tick
#: (point-to-point ppermute), the only traffic cheap enough for the
#: slowest links (DCN between hosts).
AXIS_ORDER = (AXIS_PP, AXIS_DP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)

#: The global batch is sharded over every data-ish axis.
BATCH_AXES = (AXIS_DP, AXIS_FSDP)

#: Axes whose collectives are bandwidth-bound on the critical path —
#: these must NEVER span a DCN (cross-slice) boundary.  dp may (the
#: whole point of the hierarchical grad sync, parallel/collectives.py);
#: pp moves one small activation per tick, so it tolerates DCN too, but
#: the slice-aware layout below keeps it intra-slice anyway.
MODEL_AXES = (AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)

#: Fabric names mesh_axis_links reports: ICI = intra-slice links, DCN =
#: the data-center network between slices.
FABRIC_ICI = "ici"
FABRIC_DCN = "dcn"


def _device_slice_id(dev) -> Optional[int]:
    """The hardware slice this device belongs to, when the platform
    reports a meaningful one.  TPU runtimes expose ``slice_index`` as
    the real DCN topology; CPU/sim devices carry a vestigial
    ``slice_index`` of 0 on multi-process worlds, which must NOT be
    trusted (it would contradict the MEGASCALE env the operator
    injected) — sim worlds group contiguously instead."""

    if getattr(dev, "platform", None) != "tpu":
        return None
    v = getattr(dev, "slice_index", None)
    try:
        return int(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def _slice_groups(devices: Sequence[jax.Device], slices: int) -> List[List]:
    """Partition ``devices`` into ``slices`` equal groups, one per
    slice: by the hardware ``slice_index`` when every device reports
    one, else (CPU/sim worlds) contiguously in the given order — which
    matches the operator's pod numbering (pod index = slice*H + host,
    bootstrap/tpu_env.py), so process-local devices land in their
    MEGASCALE slice."""

    ndev = len(devices)
    if ndev % slices:
        raise ValueError(f"{ndev} devices not divisible into {slices} slices")
    per = ndev // slices
    ids = [_device_slice_id(d) for d in devices]
    if all(i is not None for i in ids):
        by_id: Dict[int, List] = {}
        for d, i in zip(devices, ids):
            by_id.setdefault(i, []).append(d)
        if len(by_id) != slices or any(len(g) != per for g in by_id.values()):
            raise ValueError(
                f"device slice_index topology {sorted((k, len(v)) for k, v in by_id.items())} "
                f"does not form {slices} equal slices of {per}"
            )
        return [by_id[k] for k in sorted(by_id)]
    return [list(devices[i * per : (i + 1) * per]) for i in range(slices)]


def _sub_mesh_array(dims, group) -> np.ndarray:
    """Device array for one slice's devices at ``dims`` (the intra-slice
    mesh shape), topology-aware when mesh_utils can be."""

    if len(group) == 1:
        return np.array(group).reshape(dims)
    try:
        from jax.experimental import mesh_utils

        return mesh_utils.create_device_mesh(
            dims, devices=np.asarray(group, dtype=object)
        )
    except Exception:
        # On TPU a topology-aware layout is correctness-adjacent
        # (tp/sp collectives must ride neighbouring ICI links) —
        # never silently degrade there.
        if group[0].platform == "tpu":
            raise
        return np.array(group).reshape(dims)


def make_mesh(
    shape: Optional[Mapping[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    slices: Optional[int] = None,
) -> Mesh:
    """Build a Mesh with the canonical named axes.

    `shape` maps axis name → size; exactly one size may be -1 ("use all
    remaining devices").  Missing axes get size 1, so downstream
    PartitionSpecs can always name any canonical axis.  Default: all
    devices on `dp`.

    ``slices`` makes the mesh SLICE-AWARE (ISSUE 14): the device array
    is ordered so that ``dp`` is the only axis crossing a slice
    boundary (DCN) while every other axis stays inside one slice (ICI).
    Concretely: each slice's devices form an intra-slice sub-mesh of
    shape (pp, dp/S, fsdp, ep, sp, tp) and the S sub-meshes are
    concatenated along ``dp`` — so dp coordinate j lives on slice
    ``j // (dp/S)``, and any collective over fsdp/tp/sp/ep/pp rides
    intra-slice links only.  ``slices=None`` auto-detects: the
    operator-injected ``MEGASCALE_NUM_SLICES`` (bootstrap/tpu_env.py)
    first, else the devices' hardware ``slice_index``, else 1.
    ``slices=1`` is the degenerate case and produces exactly the
    topology-unaware mesh of old.  Shapes whose ``dp`` extent cannot
    absorb the slice dimension (dp % slices != 0) are REFUSED — they
    would force a model axis across DCN, where its bandwidth-bound
    collectives do not belong (``mesh_axis_links`` reports the
    axis→fabric mapping; parallel/collectives.py builds on it).
    """

    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    shape = dict(shape or {AXIS_DP: ndev})
    unknown = set(shape) - set(AXIS_ORDER)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXIS_ORDER}")

    if slices is None:
        from tf_operator_tpu.bootstrap.tpu_env import detected_slice_topology

        slices, _ = detected_slice_topology()
        if slices <= 1:
            seen = {_device_slice_id(d) for d in devices}
            if None not in seen and len(seen) > 1:
                slices = len(seen)
    slices = max(1, int(slices))

    sizes: Dict[str, int] = {ax: int(shape.get(ax, 1)) for ax in AXIS_ORDER}
    wild = [ax for ax, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one axis may be -1")
    if wild:
        known = math.prod(s for s in sizes.values() if s != -1)
        if ndev % known:
            raise ValueError(f"{ndev} devices not divisible by {known}")
        sizes[wild[0]] = ndev // known
    if math.prod(sizes.values()) != ndev:
        raise ValueError(f"mesh shape {sizes} != {ndev} devices")

    dims = tuple(sizes[ax] for ax in AXIS_ORDER)
    if slices > 1:
        if ndev % slices:
            raise ValueError(f"{ndev} devices do not divide into {slices} slices")
        if sizes[AXIS_DP] % slices:
            # which axes WOULD have to straddle DCN to make the shape
            # fit?  Name them in the refusal so the error teaches the
            # contract instead of just citing arithmetic.
            would_cross = [
                ax for ax in (AXIS_PP, *MODEL_AXES) if sizes[ax] > 1
            ]
            raise ValueError(
                f"slice-aware mesh: dp={sizes[AXIS_DP]} is not divisible by "
                f"slices={slices}, so the slice dimension would have to ride "
                f"a model axis ({', '.join(would_cross) or 'none available'}) "
                "across DCN — refused (bandwidth-bound collectives do not "
                "belong on the cross-slice fabric).  Give dp an extent "
                "divisible by the slice count (dp varies across slices; "
                "fsdp/tp/sp/ep stay within a slice), or pass slices=1 to "
                "explicitly opt into a topology-blind mesh."
            )
        groups = _slice_groups(devices, slices)
        dp_axis = AXIS_ORDER.index(AXIS_DP)
        intra_dims = list(dims)
        intra_dims[dp_axis] = sizes[AXIS_DP] // slices
        dev_array = np.concatenate(
            [_sub_mesh_array(tuple(intra_dims), g) for g in groups],
            axis=dp_axis,
        )
    elif ndev == 1:
        dev_array = np.array(devices).reshape(dims)
    else:
        dev_array = _sub_mesh_array(dims, list(devices))
    mesh = Mesh(dev_array, AXIS_ORDER)
    _register_slice_assignment(mesh, dev_array, slices)
    links = mesh_axis_links(mesh)
    crossing = [ax for ax in MODEL_AXES if links[ax] == FABRIC_DCN]
    if crossing:
        raise ValueError(
            f"model axes {crossing} span a slice boundary (DCN) — their "
            "collectives are bandwidth-bound and must stay on ICI"
        )
    return mesh


#: mesh → per-device slice ids, for sim worlds whose devices carry no
#: hardware slice_index.  Keyed by the mesh's device id layout — and
#: jax INTERNS Mesh objects, so two make_mesh calls producing the same
#: layout return the SAME object even when their ``slices=`` differ
#: (the 2-slice and 1-slice {dp:2, fsdp:4} sim meshes are one object).
#: The slice interpretation of a layout is therefore process-wide
#: LAST-WRITE-WINS: re-registering an equal layout under a different
#: slice count re-labels every live alias of that mesh, and
#: ``_register_slice_assignment`` logs a warning so the flip is
#: observable (a Trainer snapshots ``slice_count`` at construction, so
#: already-built trainers keep their grad-sync choice).  Real-TPU
#: worlds are immune — the hardware ``slice_index`` outranks this
#: registry.  Bounded FIFO: the oldest layout is evicted, never the
#: whole table (a wholesale clear would silently re-label every live
#: mesh to 1 slice).
_SLICE_ASSIGNMENTS: Dict[tuple, np.ndarray] = {}
_MAX_SLICE_ASSIGNMENTS = 256


def _mesh_key(mesh: Mesh) -> tuple:
    return (
        tuple(d.id for d in mesh.devices.flat),
        mesh.devices.shape,
        mesh.axis_names,
    )


def _register_slice_assignment(mesh: Mesh, dev_array: np.ndarray, slices: int) -> None:
    dp_axis = AXIS_ORDER.index(AXIS_DP)
    ids = np.zeros(dev_array.shape, dtype=np.int64)
    if slices > 1:
        # dp coordinate j -> slice j // (dp/S): the concatenation order
        # make_mesh built the array in
        dp_size = dev_array.shape[dp_axis]
        dp_index = np.arange(dp_size) // (dp_size // slices)
        ids += dp_index.reshape(
            [1] * dp_axis + [dp_size] + [1] * (dev_array.ndim - dp_axis - 1)
        )
    key = _mesh_key(mesh)
    prev = _SLICE_ASSIGNMENTS.get(key)
    if prev is not None and len(np.unique(prev)) != max(1, slices):
        # interned-Mesh aliasing (see _SLICE_ASSIGNMENTS note): the
        # caller just re-interpreted an existing layout's slice
        # topology — legal, but every live alias flips with it, so say
        # so instead of flipping silently
        from tf_operator_tpu.utils.logging import _root

        _root.warning(
            "make_mesh: re-registering device layout as %d slice(s) "
            "(was %d) — jax interns equal meshes, so every live alias "
            "of this mesh now reports the new topology",
            max(1, slices), len(np.unique(prev)),
        )
    if prev is None:
        while len(_SLICE_ASSIGNMENTS) >= _MAX_SLICE_ASSIGNMENTS:
            _SLICE_ASSIGNMENTS.pop(next(iter(_SLICE_ASSIGNMENTS)))
    _SLICE_ASSIGNMENTS[key] = ids


def _slice_id_array(mesh: Mesh) -> np.ndarray:
    """Per-position slice ids for the mesh's device array: hardware
    ``slice_index`` when the devices report one (a Mesh built by hand
    on real multi-slice TPU still maps correctly), else the assignment
    recorded by make_mesh, else all-zero (single slice)."""

    hw = [_device_slice_id(d) for d in mesh.devices.flat]
    if all(i is not None for i in hw):
        return np.array(hw, dtype=np.int64).reshape(mesh.devices.shape)
    ids = _SLICE_ASSIGNMENTS.get(_mesh_key(mesh))
    if ids is not None:
        return ids
    return np.zeros(mesh.devices.shape, dtype=np.int64)


def slice_count(mesh: Mesh) -> int:
    """Number of distinct slices the mesh spans (1 = single slice: no
    DCN anywhere; the trainer's flat grad sync is then optimal)."""

    return int(len(np.unique(_slice_id_array(mesh))))


def mesh_axis_links(mesh: Mesh) -> Dict[str, str]:
    """Which fabric each mesh axis's collectives ride: ``"ici"``
    (intra-slice) or ``"dcn"`` (the axis crosses a slice boundary
    somewhere).  An axis rides DCN iff, holding every other coordinate
    fixed, moving along it can change the slice id.  Size-1 axes are
    trivially ICI."""

    ids = _slice_id_array(mesh)
    out: Dict[str, str] = {}
    for i, ax in enumerate(mesh.axis_names):
        varies = bool(np.any(ids.max(axis=i) != ids.min(axis=i)))
        out[ax] = FABRIC_DCN if varies else FABRIC_ICI
    return out


def batch_spec(extra: Sequence[Optional[str]] = ()) -> PartitionSpec:
    """PartitionSpec for a [batch, ...] array: batch over dp+fsdp."""
    return PartitionSpec(BATCH_AXES, *extra)


def batch_sharding(mesh: Mesh, extra: Sequence[Optional[str]] = ()) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape[AXIS_DP] * mesh.shape[AXIS_FSDP]


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    n = data_parallel_size(mesh)
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by dp size {n}")
    return global_batch // n
