"""Sharding-aware checkpoint/restore for the Trainer (orbax).

Parity: checkpointing is "not an operator feature" in the reference —
its examples checkpoint via TF MonitoredTrainingSession to shared
storage so the operator's restart contract (same replica index, same
env ⇒ resume) works (SURVEY.md §5 "Checkpoint / resume").  Here the
framework ships the equivalent as a first-class component: save the
full sharded TrainState (params, optimizer state, step, rng, mutable
collections), restore it INTO the trainer's shardings — every process
of a multi-host job calls save/restore collectively, and arrays come
back laid out exactly as the mesh expects (no gather through host 0).
"""

from __future__ import annotations

from typing import Optional

import jax


class TrainerCheckpointer:
    """Thin orbax CheckpointManager wrapper bound to a Trainer."""

    def __init__(self, directory: str, max_to_keep: int = 2):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, trainer, step: Optional[int] = None, wait: bool = False) -> int:
        """Persist the trainer's full TrainState at ``step`` (default:
        the state's own step counter).  Async by default; ``wait``
        blocks until durable.

        Saved UNBOXED (flax partitioning metadata stripped): the
        artifact is a plain array tree, so it restores into any mesh's
        trainer — the elastic-reshard contract (tests/test_elastic.py)
        — instead of being welded to the sharding annotations of the
        world that wrote it."""

        from flax.core import meta

        from tf_operator_tpu.utils.trace import default_tracer

        if step is None:
            step = int(trainer.state.step)
        with default_tracer.span(
            "checkpoint.save", attributes={"step": step, "wait": wait}
        ):
            self.manager.save(
                step,
                args=self._ocp.args.StandardSave(
                    {"state": meta.unbox(trainer.state)}
                ),
            )
            if wait:
                self.manager.wait_until_finished()
        return step

    def restore_latest(self, trainer) -> Optional[int]:
        """Restore the newest checkpoint into ``trainer.state`` with the
        trainer's shardings; returns the restored step or None if the
        directory is empty (fresh start).

        The restore target comes from the LIVE trainer (shapes from its
        state, layouts from its sharding tree), so a checkpoint written
        on one mesh redistributes onto whatever mesh this trainer runs
        — repartitioned, scaled out, or scaled in.  Values are grafted
        back into the live state's partitioning-metadata boxes, keeping
        the pytree structure the jitted step was traced with."""

        from flax.core import meta

        from tf_operator_tpu.utils.trace import default_tracer

        latest = self.manager.latest_step()
        if latest is None:
            return None
        with default_tracer.span(
            "checkpoint.restore", attributes={"step": latest}
        ):
            return self._restore(trainer, latest, meta)

    def _restore(self, trainer, latest: int, meta) -> int:
        def _is_box(x):
            return isinstance(x, meta.AxisMetadata)

        def _sds(x, s):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        unboxed = meta.unbox(trainer.state)
        abstract = jax.tree_util.tree_map(_sds, unboxed, trainer.state_sharding)
        try:
            restored = self.manager.restore(
                latest, args=self._ocp.args.StandardRestore({"state": abstract})
            )["state"]
        except ValueError as primary_err:
            # ONLY the tree-structure mismatch means "legacy artifact":
            # checkpoints written before the elastic-reshard change kept
            # the flax partitioning boxes, whose saved paths differ.
            # Every other failure (corruption, IO, shape change) must
            # surface with its original diagnostic, not be retried
            # against a structurally different target.
            if "tree structures do not match" not in str(primary_err):
                raise
            # rebuild the abstract target in the boxed shape, then
            # unbox what comes back — the restart contract holds across
            # the upgrade boundary.  A failure here propagates chained
            # to the primary error ("during handling of ...").
            boxed_abstract = jax.tree_util.tree_map(
                lambda live, s: (
                    live.replace_boxed(_sds(live.unbox(), s))
                    if _is_box(live)
                    else _sds(live, s)
                ),
                trainer.state,
                trainer.state_sharding,
                is_leaf=_is_box,
            )
            restored = meta.unbox(
                self.manager.restore(
                    latest,
                    args=self._ocp.args.StandardRestore({"state": boxed_abstract}),
                )["state"]
            )

        trainer.state = jax.tree_util.tree_map(
            lambda live, val: live.replace_boxed(val) if _is_box(live) else val,
            trainer.state,
            restored,
            is_leaf=_is_box,
        )
        trainer._host_step = int(trainer.state.step)
        return latest

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()


def export_params(trainer, directory: str) -> None:
    """Params-only export for serving — the train→checkpoint→serve leg.

    COLLECTIVE on multi-host meshes (orbax writes each process's shards
    directly; nothing funnels through host 0).  Partitioned metadata is
    unboxed first so the artifact is a plain array tree any consumer can
    load without flax sharding annotations.

    For decoder families the artifact SELF-DESCRIBES: a ``model.json``
    (models/registry.describe_model) lands next to the arrays, so the
    serving side reconstructs the exact architecture instead of being
    hand-configured (examples/serve_lm.py reads it)."""

    model, params = trainer.model, trainer.state.params
    if hasattr(model, "merged_params") and hasattr(model, "model"):
        # LoRA trainer: state.params is the ADAPTER tree — exporting it
        # raw under the base family's model.json would be a silently
        # broken artifact.  Bake the adapters in; the artifact serves
        # like any dense export.
        params = model.merged_params(params)
        model = model.model
    export_merged_params(model, params, directory)


def export_merged_params(model, params, directory: str) -> None:
    """Artifact from an explicit (model, params) pair — the export core
    `export_params` delegates to.  Use directly for trees that never
    lived in a Trainer state: LoRA-merged weights
    (models/lora.LoraModel.merged_params), surgically edited params,
    etc.  Same self-describing model.json contract."""

    import json
    import os

    import orbax.checkpoint as ocp
    from flax.core import meta

    from tf_operator_tpu.models.registry import describe_model

    params = meta.unbox(params)
    ckptr = ocp.StandardCheckpointer()
    # force: re-exporting to a stable serving path ("latest/") must
    # overwrite, not raise
    ckptr.save(directory, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    # a LoraModel wrapper describes as its WRAPPED family (the merged
    # tree is plain dense weights)
    desc = describe_model(getattr(model, "model", model))
    if desc is not None:
        # process 0 writes on multi-host (the path is shared storage)
        if jax.process_index() == 0:
            with open(os.path.join(directory, "model.json"), "w") as f:
                json.dump(desc, f, indent=1)


def load_model_description(directory: str):
    """The ``model.json`` an export wrote, or None (pre-registry
    artifacts / non-decoder families).  Pair with
    models/registry.model_from_description."""

    import json
    import os

    path = os.path.join(directory, "model.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_params(directory: str):
    """Load an `export_params` artifact host-local (single-process
    serving); pass the result straight to models.decode.generate.

    Restores against an UNSHARDED abstract target built from the
    checkpoint's own metadata — a serving host with any device count
    (typically 1) can consume an artifact exported from any mesh;
    restoring with the saved shardings would demand the training
    topology."""

    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(directory)
    # orbax API drift: newer StandardCheckpointer.metadata returns the
    # item tree directly; older releases wrap it in a CheckpointMetadata
    # with .item_metadata
    meta = getattr(meta, "item_metadata", meta)
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=dev), meta
    )
    out = ckptr.restore(directory, abstract)
    ckptr.close()
    return out
