"""Sharding-aware checkpoint/restore for the Trainer (orbax).

Parity: checkpointing is "not an operator feature" in the reference —
its examples checkpoint via TF MonitoredTrainingSession to shared
storage so the operator's restart contract (same replica index, same
env ⇒ resume) works (SURVEY.md §5 "Checkpoint / resume").  Here the
framework ships the equivalent as a first-class component: save the
full sharded TrainState (params, optimizer state, step, rng, mutable
collections), restore it INTO the trainer's shardings — every process
of a multi-host job calls save/restore collectively, and arrays come
back laid out exactly as the mesh expects (no gather through host 0).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

import jax


def _device_copy(tree, zero):
    """A REAL on-device copy of every leaf, as a full-size
    ``dynamic_slice`` whose start index is a RUNTIME value (``zero``,
    passed as a traced argument).  Two lesser spellings fail on this
    platform, both measured 2026-08-03:

    - ``jax.jit(lambda t: t)`` is input-forwarded by jax (outputs that
      are literally inputs skip XLA and return the same buffers — the
      "snapshot" is then clobbered by the next donated train step);
    - an add-zero copy is algebraically foldable, and its compiled
      output buffers were observed tracking the live state under the
      training suite (content drifting toward later-step values while
      the checkpoint writer held the only reference).

    A dynamic_slice with a start XLA cannot prove constant must
    materialize a genuine gather into fresh buffers — nothing to fold,
    nothing to alias."""

    def cp(x):
        if x.ndim == 0:
            return jax.lax.dynamic_slice(x[None], (zero,), (1,))[0]
        return jax.lax.dynamic_slice(x, (zero,) * x.ndim, x.shape)

    return jax.tree_util.tree_map(cp, tree)


class TrainerCheckpointer:
    """Orbax CheckpointManager wrapper bound to a Trainer, with an
    ASYNC save path that never blocks the step loop.

    The old save() called ``manager.save`` on the LIVE state inline,
    which device_gets the full TrainState synchronously — the step loop
    stalled for (pending compute + D2H of params+optimizer state) every
    save.  Now:

      1. ``save()`` dispatches a jitted device COPY of the state
         (async — the copy runs after in-flight steps finish and
         materializes buffers the step loop's donation can't
         invalidate) and parks it as the PENDING snapshot;
      2. the pending snapshot is fetched to host at the NEXT
         checkpointer call (save/wait/restore/close), on the MAIN
         thread — by then its compute finished a whole checkpoint
         interval ago, so the fetch is a pure transfer, not a pipeline
         drain.  Fetching from the main thread is deliberate: on this
         platform a background thread's ``device_get`` racing the step
         loop's donated dispatches returns wrong values (measured
         2026-08-03, deterministic drift toward later-step state even
         though the snapshot's buffers are independent — same family
         of platform lies as hard_sync's, PROFILE.md "timing
         honesty"), so background threads here do DISK work only;
      3. the host tree goes to a background writer thread for the
         orbax write, with a bounded in-flight budget
         (``max_in_flight``): when the budget is full the caller waits
         for the oldest writer — bounded memory, traced honestly
         (``checkpoint.save.budget_wait``) because it is the one spot
         the step loop can still stall.

    Durability contract: a ``wait=False`` save is durable at latest by
    the NEXT checkpointer call; ``wait=True`` preserves the synchronous
    contract (save returns with the checkpoint durable) — same code
    path, flushed immediately, so sync and async artifacts are
    byte-identical at the payload level
    (tests/test_checkpoint_async.py).  A background write failure is
    re-raised on the NEXT save/restore/wait/close call — async must not
    mean silently lossy.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 2,
        max_in_flight: int = 1,
        metrics=None,
    ):
        import orbax.checkpoint as ocp

        if metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            metrics = default_metrics
        #: registry the durability stamp lands on — injectable so a
        #: controller/engine wired to a private registry (the e2e rigs)
        #: sees checkpoint_last_success_unix on the registry it reads
        self.metrics = metrics
        self._ocp = ocp
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )
        if max_in_flight < 1:
            raise ValueError(f"max_in_flight must be >= 1, got {max_in_flight}")
        self.max_in_flight = max_in_flight
        self._in_flight: deque = deque()  # (step, Thread) — disk writers
        #: the parked device snapshot awaiting its main-thread fetch:
        #: (step, unboxed device tree, originating trace id) or None
        self._pending = None
        self._errors: list = []
        self._errors_lock = threading.Lock()
        #: orbax managers are not safe for concurrent save calls: with
        #: max_in_flight > 1 the writer threads serialize here (the
        #: budget bounds queued SNAPSHOTS, not concurrent writes)
        self._manager_lock = threading.Lock()

    def _raise_pending_error(self) -> None:
        with self._errors_lock:
            if self._errors:
                err = self._errors.pop(0)
                raise RuntimeError(
                    f"async checkpoint save (step {err[0]}) failed"
                ) from err[1]

    def _reap(self) -> None:
        while self._in_flight and not self._in_flight[0][1].is_alive():
            self._in_flight.popleft()

    def _flush_pending(self) -> None:
        """MAIN-thread fetch of the parked snapshot (see class
        docstring: background device access is unsafe here), then hand
        the host tree to a disk-writer thread under the budget."""

        from tf_operator_tpu.utils.trace import default_tracer

        if self._pending is None:
            return
        step, unboxed, trace_id = self._pending
        self._pending = None
        with default_tracer.span(
            "checkpoint.fetch",
            attributes={"step": step, "saveTraceId": trace_id},
        ):
            host_state = jax.device_get(unboxed)
        self._reap()
        while len(self._in_flight) >= self.max_in_flight:
            with default_tracer.span(
                "checkpoint.save.budget_wait",
                attributes={"inFlight": len(self._in_flight)},
            ):
                self._in_flight.popleft()[1].join()
        thread = threading.Thread(
            target=self._write,
            args=(step, host_state, trace_id),
            name=f"ckpt-save-{step}",
            daemon=True,
        )
        self._in_flight.append((step, thread))
        thread.start()

    def _drain(self) -> None:
        """Flush the parked snapshot, join every in-flight writer and
        the orbax background work — after this, the newest requested
        save is durable."""

        self._flush_pending()
        while self._in_flight:
            self._in_flight.popleft()[1].join()
        self.manager.wait_until_finished()
        self._raise_pending_error()

    def _write(self, step: int, host_state, parent_trace_id) -> None:
        """Background writer body: the orbax DISK write of an
        already-host state tree — no device access off the main thread.
        Its span is a fresh root (threads don't inherit the loop's
        context) linked back via the saveTraceId attribute."""

        from tf_operator_tpu.utils.trace import default_tracer

        try:
            with default_tracer.span(
                "checkpoint.write",
                root=True,
                attributes={"step": step, "saveTraceId": parent_trace_id},
            ):
                with self._manager_lock:
                    self.manager.save(
                        step,
                        args=self._ocp.args.StandardSave(
                            {"state": host_state}
                        ),
                    )
                    self.manager.wait_until_finished()
            # stamped at the DURABILITY point, not at save() dispatch:
            # checkpoint-age alerting (utils/alerts.py "checkpoint-
            # stale") and the health rollup's lastCheckpointAgeSeconds
            # must measure "how much work would a crash lose", which a
            # parked-but-unwritten snapshot does not bound
            self.metrics.set(
                "checkpoint_last_success_unix", time.time()
            )
            self.metrics.inc("checkpoint_saves_total")
        except BaseException as exc:  # surfaces on the next caller op
            with self._errors_lock:
                self._errors.append((step, exc))

    def save(self, trainer, step: Optional[int] = None, wait: bool = False) -> int:
        """Persist the trainer's full TrainState at ``step`` (default:
        the trainer's HOST-side step mirror — reading
        ``trainer.state.step`` would be a blocking device sync in the
        step loop).  Returns after snapshot + enqueue; ``wait=True``
        blocks until durable (the test/shutdown contract).

        Saved UNBOXED (flax partitioning metadata stripped): the
        artifact is a plain array tree, so it restores into any mesh's
        trainer — the elastic-reshard contract (tests/test_elastic.py)
        — instead of being welded to the sharding annotations of the
        world that wrote it."""

        from flax.core import meta

        from tf_operator_tpu.utils.trace import default_tracer

        self._raise_pending_error()
        if step is None:
            host_step = getattr(trainer, "_host_step", None)
            # duck-typed trainers without the host-side mirror fall
            # back to reading the device step — a blocking sync, but
            # correct beats silently writing every checkpoint at 0
            step = (
                int(host_step)
                if host_step is not None
                else int(trainer.state.step)
            )
        # the span covers exactly what the STEP LOOP waited on: the
        # (async) snapshot dispatch, the PREVIOUS save's deferred
        # fetch (pure transfer — its compute finished an interval
        # ago), any budget wait, and — only with wait=True — the full
        # flush; the disk wall lives in the writer's own
        # checkpoint.write span
        with default_tracer.span(
            "checkpoint.save", attributes={"step": step, "wait": wait}
        ) as sp:
            # device-side copy: dispatch is async; the copied buffers
            # are independent of the live state, so the next
            # train_step's donation cannot invalidate what the
            # deferred fetch will read (_device_copy — a jit identity
            # would be input-forwarded and alias the donated buffers).
            # The snapshot compiles OUTSIDE the persistent compilation
            # cache: on this platform a cache-deserialized SPMD
            # executable of this program has computed WRONG VALUES
            # (measured 2026-08-03, only on the cache read path), and
            # a corrupt snapshot program silently saves wrong bytes.
            # One honest in-process compile per shape is the price of
            # a checkpoint you can trust.
            if not hasattr(self, "_snapshot_fn"):
                self._snapshot_fn = jax.jit(_device_copy)
            import jax.numpy as jnp

            prev_cache = jax.config.jax_enable_compilation_cache
            jax.config.update("jax_enable_compilation_cache", False)
            try:
                snapshot = self._snapshot_fn(
                    trainer.state, jnp.zeros((), jnp.int32)
                )
            finally:
                jax.config.update(
                    "jax_enable_compilation_cache", prev_cache
                )
            # resolve the PREVIOUS parked snapshot first (the same
            # deferred-window discipline as the train loop's metric
            # resolution), then park this one
            self._flush_pending()
            self._pending = (step, meta.unbox(snapshot), sp.trace_id)
            if wait:
                self._drain()
        return step

    def wait(self) -> None:
        """Block until every enqueued save is durable (end-of-run
        barrier for callers that saved with wait=False)."""

        self._drain()

    def restore_latest(self, trainer) -> Optional[int]:
        """Restore the newest checkpoint into ``trainer.state`` with the
        trainer's shardings; returns the restored step or None if the
        directory is empty (fresh start).

        The restore target comes from the LIVE trainer (shapes from its
        state, layouts from its sharding tree), so a checkpoint written
        on one mesh redistributes onto whatever mesh this trainer runs
        — repartitioned, scaled out, or scaled in.  Values are grafted
        back into the live state's partitioning-metadata boxes, keeping
        the pytree structure the jitted step was traced with."""

        from flax.core import meta

        from tf_operator_tpu.utils.trace import default_tracer

        # restore-while-saving must see the newest requested step (and
        # surface any background write failure) — drain first
        self._drain()
        latest = self.manager.latest_step()
        if latest is None:
            return None
        with default_tracer.span(
            "checkpoint.restore", attributes={"step": latest}
        ):
            return self._restore(trainer, latest, meta)

    def _restore(self, trainer, latest: int, meta) -> int:
        def _is_box(x):
            return isinstance(x, meta.AxisMetadata)

        def _sds(x, s):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        unboxed = meta.unbox(trainer.state)
        abstract = jax.tree_util.tree_map(_sds, unboxed, trainer.state_sharding)
        try:
            restored = self.manager.restore(
                latest, args=self._ocp.args.StandardRestore({"state": abstract})
            )["state"]
        except ValueError as primary_err:
            # ONLY the tree-structure mismatch means "legacy artifact":
            # checkpoints written before the elastic-reshard change kept
            # the flax partitioning boxes, whose saved paths differ.
            # Every other failure (corruption, IO, shape change) must
            # surface with its original diagnostic, not be retried
            # against a structurally different target.  Orbax wording
            # drift: 0.5 said "tree structures do not match"; 0.7 hits
            # the same mismatch as "Expected dict, got ArrayRestoreArgs"
            # (flatten_up_to of the boxed artifact against the plain
            # target).
            msg = str(primary_err)
            if (
                "tree structures do not match" not in msg
                and "Expected dict" not in msg
            ):
                raise
            # rebuild the abstract target in the boxed ARTIFACT shape,
            # then unwrap what comes back — the restart contract holds
            # across the upgrade boundary.  A legacy artifact stored
            # each flax partitioning box through its pytree form, i.e.
            # an extra {"value": leaf} nesting level; the target must
            # mirror that as PLAIN dicts (orbax 0.7 rejects real
            # AxisMetadata nodes in restore targets — the tree-flatten
            # mismatch this except arm exists for).  A failure here
            # propagates chained to the primary error ("during
            # handling of ...").
            boxed_abstract = jax.tree_util.tree_map(
                lambda live, s: (
                    {"value": _sds(live.unbox(), s)}
                    if _is_box(live)
                    else _sds(live, s)
                ),
                trainer.state,
                trainer.state_sharding,
                is_leaf=_is_box,
            )
            restored = self.manager.restore(
                latest,
                args=self._ocp.args.StandardRestore({"state": boxed_abstract}),
            )["state"]
            restored = jax.tree_util.tree_map(
                lambda live, val: val["value"] if _is_box(live) else val,
                trainer.state,
                restored,
                is_leaf=_is_box,
            )

        trainer.state = jax.tree_util.tree_map(
            lambda live, val: live.replace_boxed(val) if _is_box(live) else val,
            trainer.state,
            restored,
            is_leaf=_is_box,
        )
        trainer._host_step = int(trainer.state.step)
        return latest

    def close(self) -> None:
        try:
            self._drain()
        finally:
            self.manager.close()


def export_params(trainer, directory: str) -> None:
    """Params-only export for serving — the train→checkpoint→serve leg.

    COLLECTIVE on multi-host meshes (orbax writes each process's shards
    directly; nothing funnels through host 0).  Partitioned metadata is
    unboxed first so the artifact is a plain array tree any consumer can
    load without flax sharding annotations.

    For decoder families the artifact SELF-DESCRIBES: a ``model.json``
    (models/registry.describe_model) lands next to the arrays, so the
    serving side reconstructs the exact architecture instead of being
    hand-configured (examples/serve_lm.py reads it)."""

    model, params = trainer.model, trainer.state.params
    if hasattr(model, "merged_params") and hasattr(model, "model"):
        # LoRA trainer: state.params is the ADAPTER tree — exporting it
        # raw under the base family's model.json would be a silently
        # broken artifact.  Bake the adapters in; the artifact serves
        # like any dense export.
        params = model.merged_params(params)
        model = model.model
    export_merged_params(model, params, directory)


def export_merged_params(model, params, directory: str) -> None:
    """Artifact from an explicit (model, params) pair — the export core
    `export_params` delegates to.  Use directly for trees that never
    lived in a Trainer state: LoRA-merged weights
    (models/lora.LoraModel.merged_params), surgically edited params,
    etc.  Same self-describing model.json contract."""

    import json
    import os

    import orbax.checkpoint as ocp
    from flax.core import meta

    from tf_operator_tpu.models.registry import describe_model

    params = meta.unbox(params)
    ckptr = ocp.StandardCheckpointer()
    # force: re-exporting to a stable serving path ("latest/") must
    # overwrite, not raise
    ckptr.save(directory, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    # a LoraModel wrapper describes as its WRAPPED family (the merged
    # tree is plain dense weights)
    desc = describe_model(getattr(model, "model", model))
    if desc is not None:
        # process 0 writes on multi-host (the path is shared storage)
        if jax.process_index() == 0:
            with open(os.path.join(directory, "model.json"), "w") as f:
                json.dump(desc, f, indent=1)


def load_model_description(directory: str):
    """The ``model.json`` an export wrote, or None (pre-registry
    artifacts / non-decoder families).  Pair with
    models/registry.model_from_description."""

    import json
    import os

    path = os.path.join(directory, "model.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_params(directory: str):
    """Load an `export_params` artifact host-local (single-process
    serving); pass the result straight to models.decode.generate.

    Restores against an UNSHARDED abstract target built from the
    checkpoint's own metadata — a serving host with any device count
    (typically 1) can consume an artifact exported from any mesh;
    restoring with the saved shardings would demand the training
    topology."""

    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(directory)
    # orbax API drift: newer StandardCheckpointer.metadata returns the
    # item tree directly; older releases wrap it in a CheckpointMetadata
    # with .item_metadata
    meta = getattr(meta, "item_metadata", meta)
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=dev), meta
    )
    out = ckptr.restore(directory, abstract)
    ckptr.close()
    return out
