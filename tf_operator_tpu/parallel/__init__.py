"""Parallelism layer: device meshes, sharding rules, sharded training.

This is the TPU-native replacement for what the reference *enables* via
cluster wiring (SURVEY.md §2b): data parallelism (MultiWorkerMirrored /
Horovod+NCCL all-reduce) and parameter-server sharding become explicit
`jax.sharding` layouts over a named device Mesh, with XLA inserting the
collectives (all-reduce over ICI within a slice, DCN across slices).

Axes convention (scaling-book style):
  dp    — pure data parallelism (batch)
  fsdp  — data parallelism with fully-sharded params/optimizer state
          (the TPU-native translation of the reference's PS topology)
  tp    — tensor parallelism (megatron-style sharded matmuls)
  sp    — sequence/context parallelism (ring attention)
  ep    — expert parallelism (MoE)
  pp    — pipeline parallelism (GPipe schedule over shard_map stages)
"""

from tf_operator_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_PP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_SP,
    AXIS_TP,
    BATCH_AXES,
    batch_sharding,
    batch_spec,
    make_mesh,
    mesh_axis_links,
    replicated,
    slice_count,
)
from tf_operator_tpu.parallel.collectives import (
    GradSyncPlan,
    build_grad_sync_plan,
    psum_hierarchical,
)
from tf_operator_tpu.parallel.checkpoint import (
    TrainerCheckpointer,
    export_merged_params,
    export_params,
    load_model_description,
    load_params,
)
from tf_operator_tpu.parallel.pipeline import (
    pipeline_apply,
    pipelined,
    stack_stage_params,
)
from tf_operator_tpu.parallel.sharding import (
    LOGICAL_RULES,
    fsdp_shardings,
    logical_shardings,
)
from tf_operator_tpu.parallel.trainer import Trainer, TrainerConfig

__all__ = [
    "AXIS_DP",
    "AXIS_PP",
    "AXIS_EP",
    "AXIS_FSDP",
    "AXIS_SP",
    "AXIS_TP",
    "BATCH_AXES",
    "batch_sharding",
    "batch_spec",
    "make_mesh",
    "mesh_axis_links",
    "replicated",
    "slice_count",
    "GradSyncPlan",
    "build_grad_sync_plan",
    "psum_hierarchical",
    "LOGICAL_RULES",
    "fsdp_shardings",
    "logical_shardings",
    "Trainer",
    "TrainerCheckpointer",
    "TrainerConfig",
    "export_merged_params",
    "export_params",
    "load_model_description",
    "load_params",
    "pipeline_apply",
    "pipelined",
    "stack_stage_params",
]
