"""Parameter sharding rules.

Two mechanisms, matching how the models are written:

1. **FSDP auto-rule** (`fsdp_shardings`): for models without logical
   axis metadata (CNNs: mnist, ResNet).  Each parameter is sharded along
   its largest dimension divisible by the fsdp axis size; small params
   stay replicated.  This is the TPU-native stand-in for the reference's
   parameter-server topology (SURVEY.md §2b: "closest is … fully-sharded
   (FSDP-style) pjit sharding") — optimizer state shards identically via
   the same tree-map.

2. **Logical rules** (`logical_shardings`): for transformer models that
   annotate params with `nn.with_logical_partitioning` (bert/t5).  Rules
   map logical names → mesh axes, t5x-style.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tf_operator_tpu.parallel.mesh import AXIS_EP, AXIS_FSDP, AXIS_SP, AXIS_TP

#: Logical-axis → mesh-axis rules for the transformer family.
#: batch rides dp+fsdp; embed shards over fsdp (ZeRO-3 style); heads/mlp
#: shard over tp (megatron); sequence over sp; experts over ep.
LOGICAL_RULES: Tuple[Tuple[str, Any], ...] = (
    # -- parameter axes ----------------------------------------------------
    ("batch", ("dp", "fsdp")),
    ("embed", AXIS_FSDP),
    ("embed2", None),  # second dim of square hidden-to-hidden kernels
    ("mlp", AXIS_TP),
    ("heads", AXIS_TP),
    ("kv", None),
    ("vocab", AXIS_TP),
    ("seq", AXIS_SP),
    ("expert", AXIS_EP),
    ("cap", None),  # MoE per-expert capacity buckets (models/moe.py)
    ("stack", None),
    ("norm", None),
    ("relpos_buckets", None),
    # -- activation axes (distinct names: activations never shard their
    # feature dim over fsdp — that axis is for *param* ZeRO-sharding) ------
    ("act_embed", None),
    ("act_heads", AXIS_TP),
    ("act_kv", None),
    ("act_mlp", AXIS_TP),
)

#: Params smaller than this stay replicated under the FSDP auto-rule
#: (sharding tiny biases/norm scales costs more in collectives than it
#: saves in HBM).
MIN_SHARD_SIZE = 2**13


def fsdp_spec(
    shape: Sequence[int],
    fsdp_size: int,
    min_size: int = MIN_SHARD_SIZE,
) -> PartitionSpec:
    """Shard the largest divisible dim over fsdp; else replicate."""

    if fsdp_size <= 1:
        return PartitionSpec()
    total = 1
    for d in shape:
        total *= int(d)
    if total < min_size:
        return PartitionSpec()
    # prefer the largest dim; break ties toward the last (contraction
    # dims are usually last and largest in conv/dense kernels)
    best = -1
    best_dim = -1
    for i, d in enumerate(shape):
        if d % fsdp_size == 0 and d >= best:
            best, best_dim = d, i
    if best_dim < 0:
        return PartitionSpec()
    parts: list = [None] * len(shape)
    parts[best_dim] = AXIS_FSDP
    return PartitionSpec(*parts)


def fsdp_shardings(params: Any, mesh: Mesh, min_size: int = MIN_SHARD_SIZE) -> Any:
    """Tree of NamedShardings for a param (or opt-state) tree."""

    fsdp = mesh.shape[AXIS_FSDP]

    def one(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, fsdp_spec(shape, fsdp, min_size))

    return jax.tree_util.tree_map(one, params)


def logical_shardings(
    abstract_tree: Any,
    mesh: Mesh,
    rules: Tuple[Tuple[str, Any], ...] = LOGICAL_RULES,
) -> Any:
    """Shardings for a tree of `nn.Partitioned` / logically-annotated
    abstract values (from `jax.eval_shape` over a flax init)."""

    import flax.linen as nn

    specs = nn.get_partition_spec(abstract_tree)
    shardings = nn.logical_to_mesh_sharding(specs, mesh, list(rules))
    # clamp ONLY the optimizer-state subtree: factored optimizers
    # (adafactor) put a kernel's axis names on mis-shaped statistics
    # there, and replicating those is their memory contract.  Params
    # themselves stay unclamped so a genuinely indivisible annotated
    # dim still fails loudly at jit time instead of silently
    # replicating the model.
    opt = getattr(abstract_tree, "opt_state", None)
    if opt is not None:
        shardings = shardings.replace(
            opt_state=clamp_overranked(shardings.opt_state, opt)
        )
    return shardings


def clamp_overranked(shardings: Any, abstract_tree: Any) -> Any:
    """Replicate any leaf whose inferred spec cannot legally apply to
    the value: more spec axes than dims, or a dim not divisible by its
    mesh axes.  Factored optimizers (adafactor) keep a kernel's logical
    axis names on RANK-1 row/col statistics and shape-(1,) placeholder
    stats for vectors — replicating that O(rows + cols) state is
    exactly adafactor's memory contract anyway.  Applied to
    optimizer-state subtrees only (see logical_shardings) so model
    params keep loud jit-time errors for real misconfigurations."""

    def fix(sh, ab):
        if not isinstance(sh, NamedSharding):
            return sh
        # the abstract tree holds flax meta.Partitioned boxes at the
        # annotated positions — unbox before reading the shape, or every
        # annotated leaf reads as rank 0 and gets wrongly clamped
        ab = getattr(ab, "value", ab)
        shape = tuple(getattr(ab, "shape", ()) or ())
        if len(sh.spec) > len(shape):
            return NamedSharding(sh.mesh, PartitionSpec())
        for dim, axes in zip(shape, sh.spec):
            if not axes:
                continue
            axes_t = axes if isinstance(axes, tuple) else (axes,)
            n = 1
            for ax in axes_t:
                n *= sh.mesh.shape[ax]
            if n > 1 and dim % n:
                return NamedSharding(sh.mesh, PartitionSpec())
        return sh

    return jax.tree_util.tree_map(fix, shardings, abstract_tree)
