"""Sharded training loop: the compute-side half of the framework.

In the reference, the train step lives in user containers (TF
MonitoredTrainingSession / MultiWorkerMirrored, SURVEY.md §3.3) and the
operator only wires processes together.  Here the framework also ships
the TPU-native train-step machinery the examples use:

- params/opt-state laid out by the FSDP auto-rule or logical rules
  (parallel/sharding.py) over a named mesh;
- batch sharded over (dp, fsdp);
- one jitted, donated train step — XLA inserts the gradient all-reduce
  (ICI) exactly where the reference's examples used NCCL/CollectiveOps;
- bfloat16 compute / float32 params+optimizer (MXU-friendly);
- optional `jax.checkpoint` rematerialisation for HBM headroom.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tf_operator_tpu.parallel.mesh import batch_sharding
from tf_operator_tpu.parallel.sharding import LOGICAL_RULES, fsdp_shardings

Batch = Dict[str, jax.Array]


def hard_sync(tree):
    """Wait for `tree`'s computation to ACTUALLY finish.

    `block_until_ready` alone is not trustworthy on the tunneled axon
    TPU platform: buffer readiness does not reliably cover programs
    containing pallas custom calls (measured 2026-08-01, PROFILE.md
    "timing honesty").  A host FETCH of a value data-dependent on the
    output cannot resolve early, so sync ends with a one-leaf fetch."""

    leaves = jax.tree_util.tree_leaves(tree)
    if leaves:
        jax.device_get(leaves[0])
    jax.tree_util.tree_map(lambda x: x.block_until_ready(), tree)
    return tree
#: loss_fn(params, state, batch, rng) -> (loss, aux); aux: {"metrics":
#: {...}, "model_state": new mutable collections or None}
LossFn = Callable[[Any, "TrainState", Batch, jax.Array], Tuple[jax.Array, Dict]]


class TrainState(train_state.TrainState):
    """flax TrainState + threaded dropout rng + mutable collections
    (e.g. ResNet batch_stats)."""

    rng: Any = None
    model_state: Any = None


@dataclasses.dataclass
class TrainerConfig:
    learning_rate: float = 1e-3
    weight_decay: float = 1e-4
    #: schedule horizons count OPTIMIZER UPDATES — with accum_steps=k
    #: that is one per k train_steps, so express warmup/total in update
    #: units (train steps / k) when accumulating
    warmup_steps: int = 0
    total_steps: int = 10_000
    grad_clip: float = 1.0
    optimizer: str = "adamw"  # adamw | sgd | adafactor
    momentum: float = 0.9
    remat: bool = False  # wrap loss in jax.checkpoint
    #: gradient accumulation: apply the optimizer every k train_steps,
    #: averaging grads over the window (optax.MultiSteps) — the
    #: effective batch is k x the device batch at the same HBM footprint
    accum_steps: int = 1
    #: write step-series metrics every N steps when a SummaryWriter is
    #: attached (utils/summaries.py; mnist_with_summaries parity)
    summary_every: int = 10
    #: storage dtype for params (and therefore optimizer state — optax
    #: inits moments from the param dtype).  None keeps the model's
    #: init dtype (f32 master weights — the accuracy-safe default).
    #: jnp.bfloat16 halves param+moment HBM traffic AND removes the
    #: per-step f32→bf16 cast-copy swarm the ResNet trace shows
    #: saturating the schedule (PROFILE.md r5 trace section) — at the
    #: cost of bf16 weight-update rounding (no stochastic rounding on
    #: this path; use for BW probes and BN-robust convnets, not as the
    #: LM default).
    param_dtype: Any = None


def make_optimizer(cfg: TrainerConfig) -> optax.GradientTransformation:
    if cfg.optimizer not in ("adamw", "sgd", "adafactor"):
        raise ValueError(
            f"optimizer must be one of adamw|sgd|adafactor, got {cfg.optimizer!r}"
        )
    if cfg.warmup_steps > 0:
        sched = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, cfg.warmup_steps, max(cfg.total_steps, cfg.warmup_steps + 1)
        )
    else:
        sched = optax.constant_schedule(cfg.learning_rate)
    if cfg.optimizer == "sgd":
        opt = optax.sgd(sched, momentum=cfg.momentum)
    elif cfg.optimizer == "adafactor":
        # the TPU-era classic: factored second moments — optimizer
        # state is O(rows + cols) per matrix instead of O(rows * cols),
        # the memory-side win that made large T5-class pretraining fit
        opt = optax.adafactor(sched)
    else:
        # decay only matmul kernels — never norm scales/biases/embeddings'
        # 1-d params (standard transformer pretraining practice)
        def decay_mask(params):
            return jax.tree_util.tree_map(lambda p: jnp.ndim(p) > 1, params)

        opt = optax.adamw(sched, weight_decay=cfg.weight_decay, mask=decay_mask)
    if cfg.grad_clip and cfg.grad_clip > 0:
        opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip), opt)
    if cfg.accum_steps > 1:
        opt = optax.MultiSteps(opt, every_k_schedule=cfg.accum_steps)
    return opt


class Trainer:
    """Builds a sharded TrainState and a jitted, donated train step.

    `shardings="fsdp"` applies the auto-rule to params and opt state;
    `shardings="logical"` reads the model's logical-axis annotations
    (transformer family) and maps them through LOGICAL_RULES;
    `shardings=tree` uses an explicit NamedSharding tree for the whole
    TrainState.
    """

    def __init__(
        self,
        model,
        cfg: TrainerConfig,
        mesh: Mesh,
        loss_fn: LossFn,
        example_batch: Batch,
        init_args: Optional[Tuple] = None,
        shardings: Any = "fsdp",
        seed: int = 0,
        summary_writer: Optional[Any] = None,
        sync_ledger: Optional[Any] = None,
        grad_sync: str = "auto",
    ) -> None:
        from tf_operator_tpu.utils.metrics import StepSyncLedger, default_metrics

        if grad_sync not in ("auto", "flat", "hierarchical"):
            raise ValueError(
                f"grad_sync must be auto|flat|hierarchical, got {grad_sync!r}"
            )

        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.summary_writer = summary_writer
        #: every device→host fetch the trainer itself performs (summary
        #: scalar conversion) funnels through this ledger's resolve();
        #: the harness train loop passes its own so one ledger covers
        #: the whole run (utils/metrics.StepSyncLedger)
        self.sync_ledger = (
            sync_ledger
            if sync_ledger is not None
            else StepSyncLedger(metrics=default_metrics)
        )
        self._last_summary_time: Optional[float] = None
        self._last_summary_step = 0
        #: (step, metrics) parked by train_steps at an interval
        #: boundary, written at the START of the next window so the
        #: summary fetch never blocks on the window just dispatched
        self._pending_summary: Optional[Tuple[int, Dict]] = None
        #: host-side step counter — reading state.step would block on
        #: the device every step, defeating async dispatch
        self._host_step = 0
        self.tx = make_optimizer(cfg)
        self.batch_sharding = jax.tree_util.tree_map(
            lambda _: batch_sharding(mesh), example_batch
        )
        #: True on multi-process worlds whose mesh replicates batch
        #: shards across processes (tp/ep/sp-heavy meshes): disjoint
        #: per-process data is then UNSAFE through shard_batch — see
        #: shard_batch / shard_global_batch.  Derived from the sharding
        #: alone (mesh + spec), so it is decided at construction.
        self._batch_replicated = (
            jax.process_count() > 1 and self._sharding_replicates_across_processes()
        )
        init_rng = jax.random.PRNGKey(seed)
        train_rng = jax.random.PRNGKey(seed + 1)

        if init_args is None:
            init_args = (example_batch["image"],)

        def init_state() -> TrainState:
            variables = model.init(init_rng, *init_args, train=False)
            params = variables.pop("params")
            if cfg.param_dtype is not None:
                params = jax.tree_util.tree_map(
                    lambda p: p.astype(cfg.param_dtype)
                    if jnp.issubdtype(p.dtype, jnp.floating) else p,
                    params,
                )
            return TrainState.create(
                apply_fn=model.apply,
                params=params,
                tx=self.tx,
                rng=train_rng,
                model_state=dict(variables),
            )

        import flax.linen as nn

        self._rules = list(LOGICAL_RULES)
        abstract = jax.eval_shape(init_state)
        if shardings == "fsdp":
            replicated_tree = jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, PartitionSpec()), abstract
            )
            self.state_sharding = replicated_tree.replace(
                params=fsdp_shardings(abstract.params, mesh),
                opt_state=fsdp_shardings(abstract.opt_state, mesh),
            )
        elif shardings == "logical":
            from tf_operator_tpu.parallel.sharding import logical_shardings

            self.state_sharding = logical_shardings(abstract, mesh)
        else:
            self.state_sharding = shardings

        # -- multi-slice grad sync (ISSUE 14): when the mesh spans
        # slices, the cross-slice gradient reduction is routed through
        # parallel/collectives.py's two-stage hierarchical psum — the
        # DCN fabric sees 1/intra_slice_size of the bytes a flat psum
        # would move.  "auto" picks hierarchical iff slices > 1; "flat"
        # forces the legacy XLA-implicit sync (the A/B baseline the
        # bench section measures against).
        from tf_operator_tpu.parallel.mesh import slice_count

        self._slices = slice_count(mesh)
        if grad_sync == "auto":
            grad_sync = "hierarchical" if self._slices > 1 else "flat"
        self.grad_sync = grad_sync
        self.grad_sync_plan = None
        if grad_sync == "hierarchical":
            from tf_operator_tpu.parallel.collectives import (
                build_grad_sync_plan,
            )

            self.grad_sync_plan = build_grad_sync_plan(
                abstract.params, self.state_sharding.params, mesh
            )

        with mesh, nn.logical_axis_rules(self._rules):
            self.state: TrainState = jax.jit(init_state, out_shardings=self.state_sharding)()

        # ISSUE 20 device cost plane: the trainer's compiles register
        # with their K/eval/gen trigger classes, and the two standing
        # HBM components — weights and optimizer state — are accounted
        # per device the moment they exist (pure metadata: nbytes over
        # the sharded leaves, never a transfer)
        from tf_operator_tpu.utils.costplane import default_costplane

        self.costplane = default_costplane
        self.costplane.compiles.note("train.init_state", trigger="init")
        self.costplane.hbm.register_tree("weights", self.state.params)
        self.costplane.hbm.register_tree("optimizer", self.state.opt_state)

        self._step = self._build_step()

    # -- the hot path -------------------------------------------------------
    def _step_body(
        self, state: TrainState, batch: Batch
    ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """One train step as a PURE function — the traced body both the
        single-step jit and the fused K-step scan compile."""

        if self.grad_sync_plan is not None:
            return self._step_body_hierarchical(state, batch)
        loss_fn, remat = self.loss_fn, self.cfg.remat
        rng = jax.random.fold_in(state.rng, state.step)

        def loss_of(params):
            return loss_fn(params, state, batch, rng)

        if remat:
            loss_of = jax.checkpoint(loss_of)
        (loss, aux), grads = jax.value_and_grad(loss_of, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads)
        if aux.get("model_state") is not None:
            new_state = new_state.replace(model_state=aux["model_state"])
        metrics = dict(aux.get("metrics", {}))
        metrics["loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    def _step_body_hierarchical(
        self, state: TrainState, batch: Batch
    ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """The multi-slice step: loss/backward inside a shard_map that
        is MANUAL over the DCN axis (dp) and AUTO over every intra-slice
        axis, so the per-slice-replica gradients are explicit values and
        their cross-slice reduction goes through
        ``collectives.GradSyncPlan.apply`` (reduce-scatter over ICI →
        fragment-width psum over DCN → gather over ICI) instead of
        XLA's topology-blind full-width all-reduce.  The intra-slice
        batch axes (fsdp) stay auto, so XLA still inserts their ICI
        reductions — identical to the flat path's intra-slice half.

        Numerics: losses/grads match the flat path to float tolerance
        (mean-of-shard-means == global mean at equal shard sizes;
        tests/test_multislice.py pins allclose).  Dropout keys fold in
        the dp coordinate, so stochastic runs are valid but not
        bit-comparable to the flat program."""

        from jax.sharding import PartitionSpec as P

        from tf_operator_tpu.utils.jax_compat import shard_map_partial_auto

        plan = self.grad_sync_plan
        loss_fn, remat = self.loss_fn, self.cfg.remat
        mesh, dcn = self.mesh, plan.dcn_axis
        n_dcn = mesh.shape[dcn]
        auto = frozenset(ax for ax in mesh.axis_names if ax != dcn)

        def replica_step(st: TrainState, local_batch: Batch, rng_row):
            # per-replica dropout key, folded OUTSIDE the manual region
            # (axis_index lowers to PartitionId, which the partial-auto
            # partitioner refuses) and threaded in sharded over dp
            rng = rng_row[0]

            def loss_of(params):
                return loss_fn(params, st, local_batch, rng)

            if remat:
                loss_of = jax.checkpoint(loss_of)
            (loss, aux), grads = jax.value_and_grad(loss_of, has_aux=True)(
                st.params
            )
            grads = plan.apply(grads)
            grads = jax.tree_util.tree_map(lambda g: g / n_dcn, grads)
            loss = jax.lax.pmean(loss, dcn)
            metrics = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, dcn), dict(aux.get("metrics", {}))
            )
            mstate = aux.get("model_state")
            if mstate is not None:
                # BN running stats etc: average the replicas' views so
                # the carried state is replica-identical, like the flat
                # program's (non-float leaves pass through)
                mstate = jax.tree_util.tree_map(
                    lambda v: jax.lax.pmean(v, dcn)
                    if jnp.issubdtype(jnp.result_type(v), jnp.floating)
                    else v,
                    mstate,
                )
            return loss, metrics, mstate, grads

        base_rng = jax.random.fold_in(state.rng, state.step)
        replica_rngs = jax.vmap(
            lambda i: jax.random.fold_in(base_rng, i)
        )(jnp.arange(n_dcn))
        loss, metrics, new_model_state, grads = shard_map_partial_auto(
            replica_step,
            mesh=mesh,
            # pytree-prefix specs over the MANUAL axis only: the state
            # is dp-replicated, the batch and the rng rows split their
            # leading dim over dp; intra-slice shardings flow as auto
            in_specs=(P(), P(dcn), P(dcn)),
            out_specs=(P(), P(), P(), P()),
            auto=auto,
        )(state, batch, replica_rngs)
        new_state = state.apply_gradients(grads=grads)
        if new_model_state is not None:
            new_state = new_state.replace(model_state=new_model_state)
        metrics = dict(metrics)
        metrics["loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    def _record_dcn_traffic(self, n_steps: int) -> None:
        """Host-side per-dispatch accounting of the multi-slice grad
        sync (no device read): the plan's static bytes/collective
        counts per step × steps dispatched, onto the ledger's registry
        as the ``train_dcn_*`` families the lint gates pin."""

        plan = self.grad_sync_plan
        if plan is None:
            return
        m = getattr(self.sync_ledger, "metrics", None)
        if m is None:
            return
        m.inc(
            "train_dcn_bytes_total",
            float(plan.dcn_bytes * n_steps), fabric="dcn",
        )
        m.inc(
            "train_dcn_bytes_total",
            float(plan.ici_bytes * n_steps), fabric="ici",
        )
        m.inc(
            "train_dcn_collectives_total",
            float(plan.dcn_collectives * n_steps), fabric="dcn",
        )
        m.inc(
            "train_dcn_collectives_total",
            float(plan.ici_collectives * n_steps), fabric="ici",
        )

    def _build_step(self):
        return self.costplane.compiles.wrap(
            jax.jit(
                self._step_body,
                in_shardings=(self.state_sharding, self.batch_sharding),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            ),
            "train.step", trigger="K=1",
        )

    def _build_multi_step(self, k: int):
        """K steps fused into ONE compiled program: ``jax.lax.scan`` of
        the step body over the SAME batch, state threaded as carry.
        One host dispatch per K steps instead of K — on a tunneled
        platform (dispatch RTT >> device math) the steady-state training
        analogue of serving's fused admission (PROFILE.md "r6 dispatch
        ledger").  Metrics come back STACKED (leading axis k, one row
        per step) and stay on device — resolving them is the caller's
        (windowed, deferred) decision, not this program's.

        The carry (state) is donated; the batch is NOT — the fixed-batch
        loop reuses it across windows, and a live pipeline's batches are
        owned by the prefetch buffer."""

        body = self._step_body

        def multi(state: TrainState, batch: Batch):
            def scan_body(s, _):
                s2, metrics = body(s, batch)
                return s2, metrics

            return jax.lax.scan(scan_body, state, None, length=k)

        # each K class is its own compiled scan — exactly the
        # recompile family the compile-storm rule exists to catch
        # (a K-sweep harness bug would show up as train.multi_step
        # compiles with marching triggers)
        return self.costplane.compiles.wrap(
            jax.jit(
                multi,
                in_shardings=(self.state_sharding, self.batch_sharding),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            ),
            "train.multi_step", trigger=f"K={k}",
        )

    def train_step(self, batch: Batch) -> Dict[str, jax.Array]:
        import flax.linen as nn

        with self.mesh, nn.logical_axis_rules(self._rules):
            self.state, metrics = self._step(self.state, batch)
        self._host_step += 1
        self._record_dcn_traffic(1)
        if self.summary_writer is not None:
            self._maybe_write_summary(metrics)
        return metrics

    def train_steps(self, batch: Batch, k: int) -> Dict[str, jax.Array]:
        """Run ``k`` fused steps (one compiled scan, one dispatch) on a
        fixed device-resident batch; returns the per-step metrics
        STACKED along a leading axis of length k, as device arrays —
        the host does not wait on them.  Programs are cached per k (the
        step loop's final partial window compiles its own length once).
        ``k=1`` compiles a length-1 scan — semantically train_step, kept
        distinct so callers comparing the paths exercise both programs.

        Numerics: the scan compiles as its OWN program, so XLA may
        schedule/fuse the float math differently than the per-step
        program — same operations, not bit-pinned against train_step
        (measured ~1e-3 loss drift after 20 mnist steps on CPU).  The
        per-step K=1 harness path stays bit-identical to the legacy
        loop; use it when debugging numerics.
        """

        import flax.linen as nn

        if k < 1:
            raise ValueError(f"train_steps needs k >= 1, got {k}")
        if not hasattr(self, "_multi_step_cache"):
            self._multi_step_cache = {}
        fn = self._multi_step_cache.get(k)
        if fn is None:
            fn = self._multi_step_cache[k] = self._build_multi_step(k)
        with self.mesh, nn.logical_axis_rules(self._rules):
            # a summary parked by the PREVIOUS window is written first —
            # its arrays finished at least one window ago, so the
            # resolve is a pure fetch, not a stall on the window we are
            # about to dispatch (the same deferred discipline as the
            # harness loop's loss resolution)
            if getattr(self, "_pending_summary", None) is not None:
                at_step, pending = self._pending_summary
                self._pending_summary = None
                self._write_summary(pending, at_step=at_step)
            self.state, metrics = fn(self.state, batch)
        self._host_step += k
        self._record_dcn_traffic(k)
        if self.summary_writer is not None:
            every = max(1, self.cfg.summary_every)
            if self._host_step // every != (self._host_step - k) // every:
                # the interval boundary fell inside this window: PARK
                # the window's LAST step (index -1 of the stacked axis)
                # for the next call — writing now would block on the
                # window just dispatched.  A run's final parked summary
                # is dropped if no further window runs (periodic
                # diagnostics, not the record of truth).
                self._pending_summary = (
                    self._host_step,
                    jax.tree_util.tree_map(lambda v: v[-1], metrics),
                )
        return metrics

    def _build_eval_step(self):
        import inspect

        loss_fn = self.loss_fn
        # inference mode when the loss supports it (all shipped losses
        # take train=; user losses without the kwarg run as written)
        try:
            takes_train = "train" in inspect.signature(loss_fn).parameters
        except (TypeError, ValueError):
            takes_train = False

        def step(state: TrainState, batch: Batch) -> Dict[str, jax.Array]:
            # fixed rng: deterministic; with takes_train the model runs
            # deterministic anyway (no dropout, BN running stats)
            kw = {"train": False} if takes_train else {}
            loss, aux = loss_fn(state.params, state, batch, jax.random.PRNGKey(0), **kw)
            metrics = dict(aux.get("metrics", {}))
            metrics["loss"] = loss
            return metrics

        return self.costplane.compiles.wrap(
            jax.jit(
                step,
                in_shardings=(self.state_sharding, self.batch_sharding),
                out_shardings=None,
            ),
            "train.eval_step", trigger="resharded",
        )

    def eval_step(self, batch: Batch) -> Dict[str, jax.Array]:
        """Forward-only metrics on a held-out batch: no grads, no state
        update, deterministic.  Same sharding as train_step.

        The compiled step is cached keyed on the CURRENT sharding trees
        (ADVICE r3): swapping in differently-sharded state/batch
        shardings rebuilds instead of silently running with stale
        in_shardings.  The key holds strong references and compares by
        identity — id()-based keys could alias a GC'd tree's reused
        address."""

        import flax.linen as nn

        prev = getattr(self, "_eval_step_key", None)
        if (
            prev is None
            or prev[0] is not self.state_sharding
            or prev[1] is not self.batch_sharding
        ):
            self._eval_step_fn = self._build_eval_step()
            self._eval_step_key = (self.state_sharding, self.batch_sharding)
        with self.mesh, nn.logical_axis_rules(self._rules):
            return self._eval_step_fn(self.state, batch)

    def generate(
        self,
        prompt_ids,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        rng: Optional[Any] = None,
    ):
        """Sharded autoregressive generation with the LIVE TrainState
        params — no host gather, no replication.  The decode graph runs
        under the mesh + logical rules, so tp-sharded projections stay
        sharded and XLA inserts the collectives (the scalable story:
        params that never fit one host still decode).  The whole call
        is jitted once per (prompt shape, max_new_tokens, sampling
        config) and cached — repeat calls are a single XLA program."""

        import flax.linen as nn

        from tf_operator_tpu.models.decode import generate

        if temperature != 0.0 and rng is None:
            raise ValueError("temperature sampling needs an explicit rng key")
        if rng is None:
            rng = jax.random.PRNGKey(0)  # greedy: never consumed meaningfully
        if not hasattr(self, "_gen_cache"):
            from collections import OrderedDict

            self._gen_cache = OrderedDict()
        key = (tuple(prompt_ids.shape), max_new_tokens, temperature, top_k)
        if key not in self._gen_cache:
            # LRU-bounded (ADVICE r3): many distinct prompt shapes must
            # not accumulate compiled programs for the process lifetime.
            # A server facing arbitrary lengths should use
            # models/decode.ChunkedServingDecoder instead (logarithmic
            # program count by construction).
            while len(self._gen_cache) >= 16:
                self._gen_cache.popitem(last=False)
            # trigger is the prompt-shape class only: sampling config
            # is caller-influenced and stays out of the label set (the
            # ring event's shapes carry the rest)
            self._gen_cache[key] = self.costplane.compiles.wrap(
                jax.jit(
                    lambda params, prompt, r: generate(
                        self.model, params, prompt, max_new_tokens,
                        temperature=temperature, top_k=top_k, rng=r,
                    )
                ),
                "train.generate",
                trigger=f"shape={'x'.join(str(int(s)) for s in prompt_ids.shape)}",
            )
        else:
            self._gen_cache.move_to_end(key)
        with self.mesh, nn.logical_axis_rules(self._rules):
            return self._gen_cache[key](self.state.params, prompt_ids, rng)

    def evaluate(self, batches) -> Dict[str, float]:
        """Mean metrics over an iterable of (already host-side) batches."""

        totals: Dict[str, float] = {}
        n = 0
        for batch in batches:
            m = self.eval_step(self._shard_input(batch))
            for k, v in m.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            n += 1
        if not n:
            raise ValueError("evaluate() got an empty batch iterable")
        return {k: v / n for k, v in totals.items()}

    def _maybe_write_summary(self, metrics: Dict[str, jax.Array]) -> None:
        """Every cfg.summary_every steps: scalar metrics + steps/sec to
        the attached SummaryWriter.  The device→host fetch synchronises,
        so it runs at an interval, never per step (the interval check
        uses the host-side counter), and is routed through the sync
        ledger's resolve() — the summary cadence shows up in the
        ``train_sync_*`` accounting instead of hiding from it."""

        step = self._host_step
        every = max(1, self.cfg.summary_every)
        if step % every:
            return
        self._write_summary(metrics)

    def _write_summary(
        self, metrics: Dict[str, jax.Array], at_step: Optional[int] = None
    ) -> None:
        """Unconditional summary write (train_steps calls this with the
        PREVIOUS window's parked metrics and their boundary step, where
        _host_step need not be an exact multiple of summary_every)."""

        step = self._host_step if at_step is None else at_step
        now = time.perf_counter()
        host = self.sync_ledger.resolve("summary", metrics)
        scalars = {}
        for k, v in host.items():
            try:
                scalars[k] = float(v)
            except (TypeError, ValueError):
                continue
        if self._last_summary_time is not None:
            scalars["steps_per_sec"] = (step - self._last_summary_step) / (
                now - self._last_summary_time
            )
        # republish the checkpointer's durability stamp into the series:
        # the gauge is PROCESS scope (a subprocess-pod trainer's registry
        # never reaches the operator), the summary series is the one
        # channel that already crosses that boundary — the health
        # rollup's lastCheckpointAgeSeconds and the autoscaler's resize
        # gate read it back via utils/summaries.latest_checkpoint_time
        mreg = getattr(self.sync_ledger, "metrics", None)
        if mreg is not None:
            ckpt = mreg.gauge("checkpoint_last_success_unix")
            if ckpt > 0:
                scalars["checkpoint_time_unix"] = ckpt
        self._last_summary_time = now
        self._last_summary_step = step
        self.summary_writer.write(step, **scalars)

    def _sharding_replicates_across_processes(self) -> bool:
        """True when some batch shard spans devices of MULTIPLE
        processes — the layout where feeding disjoint per-process data
        through shard_batch is silently wrong (XLA assumes replicas
        are bit-identical; different hosts' rows are not).  A property
        of mesh + PartitionSpec only, probed with a synthetic
        mesh-size-divisible shape (real batch shapes need not divide
        the global partition count on this side of the boundary)."""

        s = jax.tree_util.tree_leaves(self.batch_sharding)[0]
        groups: dict = {}
        for dev, idx in s.devices_indices_map((s.mesh.size,)).items():
            key = (idx[0].start, idx[0].stop)
            groups.setdefault(key, set()).add(dev.process_index)
        return any(len(procs) > 1 for procs in groups.values())

    def _shard_input(self, batch: Batch) -> Batch:
        """Internal sharder for evaluate()/benchmark(): local-shard
        semantics on data-parallel meshes, identical-global semantics
        on replicating meshes (the only correct interpretation there —
        callers on tp/ep/sp-spanning worlds must feed every process
        the same batch)."""

        if self._batch_replicated:
            return self.shard_global_batch(batch)
        return self.shard_batch(batch)

    def shard_batch(self, batch: Batch) -> Batch:
        """Lay the batch out on the mesh.

        Single-process: ``batch`` is the global batch.  Multi-process
        (jax.distributed): each process passes its *local shard* (its
        rows of the batch axis) and the returned arrays are global —
        the multi-host path the operator's examples use.

        Raises when the mesh replicates batch shards across processes
        (dp·fsdp shards fewer than processes — e.g. a tp- or ep-heavy
        mesh): disjoint local data would be treated as bit-identical
        replicas by XLA's collectives, silently diverging params
        across hosts.  Pass an IDENTICAL global batch through
        `shard_global_batch` instead, or reshape the mesh so every
        process holds a distinct batch shard.
        """

        with self.mesh:
            if jax.process_count() == 1:
                return jax.device_put(batch, self.batch_sharding)
            if self._batch_replicated:
                raise ValueError(
                    "shard_batch: this mesh replicates batch shards across "
                    "processes (batch shards < processes), so per-process "
                    "DISJOINT data would silently diverge — use "
                    "shard_global_batch with an identical global batch, or "
                    "give the mesh a dp/fsdp extent >= the process count"
                )
            return jax.tree_util.tree_map(
                lambda x, s: jax.make_array_from_process_local_data(s, x),
                batch,
                self.batch_sharding,
            )

    def shard_global_batch(self, batch: Batch) -> Batch:
        """Multi-process-safe layout from an *identical global* batch.

        Use instead of shard_batch when the mesh has replicating axes
        for the batch (e.g. tp): every process passes the same global
        batch and each device receives exactly its shard — replicas end
        up bit-identical, as XLA's collectives require.
        """

        with self.mesh:
            if jax.process_count() == 1:
                return jax.device_put(batch, self.batch_sharding)
            return jax.tree_util.tree_map(
                lambda x, s: jax.make_array_from_callback(
                    x.shape, s, lambda idx: x[idx]
                ),
                batch,
                self.batch_sharding,
            )

    # -- measurement --------------------------------------------------------
    def _slope_time(self, run_steps, steps: int) -> float:
        """Two-point SLOPE timing: time an n1-step window and an
        n2-step window (each ending in a data-dependent host fetch via
        hard_sync) and divide the difference by the extra steps.  Every
        fixed cost — dispatch latency, the tunnel's ~66 ms host↔device
        round trip, sync tails, the missing final backward after the
        loss fetch — appears in BOTH windows and cancels, so the slope
        is the honest per-step device time on any platform (PROFILE.md
        "timing honesty", 2026-08-01: one-window timing mis-measured
        flash-path steps by -65%/+25% depending on sync primitive).

        `run_steps(n)` runs n train steps and returns the last metrics.
        Consumes exactly `steps` measured steps total (n1 + n2 ==
        steps), so finite batch iterators sized to warmup+steps still
        suffice.  Returns seconds per step, always positive."""

        def window(n: int) -> float:
            t0 = time.perf_counter()
            hard_sync(run_steps(n))
            return time.perf_counter() - t0

        if steps < 3:
            # no room for two distinct windows within the contract:
            # single-window average (fixed costs included — biased
            # high, but the caller asked for a 1-2 step measurement)
            n = max(1, steps)
            return window(n) / n
        n1 = max(1, steps // 6)
        n2 = steps - n1
        t1 = window(n1)
        t2 = window(n2)
        dt_step = (t2 - t1) / (n2 - n1)
        if dt_step <= 0:
            # tiny models under timing jitter: the two windows can
            # invert (per-step time below scheduler noise).  Fall back
            # to the larger window's average — biased high by the
            # fixed costs, but always positive.
            dt_step = t2 / n2
        return dt_step

    def benchmark_stream(
        self, batches, steps: int = 20, warmup: int = 3
    ) -> Dict[str, float]:
        """Like benchmark, but pulling each step's batch from an
        iterator of device-resident global batches (the live input
        pipeline, e.g. data.device_prefetch) — input loading and
        host→device transfer are inside the measured window."""

        m = None
        n_batch = 0
        for _ in range(warmup):
            batch = next(batches)
            n_batch = next(iter(batch.values())).shape[0]
            m = self.train_step(batch)
        if m is not None:
            hard_sync(m)

        def run_steps(n: int):
            nonlocal n_batch
            mm = None
            for _ in range(n):
                batch = next(batches)
                n_batch = next(iter(batch.values())).shape[0]
                mm = self.train_step(batch)
            return mm

        dt_step = self._slope_time(run_steps, steps)
        return {
            "steps_per_sec": 1.0 / dt_step,
            "examples_per_sec": n_batch / dt_step,
            "step_ms": 1e3 * dt_step,
        }

    def benchmark(self, batch: Batch, steps: int = 20, warmup: int = 3) -> Dict[str, float]:
        """Slope-timed steps/sec on a fixed device-resident batch —
        see _slope_time for the measurement protocol."""

        batch = self._shard_input(batch)
        m = None
        for _ in range(warmup):
            m = self.train_step(batch)
        if m is not None:
            hard_sync(m)

        def run_steps(n: int):
            mm = None
            for _ in range(n):
                mm = self.train_step(batch)
            return mm

        dt_step = self._slope_time(run_steps, steps)
        n_batch = next(iter(batch.values())).shape[0]
        return {
            "steps_per_sec": 1.0 / dt_step,
            "examples_per_sec": n_batch / dt_step,
            "step_ms": 1e3 * dt_step,
        }


def cross_entropy_loss(
    params, state: TrainState, batch: Batch, rng, train: bool = True
) -> Tuple[jax.Array, Dict]:
    """Supervised classification loss for models without mutable state
    (mnist CNN)."""

    logits = state.apply_fn(
        {"params": params}, batch["image"], train=train, rngs={"dropout": rng}
    )
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["label"]
    ).mean()
    acc = (logits.argmax(-1) == batch["label"]).mean()
    return loss, {"metrics": {"accuracy": acc}}


def batchnorm_cross_entropy_loss(
    params, state: TrainState, batch: Batch, rng, train: bool = True
) -> Tuple[jax.Array, Dict]:
    """Classification loss for BatchNorm models (ResNet): threads the
    batch_stats collection through the step.  train=False evaluates
    with the RUNNING statistics and mutates nothing."""

    if train:
        logits, new_model_state = state.apply_fn(
            {"params": params, **state.model_state},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": rng},
        )
    else:
        logits = state.apply_fn(
            {"params": params, **state.model_state}, batch["image"], train=False
        )
        new_model_state = None
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["label"]
    ).mean()
    acc = (logits.argmax(-1) == batch["label"]).mean()
    return loss, {"metrics": {"accuracy": acc}, "model_state": new_model_state}
