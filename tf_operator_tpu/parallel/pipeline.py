"""Pipeline parallelism: GPipe schedule over the ``pp`` mesh axis.

Parity: SURVEY.md §2b marks PP absent from the reference ("optional;
shard_map stages or GSPMD pipelining") — this closes the row the
TPU-native way: stage parameters live sharded over the ``pp`` axis, and
one jitted computation runs the classic GPipe schedule — S stages × M
microbatches over S+M-1 ticks — entirely inside ``shard_map``:

- every device applies ITS stage block to the microbatch it currently
  holds (all devices busy once the pipeline fills);
- activations move stage→stage with a single ``lax.ppermute`` per tick
  (point-to-point neighbour traffic: the only collective in the hot
  loop, so the pp axis can ride the slowest links);
- ``lax.scan`` drives the ticks — compiler-friendly control flow, one
  trace, no Python-level loop in the compiled artifact;
- autodiff straight through (ppermute and scan are differentiable), so
  ``jax.grad`` of a pipelined loss yields the standard GPipe backward
  schedule without hand-written reverse plumbing.

Composes with dp/fsdp on the batch dimension (the microbatch dimension
is per-shard) and with tp inside a stage.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.utils.jax_compat import shard_map_unchecked as shard_map

from tf_operator_tpu.parallel.mesh import AXIS_PP

#: stage_fn(stage_params, x) -> y; same pytree structure for x and y
StageFn = Callable[[Any, jax.Array], jax.Array]


def stack_stage_params(per_stage_params) -> Any:
    """[params_stage0, params_stage1, ...] -> one pytree with a leading
    stage dimension on every leaf (the pp-sharded layout)."""

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_sharding_spec() -> P:
    """PartitionSpec for stacked stage params: leading dim over pp."""

    return P(AXIS_PP)


def pipeline_apply(
    stage_fn: StageFn,
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    microbatches: int,
    axis: str = AXIS_PP,
    batch_axes=None,
) -> jax.Array:
    """Run ``x`` through S pipelined stages; returns the final output.

    ``stacked_params``: every leaf has leading dim S (use
    ``stack_stage_params``), laid out ``P(axis)``; ``x``: [batch, ...],
    split into ``microbatches`` equal microbatches along dim 0.
    ``stage_fn`` must be shape-preserving (the activation that moves
    between stages).  ``batch_axes`` names the mesh axes the batch dim
    is sharded over (e.g. ``("dp", "fsdp")``) so pp composes with data
    parallelism — each dp shard runs its own microbatch stream.
    """

    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % microbatches:
        raise ValueError(f"batch {batch} not divisible by microbatches {microbatches}")
    mb = batch // microbatches
    if batch_axes:
        axes = (batch_axes,) if isinstance(batch_axes, str) else tuple(batch_axes)
        dp_size = 1
        for a in axes:
            dp_size *= mesh.shape[a]
        if mb % dp_size:
            raise ValueError(
                f"microbatch rows ({mb}) not divisible by the batch-axis "
                f"mesh size ({dp_size}); batch must be a multiple of "
                f"microbatches x {'x'.join(axes)}"
            )

    # [M, mb, ...] microbatch stream
    xs = x.reshape(microbatches, mb, *x.shape[1:])

    def per_device(params_local, xs_local):
        # shard_map hands each device its own stage block with the
        # (now size-1) stage dim still attached
        params_me = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis)
        n_ticks = microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            held = carry  # [mb, ...] activation this device holds
            # stage 0 ingests microbatch t (clamped: beyond M it feeds
            # garbage that never reaches a valid output slot)
            feed = xs_local[jnp.minimum(t, microbatches - 1)]
            inp = jnp.where(stage == 0, feed, held)
            out = stage_fn(params_me, inp)
            nxt = jax.lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = jax.lax.scan(tick, jnp.zeros_like(xs_local[0]), jnp.arange(n_ticks))
        # microbatch m leaves the last stage at tick m + S - 1
        ys = outs[n_stages - 1 :]
        # only the last stage holds real outputs: zero everyone else
        # and share via psum (activations are small relative to FLOPs)
        ys = jnp.where(stage == n_stages - 1, ys, jnp.zeros_like(ys))
        return jax.lax.psum(ys, axis)

    stream_spec = P(None, batch_axes)  # [M, mb, ...]; mb over dp/fsdp
    out = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(stage_sharding_spec(), stream_spec),
        out_specs=stream_spec,
    )(stacked_params, xs)
    return out.reshape(batch, *out.shape[2:])


def pipelined(
    stage_fn: StageFn,
    mesh: Mesh,
    microbatches: int,
    axis: str = AXIS_PP,
    batch_axes=None,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Partial-application convenience: a (params, x) -> y callable."""

    return partial(
        pipeline_apply,
        stage_fn,
        mesh=mesh,
        microbatches=microbatches,
        axis=axis,
        batch_axes=batch_axes,
    )
