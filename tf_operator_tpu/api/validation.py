"""Spec validation.

Parity: the reference's ``ValidateV1TFJobSpec`` (SURVEY.md §2 "Validation",
expected upstream ``pkg/apis/tensorflow/validation/validation.go``):
reject specs with no replicas, unknown replica types, a missing main
container, or more than one chief/master.

TPU additions: TPU_SLICE replicas must carry a parseable topology and may
not coexist with PS replicas (parameter-server traffic has no ICI analogue;
SURVEY.md §2b row "Parameter-server").
"""

from __future__ import annotations

import math
import re
from typing import List

from tf_operator_tpu.api.types import (
    AUTOSCALING_MODES,
    CHIEF_LIKE,
    DEFAULT_CONTAINER_NAME,
    PRIORITY_CLASSES,
    SIGNAL_KINDS,
    ReplicaType,
    TPUJob,
)

#: DNS-1123 subdomain, as Kubernetes enforces for object names — the
#: name feeds pod/service DNS names and TF_CONFIG hostnames, so this is
#: a correctness (and HTML/JSON-safety) constraint, not cosmetics
_DNS1123 = re.compile(r"^[a-z0-9]([a-z0-9-]{0,51}[a-z0-9])?$")


class ValidationError(ValueError):
    """Raised when a TPUJob spec is rejected.  Carries every problem found."""

    def __init__(self, problems: List[str]):
        self.problems = problems
        super().__init__("; ".join(problems))


#: generations whose accelerator-type names count TensorCores (2 per
#: chip), per the public naming convention: "v4-8" is a 4-chip slice,
#: while "v5e-16"/"v5litepod-16" is a 16-chip slice.  Getting this
#: wrong compiles GKE node selectors no nodepool matches (VERDICT r4
#: weak #3).
_CORE_COUNTED_GENERATIONS = frozenset({"v2", "v3", "v4", "v5p"})


def parse_tpu_topology(topology: str) -> int:
    """Return the chip count of a slice topology string.

    Accepts accelerator-type names — "v5e-16" / "v5litepod-16"
    (generation-chips) and "v4-8" / "v5p-8" (generation-TensorCores,
    2 cores per chip) — and "2x4" / "4x4x4" mesh-dim style.  Raises
    ValueError otherwise.
    """

    t = topology.strip().lower()
    if not t:
        raise ValueError("empty topology")
    if "x" in t and all(p.isdigit() for p in t.split("x")):
        n = 1
        for p in t.split("x"):
            n *= int(p)
        if n < 1:
            raise ValueError(f"degenerate TPU topology {topology!r}: 0 chips")
        return n
    if "-" in t:
        gen, _, count = t.rpartition("-")
        if gen and count.isdigit():
            n = int(count)
            if n < 1:
                raise ValueError(
                    f"degenerate TPU topology {topology!r}: 0 chips"
                )
            if gen in _CORE_COUNTED_GENERATIONS:
                if n % 2:
                    raise ValueError(
                        f"{topology!r}: {gen} accelerator names count "
                        "TensorCores (2 per chip); an odd count is invalid"
                    )
                return n // 2
            return n
    raise ValueError(f"unparseable TPU topology {topology!r}")


#: chips per TPU host VM — 4 across v4/v5e/v5p/v6e pod slices (public
#: GKE topology: v5litepod-16 = 4 VMs x 4 chips).
CHIPS_PER_HOST = 4


def slice_hosts(topology: str) -> int:
    """Number of host VMs backing one slice of this topology.

    The multi-host expansion contract (bootstrap/tpu_env.py): a slice
    whose topology spans H > 1 hosts runs as H pods — one per host VM,
    exactly as GKE schedules one pod per TPU VM — each with
    TPU_WORKER_ID = host and the full slice host list.
    """

    chips = parse_tpu_topology(topology)
    return max(1, -(-chips // CHIPS_PER_HOST))


def validate(job: TPUJob) -> None:
    """Raise ValidationError if the spec is invalid.  No-op otherwise."""

    problems: List[str] = []
    spec = job.spec

    if not job.metadata.name:
        problems.append("metadata.name is required")
    elif not _DNS1123.match(job.metadata.name):
        problems.append(
            "metadata.name must be a DNS-1123 label (lowercase alphanumerics"
            " and '-', at most 52 chars, to leave room for replica suffixes)"
        )
    if job.metadata.namespace and not _DNS1123.match(job.metadata.namespace):
        problems.append("metadata.namespace must be a DNS-1123 label")

    if not spec.replica_specs:
        problems.append("spec.replicaSpecs must contain at least one replica type")

    for rtype, rspec in spec.replica_specs.items():
        if not isinstance(rtype, ReplicaType):
            problems.append(f"unknown replica type {rtype!r}")
            continue
        prefix = f"replicaSpecs[{rtype.value}]"
        if rspec.replicas is not None and rspec.replicas < 0:
            problems.append(f"{prefix}.replicas must be >= 0")
        if rspec.hosts_per_replica is not None:
            # admission must reject what pod_count() would crash on
            if (
                not isinstance(rspec.hosts_per_replica, int)
                or isinstance(rspec.hosts_per_replica, bool)
                or rspec.hosts_per_replica < 1
            ):
                problems.append(
                    f"{prefix}.hostsPerReplica must be an integer >= 1"
                )
            elif rtype is not ReplicaType.TPU_SLICE:
                problems.append(
                    f"{prefix}.hostsPerReplica is only valid for TPUSlice replicas"
                )
        main = rspec.template.main_container(DEFAULT_CONTAINER_NAME)
        if main is None:
            problems.append(
                f"{prefix}: template must contain a container named "
                f"{DEFAULT_CONTAINER_NAME!r}"
            )
        elif not (main.command or main.args or main.image):
            problems.append(f"{prefix}: main container needs a command, args, or image")
        if rtype in CHIEF_LIKE:
            count = 1 if rspec.replicas is None else rspec.replicas
            if count > 1:
                problems.append(f"{prefix}.replicas must be <= 1 for chief/master")
        if rtype is ReplicaType.TPU_SLICE:
            try:
                parse_tpu_topology(rspec.tpu_topology)
            except ValueError as e:
                problems.append(f"{prefix}.tpuTopology: {e}")

    if ReplicaType.CHIEF in spec.replica_specs and ReplicaType.MASTER in spec.replica_specs:
        problems.append("spec may not contain both Chief and Master replicas")

    if (
        ReplicaType.TPU_SLICE in spec.replica_specs
        and ReplicaType.PS in spec.replica_specs
    ):
        problems.append(
            "TPUSlice replicas cannot be combined with PS replicas: "
            "parameter-server traffic has no ICI analogue (use FSDP-style "
            "sharding instead; SURVEY.md §2b)"
        )

    if spec.autoscaling is not None:
        _validate_autoscaling(spec, problems)

    if spec.scheduling is not None:
        _validate_scheduling(spec, problems)

    if problems:
        raise ValidationError(problems)


def _finite_nonneg(v) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v) and v >= 0


def _validate_autoscaling(spec, problems: List[str]) -> None:
    """Structural checks on ``spec.autoscaling`` — admission must
    reject what the autoscaler's evaluation loop would otherwise act
    nonsensically on (negative bounds, unknown modes, an empty signal
    list that could never trigger).  Whether a bound ALERT name exists
    is an engine-runtime property the static lint gate covers for the
    stock policy set (tests/test_autoscaling_lint.py)."""

    seen_types = set()
    for i, pol in enumerate(spec.autoscaling.policies):
        prefix = f"autoscaling.policies[{i}]"
        if not isinstance(pol.replica_type, ReplicaType):
            problems.append(f"{prefix}: unknown replica type {pol.replica_type!r}")
            continue
        if pol.replica_type not in spec.replica_specs:
            problems.append(
                f"{prefix}: replicaType {pol.replica_type.value} has no "
                "replica spec to scale"
            )
        if pol.replica_type in CHIEF_LIKE:
            problems.append(
                f"{prefix}: chief/master replicas cannot be autoscaled"
            )
        if pol.replica_type in seen_types:
            problems.append(
                f"{prefix}: duplicate policy for {pol.replica_type.value}"
            )
        seen_types.add(pol.replica_type)
        if pol.mode not in AUTOSCALING_MODES:
            problems.append(
                f"{prefix}.mode must be one of {AUTOSCALING_MODES}, "
                f"got {pol.mode!r}"
            )
        if not (
            isinstance(pol.min_replicas, int)
            and isinstance(pol.max_replicas, int)
            and 0 <= pol.min_replicas <= pol.max_replicas
            and pol.max_replicas >= 1
        ):
            problems.append(
                f"{prefix}: need 0 <= minReplicas <= maxReplicas "
                f"(got {pol.min_replicas!r}..{pol.max_replicas!r})"
            )
        if not (isinstance(pol.step, int) and pol.step >= 1):
            problems.append(f"{prefix}.step must be an integer >= 1")
        if not _finite_nonneg(pol.cooldown_seconds):
            problems.append(f"{prefix}.cooldownSeconds must be finite and >= 0")
        if not _finite_nonneg(pol.stabilization_seconds):
            problems.append(
                f"{prefix}.stabilizationSeconds must be finite and >= 0"
            )
        if not (
            isinstance(pol.hysteresis_ratio, (int, float))
            and math.isfinite(pol.hysteresis_ratio)
            and 0 < pol.hysteresis_ratio <= 1
        ):
            problems.append(f"{prefix}.hysteresisRatio must be in (0, 1]")
        if not (
            _finite_nonneg(pol.max_checkpoint_age_seconds)
            and pol.max_checkpoint_age_seconds > 0
        ):
            problems.append(
                f"{prefix}.maxCheckpointAgeSeconds must be finite and > 0"
            )
        if not pol.signals:
            problems.append(f"{prefix}.signals must bind at least one signal")
        for j, sig in enumerate(pol.signals):
            spre = f"{prefix}.signals[{j}]"
            if sig.kind not in SIGNAL_KINDS:
                problems.append(
                    f"{spre}.kind must be one of {SIGNAL_KINDS}, got {sig.kind!r}"
                )
            if not sig.name:
                problems.append(f"{spre}.name is required")
            if sig.kind == "gauge" and not (
                isinstance(sig.threshold, (int, float))
                and math.isfinite(sig.threshold)
            ):
                problems.append(f"{spre}.threshold must be finite")


def _validate_scheduling(spec, problems: List[str]) -> None:
    """Structural checks on ``spec.scheduling`` — the fleet scheduler
    (controller/scheduler.py) keys its queue/quota accounting on these
    fields, so admission must reject shapes the queue cannot rank.
    Quota *limits* are cluster operator config (Scheduler.set_quota),
    not part of the job manifest, so there is nothing numeric here."""

    sched = spec.scheduling
    if sched.priority_class and sched.priority_class not in PRIORITY_CLASSES:
        problems.append(
            "scheduling.priorityClass must be one of "
            f"{PRIORITY_CLASSES}, got {sched.priority_class!r}"
        )
    if sched.quota_group and not _DNS1123.match(sched.quota_group):
        # the group name joins the namespace in the quota key and is
        # exported as a metric label — same DNS-1123 hygiene as names
        problems.append(
            "scheduling.quotaGroup must be a DNS-1123 label, got "
            f"{sched.quota_group!r}"
        )
