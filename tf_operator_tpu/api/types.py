"""TPUJob API types — the declarative job schema.

Parity: the reference's CRD schema (SURVEY.md §2 "TFJob API types",
expected upstream ``pkg/apis/tensorflow/v1/types.go`` and the shared
``pkg/apis/common/v1/types.go``).  The reference expresses these as Go
structs consumed by Kubernetes API machinery; here they are frozen-ish
dataclasses consumed by the reconciler and serialisable to/from plain
dicts (the CRD-yaml equivalent).

TPU-first addition: ``ReplicaType.TPU_SLICE`` — a replica type whose unit
of allocation is an *atomic TPU slice* (e.g. v5e-16): it either exists
whole or not at all, which is the TPU-native generalisation of the
reference's gang-scheduled pod groups (SURVEY.md §3.4).
"""

from __future__ import annotations

import copy
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class ReplicaType(str, enum.Enum):
    """Roles a replica can play in a distributed training job.

    Mirrors the reference's TFReplicaType consts (chief/master/ps/worker/
    evaluator, SURVEY.md §2) plus the TPU-native ``TPU_SLICE``.
    """

    CHIEF = "Chief"
    MASTER = "Master"  # legacy alias for CHIEF in the reference API
    PS = "PS"
    WORKER = "Worker"
    EVALUATOR = "Evaluator"
    TPU_SLICE = "TPUSlice"

    @property
    def lower_name(self) -> str:
        """Lowercased role name for DNS-safe pod/service names."""
        return self.value.lower()

    @classmethod
    def from_str(cls, s: str) -> "ReplicaType":
        t = _REPLICA_TYPE_BY_LOWER.get(s.lower())
        if t is None:
            raise ValueError(f"unknown replica type: {s!r}")
        return t


_REPLICA_TYPE_BY_LOWER = {t.value.lower(): t for t in ReplicaType}


#: Replica types that count as "the chief" for success-policy purposes.
CHIEF_LIKE: Tuple[ReplicaType, ...] = (ReplicaType.CHIEF, ReplicaType.MASTER)

#: Deterministic ordering for reconcile loops and cluster-spec generation
#: (the reference iterates replica types sorted; SURVEY.md §3.2).
REPLICA_TYPE_ORDER: Tuple[ReplicaType, ...] = (
    ReplicaType.CHIEF,
    ReplicaType.MASTER,
    ReplicaType.PS,
    ReplicaType.WORKER,
    ReplicaType.EVALUATOR,
    ReplicaType.TPU_SLICE,
)


class RestartPolicy(str, enum.Enum):
    """Per-replica restart policy (SURVEY.md §2 "Common API types")."""

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    EXIT_CODE = "ExitCode"
    NEVER = "Never"


class CleanPodPolicy(str, enum.Enum):
    """What to delete when the job reaches a terminal state."""

    RUNNING = "Running"  # delete only still-running replicas (kills PS)
    ALL = "All"
    NONE = "None"


class SuccessPolicy(str, enum.Enum):
    """When a job counts as Succeeded (SURVEY.md §2 "TFJob API types").

    DEFAULT: the chief (or worker-0 if no chief) exiting 0 ends the job.
    ALL_WORKERS: every worker must succeed.
    """

    DEFAULT = ""
    ALL_WORKERS = "AllWorkers"


class JobConditionType(str, enum.Enum):
    """Job condition types (SURVEY.md §2 "Common API types").

    ``DEGRADED`` is ours, not the reference's: it is NOT a phase — it
    coexists with Running (a job can be running AND burning its SLO
    budget) and is set/cleared by the health rollup
    (controller/reconciler.py) from the alert engine's firing set
    (utils/alerts.py).  Reason ``SLOViolation`` when a burn-rate rule
    fires, ``HealthDegraded`` for threshold rules.
    """

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DEGRADED = "Degraded"
    # Fleet-scheduler conditions (controller/scheduler.py) — like
    # DEGRADED these are NOT phases: QUEUED marks a gang waiting for
    # capacity/quota, PREEMPTED marks a job whose slices were reclaimed
    # for a higher-priority gang, RESUMED marks a previously-preempted
    # job running again from its checkpoint.  All three coexist with
    # the phase conditions and are set/cleared by the reconciler's
    # scheduling gate.
    QUEUED = "Queued"
    PREEMPTED = "Preempted"
    RESUMED = "Resumed"


class PodPhase(str, enum.Enum):
    """Replica ("pod") lifecycle phases, as surfaced by cluster backends."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


# ---------------------------------------------------------------------------
# Spec objects
# ---------------------------------------------------------------------------


@dataclass
class Port:
    name: str
    container_port: int

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "containerPort": self.container_port}


@dataclass
class Container:
    """The command a replica runs — the pod-template core.

    The reference requires a container literally named ``tensorflow``
    (SURVEY.md §2 "Validation"); we keep that as the default name for
    spec-level compatibility while accepting any name the validator is
    configured for.
    """

    name: str = "tensorflow"
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    ports: List[Port] = field(default_factory=list)
    resources: Dict[str, Any] = field(default_factory=dict)
    working_dir: str = ""

    def port_named(self, name: str) -> Optional[Port]:
        for p in self.ports:
            if p.name == name:
                return p
        return None

    def clone(self) -> "Container":
        """Fast deep copy — generic copy.deepcopy dominated the sync
        hot path (watch-event snapshots happen per write, cache reads
        per sync), so every object clones by hand."""

        return Container(
            name=self.name,
            image=self.image,
            command=list(self.command),
            args=list(self.args),
            env=dict(self.env),
            ports=[Port(p.name, p.container_port) for p in self.ports],
            resources=copy.deepcopy(self.resources) if self.resources else {},
            working_dir=self.working_dir,
        )


@dataclass
class PodTemplateSpec:
    """Template stamped out once per replica index."""

    containers: List[Container] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    scheduler_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)

    def main_container(self, name: str = "tensorflow") -> Optional[Container]:
        for c in self.containers:
            if c.name == name:
                return c
        return None

    def clone(self) -> "PodTemplateSpec":
        return PodTemplateSpec(
            containers=[c.clone() for c in self.containers],
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            scheduler_name=self.scheduler_name,
            node_selector=dict(self.node_selector),
        )


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (SURVEY.md §2 "Generic job-controller runtime").

    ``min_member`` is a POD count (volcano semantics): a multi-host
    TPU_SLICE replica contributes one member per host VM.  Unset, it
    defaults to the job's total pod count — which keeps multi-host
    slices atomic; pinning it below that deliberately permits partial
    gangs (not recommended with TPU_SLICE: a slice is atomic hardware).
    """

    min_member: Optional[int] = None
    queue: str = ""
    priority_class: str = ""

    def clone(self) -> "SchedulingPolicy":
        return SchedulingPolicy(self.min_member, self.queue, self.priority_class)


@dataclass
class RunPolicy:
    clean_pod_policy: Optional[CleanPodPolicy] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None

    def clone(self) -> "RunPolicy":
        return RunPolicy(
            clean_pod_policy=self.clean_pod_policy,
            ttl_seconds_after_finished=self.ttl_seconds_after_finished,
            active_deadline_seconds=self.active_deadline_seconds,
            backoff_limit=self.backoff_limit,
            scheduling_policy=(
                self.scheduling_policy.clone() if self.scheduling_policy else None
            ),
        )


@dataclass
class ReplicaSpec:
    replicas: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: Optional[RestartPolicy] = None
    #: TPU_SLICE only: accelerator topology of the atomic slice, e.g.
    #: "v5e-16".  Informs the gang allocator's chip accounting.
    tpu_topology: str = ""
    #: TPU_SLICE only: host VMs per slice.  None = derive from the
    #: topology (4 chips/host); a multi-host slice expands into one pod
    #: per host (bootstrap/tpu_env.py expansion contract).
    hosts_per_replica: Optional[int] = None

    def clone(self) -> "ReplicaSpec":
        return ReplicaSpec(
            replicas=self.replicas,
            template=self.template.clone(),
            restart_policy=self.restart_policy,
            tpu_topology=self.tpu_topology,
            hosts_per_replica=self.hosts_per_replica,
        )

    def slice_host_count(self) -> int:
        if self.hosts_per_replica is not None:
            return max(1, int(self.hosts_per_replica))
        if not self.tpu_topology:
            return 1
        from tf_operator_tpu.api.validation import slice_hosts

        try:
            return slice_hosts(self.tpu_topology)
        except ValueError:
            return 1


#: AutoscalingPolicy.mode values
AUTOSCALING_MODES = ("serving", "training")

#: SignalBinding.kind values
SIGNAL_KINDS = ("alert", "gauge")


@dataclass
class SignalBinding:
    """One scaling signal: either a registered alert rule (breaching =
    the rule is firing) or a gauge metric family (breaching = worst
    matching level > ``threshold``).  The autoscaler
    (controller/autoscaler.py) evaluates these against the operator's
    alert engine and metrics registry."""

    kind: str = "alert"
    name: str = ""
    #: gauge kind only: breach when the level exceeds this
    threshold: float = 0.0
    #: gauge kind only: label filter (subset match, like alert rules)
    labels: Dict[str, str] = field(default_factory=dict)

    def clone(self) -> "SignalBinding":
        return SignalBinding(
            kind=self.kind,
            name=self.name,
            threshold=self.threshold,
            labels=dict(self.labels),
        )


@dataclass
class AutoscalingPolicy:
    """Declarative elastic-scaling policy for one replica set
    (SURVEY.md §2b "Elastic" — the reference reserved scale-in/out of
    replica sets for v1.x; this is the TPU-native realisation).

    ``mode`` picks the response direction:

    - ``serving`` scales INTO pressure: any breaching signal adds
      replicas (stateless serving replicas behind a shared admission
      queue); sustained quiet shrinks back toward ``min_replicas``.
    - ``training`` scales AWAY from distress: a breaching signal
      (stall/preemption alerts) sheds replicas so the job re-shards
      onto the survivors and resumes from checkpoint; sustained quiet
      grows back toward the spec's declared replica count.  Every
      training resize restarts the replica set (the world size is
      baked into each pod's bootstrap env) and is gated by
      ``max_checkpoint_age_seconds`` — a resize may only throw away
      work a sufficiently fresh checkpoint bounds.
    """

    replica_type: ReplicaType = ReplicaType.WORKER
    mode: str = "serving"
    min_replicas: int = 1
    max_replicas: int = 1
    #: replicas added/removed per decision
    step: int = 1
    #: floor between consecutive decisions for this policy (both
    #: directions share it — half of the anti-flap story)
    cooldown_seconds: float = 60.0
    #: every signal must be quiet this long before the relief direction
    #: engages (temporal hysteresis — the other half)
    stabilization_seconds: float = 120.0
    #: gauge signals only: level hysteresis — a breached gauge counts
    #: as quiet only once it drops to <= threshold * ratio, so a level
    #: hovering at the threshold cannot flap decisions
    hysteresis_ratio: float = 0.5
    #: training mode only: resize safety gate — skip any resize unless
    #: the job's checkpoint is at most this old (unknown age = skip)
    max_checkpoint_age_seconds: float = 600.0
    signals: List[SignalBinding] = field(default_factory=list)

    def clone(self) -> "AutoscalingPolicy":
        return AutoscalingPolicy(
            replica_type=self.replica_type,
            mode=self.mode,
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
            step=self.step,
            cooldown_seconds=self.cooldown_seconds,
            stabilization_seconds=self.stabilization_seconds,
            hysteresis_ratio=self.hysteresis_ratio,
            max_checkpoint_age_seconds=self.max_checkpoint_age_seconds,
            signals=[s.clone() for s in self.signals],
        )


@dataclass
class AutoscalingSpec:
    policies: List[AutoscalingPolicy] = field(default_factory=list)

    def policy_for(self, rtype: ReplicaType) -> Optional[AutoscalingPolicy]:
        for p in self.policies:
            if p.replica_type is rtype:
                return p
        return None

    def clone(self) -> "AutoscalingSpec":
        return AutoscalingSpec(policies=[p.clone() for p in self.policies])


#: SchedulingSpec.priority_class values, rank order — index IS the rank
#: (Kueue/Volcano-shaped fleet scheduling, ROADMAP item 4).  The fleet
#: scheduler (controller/scheduler.py) admits queued gangs highest
#: effective rank first and only preempts strictly-lower classes.
PRIORITY_CLASSES = ("low", "standard", "high", "critical")

#: Default class for jobs that declare ``spec.scheduling`` without a
#: ``priorityClass``.
DEFAULT_PRIORITY_CLASS = "standard"


def priority_rank(priority_class: str) -> int:
    """Numeric rank for a priority class (higher = more important).
    Unknown/empty names rank as the default class — validation rejects
    unknown names at admission, so this is a belt for stale objects."""

    try:
        return PRIORITY_CLASSES.index(priority_class)
    except ValueError:
        return PRIORITY_CLASSES.index(DEFAULT_PRIORITY_CLASS)


@dataclass
class SchedulingSpec:
    """Fleet-scheduling declaration (controller/scheduler.py): opting
    in routes the job through the cluster-level queue — whole-gang
    admission by priority × age with per-namespace quota accounting,
    and eligibility for (or exposure to) cross-job preemption.

    Jobs WITHOUT this block bypass the fleet queue entirely (single-job
    admission, the pre-scheduler behaviour)."""

    #: one of PRIORITY_CLASSES; "" defaults to DEFAULT_PRIORITY_CLASS
    priority_class: str = ""
    #: quota-group name, namespaced — chips admitted under the key
    #: "<namespace>/<quotaGroup>" count against any limit registered
    #: for it via Scheduler.set_quota; "" = the namespace default group
    quota_group: str = ""

    def effective_priority_class(self) -> str:
        return self.priority_class or DEFAULT_PRIORITY_CLASS

    def clone(self) -> "SchedulingSpec":
        return SchedulingSpec(
            priority_class=self.priority_class,
            quota_group=self.quota_group,
        )


@dataclass
class TPUJobSpec:
    replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    success_policy: SuccessPolicy = SuccessPolicy.DEFAULT
    #: enable gang (all-or-nothing) scheduling for this job
    enable_gang_scheduling: bool = False
    #: v1.x scale-in/out for workers (SURVEY.md §2b "Elastic") —
    #: defaulted True whenever ``autoscaling`` is declared
    enable_dynamic_worker: bool = False
    #: elastic autoscaling policies (controller/autoscaler.py); None =
    #: the operator never touches this job's replica counts
    autoscaling: Optional[AutoscalingSpec] = None
    #: fleet-scheduling declaration (controller/scheduler.py); None =
    #: the job bypasses the cluster queue (single-job admission)
    scheduling: Optional[SchedulingSpec] = None

    def total_replicas(self) -> int:
        return sum(int(rs.replicas or 0) for rs in self.replica_specs.values())

    def pod_count(self, rtype: "ReplicaType") -> int:
        """Pods backing one replica type.  A multi-host TPU_SLICE
        replica expands into one pod per host VM (slice s, host h →
        pod index s*H + h); every other type is 1:1."""

        spec = self.replica_specs.get(rtype)
        if spec is None:
            return 0
        n = int(spec.replicas or 0)
        if rtype is ReplicaType.TPU_SLICE:
            return n * spec.slice_host_count()
        return n

    def total_pods(self) -> int:
        return sum(self.pod_count(t) for t in self.replica_specs)

    def ordered_types(self) -> List[ReplicaType]:
        return [t for t in REPLICA_TYPE_ORDER if t in self.replica_specs]

    def clone(self) -> "TPUJobSpec":
        return TPUJobSpec(
            replica_specs={t: rs.clone() for t, rs in self.replica_specs.items()},
            run_policy=self.run_policy.clone(),
            success_policy=self.success_policy,
            enable_gang_scheduling=self.enable_gang_scheduling,
            enable_dynamic_worker=self.enable_dynamic_worker,
            autoscaling=self.autoscaling.clone() if self.autoscaling else None,
            scheduling=self.scheduling.clone() if self.scheduling else None,
        )


# ---------------------------------------------------------------------------
# Status objects
# ---------------------------------------------------------------------------


def _copy_jsonish(value):
    """Recursive copy of a JSON-shaped tree (dict/list/scalars) — the
    observedHealth block now nests (the ``autoscaler`` sub-block), and
    a shallow clone would alias the nested containers across status
    snapshots, defeating the old-vs-new status diff."""

    if isinstance(value, dict):
        return {k: _copy_jsonish(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_copy_jsonish(v) for v in value]
    return value


@dataclass
class JobCondition:
    type: JobConditionType
    status: bool
    reason: str = ""
    message: str = ""
    last_update_time: float = 0.0
    last_transition_time: float = 0.0


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class TPUJobStatus:
    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[ReplicaType, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    #: operator-side restart count, compared against backoff_limit
    restart_count: int = 0
    #: live health rollup published by the reconciler (flat JSON-able
    #: scalars/lists, camelCase keys — serialized as ``observedHealth``):
    #: firingAlerts, stallCount, restartCount, lastCheckpointAgeSeconds,
    #: throughputStepsPerSec, updatedAt.  Empty until an alert engine is
    #: wired; ``get``/``describe`` surface it so status shows live
    #: health, not just phase.
    observed_health: Dict[str, Any] = field(default_factory=dict)

    def condition(self, ctype: JobConditionType) -> Optional[JobCondition]:
        for c in self.conditions:
            if c.type == ctype:
                return c
        return None

    def clone(self) -> "TPUJobStatus":
        return TPUJobStatus(
            conditions=[
                JobCondition(
                    c.type, c.status, c.reason, c.message,
                    c.last_update_time, c.last_transition_time,
                )
                for c in self.conditions
            ],
            replica_statuses={
                t: ReplicaStatus(r.active, r.succeeded, r.failed)
                for t, r in self.replica_statuses.items()
            },
            start_time=self.start_time,
            completion_time=self.completion_time,
            restart_count=self.restart_count,
            observed_health=_copy_jsonish(self.observed_health),
        )

    def has_condition(self, ctype: JobConditionType, status: bool = True) -> bool:
        c = self.condition(ctype)
        return c is not None and c.status == status


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_time: float = field(default_factory=time.time)
    deletion_time: Optional[float] = None
    resource_version: int = 0
    owner_uid: str = ""  # ownerRef equivalent: the owning job's uid

    def clone(self) -> "ObjectMeta":
        return ObjectMeta(
            name=self.name,
            namespace=self.namespace,
            uid=self.uid,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            creation_time=self.creation_time,
            deletion_time=self.deletion_time,
            resource_version=self.resource_version,
            owner_uid=self.owner_uid,
        )


@dataclass
class TPUJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)
    #: set at informer ingestion when the stored object failed to parse
    #: or validate (out-of-band apiserver write, no admission webhook):
    #: the reconciler marks such a job Failed/InvalidSpec and never
    #: reconciles it.  Derived, never serialized.
    invalid_reason: Optional[str] = None

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def deepcopy(self) -> "TPUJob":
        return TPUJob(
            metadata=self.metadata.clone(),
            spec=self.spec.clone(),
            status=self.status.clone(),
            invalid_reason=self.invalid_reason,
        )

    clone = deepcopy

    def is_terminal(self) -> bool:
        return self.status.has_condition(JobConditionType.SUCCEEDED) or self.status.has_condition(
            JobConditionType.FAILED
        )


# ---------------------------------------------------------------------------
# Constants (SURVEY.md §2: default port 2222, container name "tensorflow")
# ---------------------------------------------------------------------------

DEFAULT_CONTAINER_NAME = "tensorflow"
DEFAULT_PORT_NAME = "tfjob-port"
DEFAULT_PORT = 2222
#: jax.distributed default coordinator port (SURVEY.md §2c)
DEFAULT_COORDINATOR_PORT = 8476

#: Label keys stamped on every replica pod (SURVEY.md §3.2).  The reference
#: used group-prefixed keys; these are our canonical equivalents.
LABEL_JOB_NAME = "tpujob.dist/job-name"
LABEL_REPLICA_TYPE = "tpujob.dist/replica-type"
LABEL_REPLICA_INDEX = "tpujob.dist/replica-index"
LABEL_GROUP_NAME = "tpujob.dist/group-name"
#: Annotation marking gang membership (reference: scheduling.k8s.io/group-name)
ANNOTATION_GANG_GROUP = "scheduling.tpujob.dist/group-name"
#: Annotation the reconciler stamps on pods carrying a telemetry
#: server (ISSUE 15): the port the pod's harness serves /metrics on.
#: The operator-side TelemetryScraper discovers scrape targets from
#: live pod records through this — the pod record IS the service
#: discovery, no extra registry.
ANNOTATION_TELEMETRY_PORT = "tpujob.dist/telemetry-port"
#: Annotation for the cross-pod KV fabric (ISSUE 17): the port a
#: serving pod's FabricServer exports /fabric/* on.  Same discovery
#: contract as the telemetry port — live pod records ARE the registry.
ANNOTATION_FABRIC_PORT = "tpujob.dist/fabric-port"


def replica_name(job_name: str, rtype: ReplicaType, index: int) -> str:
    """Stable replica/pod/service name ``<job>-<type>-<idx>``.

    This is the naming contract the cluster-spec generator relies on for
    peer discovery (SURVEY.md §2 "TF_CONFIG generation").
    """

    return f"{job_name}-{rtype.lower_name}-{index}"


def replica_labels(job_name: str, rtype: ReplicaType, index: int) -> Dict[str, str]:
    return {
        LABEL_JOB_NAME: job_name,
        LABEL_REPLICA_TYPE: rtype.lower_name,
        LABEL_REPLICA_INDEX: str(index),
    }
