"""Defaulting pass over a TPUJobSpec.

Parity: the reference's ``SetDefaults_TFJob`` (SURVEY.md §2 "Defaults",
expected upstream ``pkg/apis/tensorflow/v1/defaults.go``): fill replicas=1,
default port 2222 on the main container, default restart policy, default
clean-pod policy, and normalise replica-type spelling.

TPU additions: default the job port for TPU_SLICE replicas to the
jax.distributed coordinator port, and force gang scheduling on for any job
with a TPU_SLICE replica (a slice is atomic hardware — partial grants do
not exist).
"""

from __future__ import annotations

from tf_operator_tpu.api.types import (
    DEFAULT_CONTAINER_NAME,
    DEFAULT_COORDINATOR_PORT,
    DEFAULT_PORT,
    DEFAULT_PORT_NAME,
    CleanPodPolicy,
    Container,
    Port,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    SchedulingPolicy,
    TPUJob,
)

#: Reference default ([U] in SURVEY.md §2: "default RestartPolicy").
DEFAULT_RESTART_POLICY = RestartPolicy.NEVER
#: The reference's v1 default clean-pod policy is Running (kills lingering
#: PS replicas once the chief finishes) — SURVEY.md §2 "Common API types".
DEFAULT_CLEAN_POD_POLICY = CleanPodPolicy.RUNNING


def set_default_port(container: Container, port: int) -> None:
    if container.port_named(DEFAULT_PORT_NAME) is None:
        container.ports.append(Port(name=DEFAULT_PORT_NAME, container_port=port))


def set_defaults_replica(rtype: ReplicaType, spec: ReplicaSpec) -> None:
    if spec.replicas is None:
        spec.replicas = 1
    if spec.restart_policy is None:
        spec.restart_policy = DEFAULT_RESTART_POLICY
    if not spec.template.containers:
        spec.template.containers.append(Container(name=DEFAULT_CONTAINER_NAME))
    main = spec.template.main_container(DEFAULT_CONTAINER_NAME)
    if main is None:
        # Validation will reject; nothing to default onto.
        return
    port = DEFAULT_COORDINATOR_PORT if rtype is ReplicaType.TPU_SLICE else DEFAULT_PORT
    set_default_port(main, port)


def set_defaults(job: TPUJob) -> TPUJob:
    """Mutate ``job`` in place applying all defaults; returns it for chaining."""

    spec = job.spec
    for rtype, rspec in list(spec.replica_specs.items()):
        set_defaults_replica(rtype, rspec)

    rp = spec.run_policy
    if rp.clean_pod_policy is None:
        rp.clean_pod_policy = DEFAULT_CLEAN_POD_POLICY
    # backoff_limit stays None when unset: the reconciler treats None as
    # "unlimited restarts" (reference semantics for an absent backoffLimit).

    if ReplicaType.TPU_SLICE in spec.replica_specs:
        spec.enable_gang_scheduling = True

    if spec.autoscaling is not None:
        # an autoscaled worker set IS the v1.x dynamic-worker feature
        # (SURVEY.md §2b "Elastic") — flip the flag so consumers keying
        # on it see the truth
        spec.enable_dynamic_worker = True

    if spec.scheduling is not None:
        # the fleet scheduler admits WHOLE gangs (controller/scheduler.py)
        # — a fleet-queued job without gang semantics could be partially
        # placed, which is exactly the state the queue exists to prevent
        spec.enable_gang_scheduling = True

    if spec.enable_gang_scheduling and rp.scheduling_policy is None:
        # min_member stays None unless the user pinned it: the reconciler
        # resolves None to the job's *current* total replicas each sync,
        # so dynamic scaling keeps gang accounting in step
        rp.scheduling_policy = SchedulingPolicy()

    return job
