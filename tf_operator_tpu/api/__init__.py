"""Spec layer: job API types, defaults, validation (SURVEY.md §2, L4)."""
