"""Dict (JSON/YAML) serialisation for TPUJob — the CRD-manifest surface.

Parity: in the reference, the CRD schema *is* the Go struct via k8s
codegen (SURVEY.md §2 "Generated clients"); users author YAML manifests.
Here ``job_from_dict``/``job_to_dict`` play that role: a camelCase dict
matching the TFJob manifest shape (apiVersion/kind/metadata/spec) round-
trips through the typed objects.
"""

from __future__ import annotations

from typing import Any, Dict

from tf_operator_tpu.api.types import (
    AutoscalingPolicy,
    AutoscalingSpec,
    CleanPodPolicy,
    Container,
    JobCondition,
    JobConditionType,
    ObjectMeta,
    PodTemplateSpec,
    Port,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    SchedulingSpec,
    SignalBinding,
    SuccessPolicy,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
)

API_VERSION = "tpujob.dist/v1"
KIND = "TPUJob"


def _container_from_dict(d: Dict[str, Any]) -> Container:
    return Container(
        name=d.get("name", "tensorflow"),
        image=d.get("image", ""),
        command=list(d.get("command", [])),
        args=list(d.get("args", [])),
        env={e["name"]: e["value"] for e in d.get("env", [])}
        if isinstance(d.get("env"), list)
        else dict(d.get("env", {})),
        ports=[
            Port(name=p.get("name", ""), container_port=int(p["containerPort"]))
            for p in d.get("ports", [])
        ],
        resources=dict(d.get("resources", {})),
        working_dir=d.get("workingDir", ""),
    )


def _container_to_dict(c: Container) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": c.name}
    if c.image:
        out["image"] = c.image
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    if c.env:
        out["env"] = [{"name": k, "value": v} for k, v in sorted(c.env.items())]
    if c.ports:
        out["ports"] = [p.to_dict() for p in c.ports]
    if c.resources:
        out["resources"] = dict(c.resources)
    if c.working_dir:
        out["workingDir"] = c.working_dir
    return out


def _template_from_dict(d: Dict[str, Any]) -> PodTemplateSpec:
    spec = d.get("spec", d)
    meta = d.get("metadata", {})
    return PodTemplateSpec(
        containers=[_container_from_dict(c) for c in spec.get("containers", [])],
        labels=dict(meta.get("labels", {})),
        annotations=dict(meta.get("annotations", {})),
        scheduler_name=spec.get("schedulerName", ""),
        node_selector=dict(spec.get("nodeSelector", {})),
    )


def _template_to_dict(t: PodTemplateSpec) -> Dict[str, Any]:
    out: Dict[str, Any] = {"spec": {"containers": [_container_to_dict(c) for c in t.containers]}}
    if t.labels or t.annotations:
        out["metadata"] = {}
        if t.labels:
            out["metadata"]["labels"] = dict(t.labels)
        if t.annotations:
            out["metadata"]["annotations"] = dict(t.annotations)
    if t.scheduler_name:
        out["spec"]["schedulerName"] = t.scheduler_name
    if t.node_selector:
        out["spec"]["nodeSelector"] = t.node_selector
    return out


def _autoscaling_from_dict(d: Dict[str, Any]) -> AutoscalingSpec:
    policies = []
    for p in d.get("policies", []):
        defaults = AutoscalingPolicy()
        policies.append(
            AutoscalingPolicy(
                replica_type=ReplicaType.from_str(p.get("replicaType", "Worker")),
                mode=p.get("mode", defaults.mode),
                min_replicas=int(p.get("minReplicas", defaults.min_replicas)),
                max_replicas=int(p.get("maxReplicas", defaults.max_replicas)),
                step=int(p.get("step", defaults.step)),
                cooldown_seconds=float(
                    p.get("cooldownSeconds", defaults.cooldown_seconds)
                ),
                stabilization_seconds=float(
                    p.get("stabilizationSeconds", defaults.stabilization_seconds)
                ),
                hysteresis_ratio=float(
                    p.get("hysteresisRatio", defaults.hysteresis_ratio)
                ),
                max_checkpoint_age_seconds=float(
                    p.get(
                        "maxCheckpointAgeSeconds",
                        defaults.max_checkpoint_age_seconds,
                    )
                ),
                signals=[
                    SignalBinding(
                        kind=s.get("kind", "alert"),
                        name=s.get("name", ""),
                        threshold=float(s.get("threshold", 0.0)),
                        labels=dict(s.get("labels", {})),
                    )
                    for s in p.get("signals", [])
                ],
            )
        )
    return AutoscalingSpec(policies=policies)


def _autoscaling_to_dict(a: AutoscalingSpec) -> Dict[str, Any]:
    out = []
    for p in a.policies:
        pd: Dict[str, Any] = {
            "replicaType": p.replica_type.value,
            "mode": p.mode,
            "minReplicas": p.min_replicas,
            "maxReplicas": p.max_replicas,
            "step": p.step,
            "cooldownSeconds": p.cooldown_seconds,
            "stabilizationSeconds": p.stabilization_seconds,
            "hysteresisRatio": p.hysteresis_ratio,
            "maxCheckpointAgeSeconds": p.max_checkpoint_age_seconds,
            "signals": [],
        }
        for s in p.signals:
            sd: Dict[str, Any] = {"kind": s.kind, "name": s.name}
            if s.kind == "gauge":
                sd["threshold"] = s.threshold
                if s.labels:
                    sd["labels"] = dict(s.labels)
            pd["signals"].append(sd)
        out.append(pd)
    return {"policies": out}


def _scheduling_from_dict(d: Dict[str, Any]) -> SchedulingSpec:
    return SchedulingSpec(
        priority_class=d.get("priorityClass", ""),
        quota_group=d.get("quotaGroup", ""),
    )


def _scheduling_to_dict(s: SchedulingSpec) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if s.priority_class:
        out["priorityClass"] = s.priority_class
    if s.quota_group:
        out["quotaGroup"] = s.quota_group
    return out


def job_from_dict(d: Dict[str, Any]) -> TPUJob:
    meta_d = d.get("metadata", {})
    spec_d = d.get("spec", {})
    rp_d = spec_d.get("runPolicy", {})
    sp_d = rp_d.get("schedulingPolicy")

    replica_specs: Dict[ReplicaType, ReplicaSpec] = {}
    for tname, rs in spec_d.get("tpuReplicaSpecs", spec_d.get("tfReplicaSpecs", {})).items():
        rtype = ReplicaType.from_str(tname)
        replica_specs[rtype] = ReplicaSpec(
            replicas=rs.get("replicas"),
            template=_template_from_dict(rs.get("template", {})),
            restart_policy=RestartPolicy(rs["restartPolicy"]) if rs.get("restartPolicy") else None,
            tpu_topology=rs.get("tpuTopology", ""),
            hosts_per_replica=rs.get("hostsPerReplica"),
        )

    run_policy = RunPolicy(
        clean_pod_policy=CleanPodPolicy(rp_d["cleanPodPolicy"]) if rp_d.get("cleanPodPolicy") else None,
        ttl_seconds_after_finished=rp_d.get("ttlSecondsAfterFinished"),
        active_deadline_seconds=rp_d.get("activeDeadlineSeconds"),
        backoff_limit=rp_d.get("backoffLimit"),
        scheduling_policy=SchedulingPolicy(
            min_member=sp_d.get("minMember"),
            queue=sp_d.get("queue", ""),
            priority_class=sp_d.get("priorityClass", ""),
        )
        if sp_d is not None
        else None,
    )

    return TPUJob(
        metadata=ObjectMeta(
            name=meta_d.get("name", ""),
            namespace=meta_d.get("namespace", "default"),
            uid=meta_d.get("uid", ""),
            labels=dict(meta_d.get("labels", {})),
            annotations=dict(meta_d.get("annotations", {})),
        ),
        spec=TPUJobSpec(
            replica_specs=replica_specs,
            run_policy=run_policy,
            success_policy=SuccessPolicy(spec_d.get("successPolicy", "")),
            enable_gang_scheduling=bool(spec_d.get("enableGangScheduling", False)),
            enable_dynamic_worker=bool(spec_d.get("enableDynamicWorker", False)),
            autoscaling=(
                _autoscaling_from_dict(spec_d["autoscaling"])
                if spec_d.get("autoscaling")
                else None
            ),
            scheduling=(
                _scheduling_from_dict(spec_d["scheduling"])
                if spec_d.get("scheduling") is not None
                else None
            ),
        ),
        status=status_from_dict(d["status"]) if "status" in d else TPUJobStatus(),
    )


def job_to_dict(job: TPUJob) -> Dict[str, Any]:
    spec = job.spec
    rp = spec.run_policy
    spec_d: Dict[str, Any] = {
        "tpuReplicaSpecs": {
            rtype.value: _replica_spec_to_dict(rs)
            for rtype, rs in ((t, spec.replica_specs[t]) for t in spec.ordered_types())
        }
    }
    rp_d: Dict[str, Any] = {}
    if rp.clean_pod_policy is not None:
        rp_d["cleanPodPolicy"] = rp.clean_pod_policy.value
    if rp.ttl_seconds_after_finished is not None:
        rp_d["ttlSecondsAfterFinished"] = rp.ttl_seconds_after_finished
    if rp.active_deadline_seconds is not None:
        rp_d["activeDeadlineSeconds"] = rp.active_deadline_seconds
    if rp.backoff_limit is not None:
        rp_d["backoffLimit"] = rp.backoff_limit
    if rp.scheduling_policy is not None:
        sp: Dict[str, Any] = {}
        if rp.scheduling_policy.min_member is not None:
            sp["minMember"] = rp.scheduling_policy.min_member
        if rp.scheduling_policy.queue:
            sp["queue"] = rp.scheduling_policy.queue
        if rp.scheduling_policy.priority_class:
            sp["priorityClass"] = rp.scheduling_policy.priority_class
        rp_d["schedulingPolicy"] = sp
    if rp_d:
        spec_d["runPolicy"] = rp_d
    if spec.success_policy is not SuccessPolicy.DEFAULT:
        spec_d["successPolicy"] = spec.success_policy.value
    if spec.enable_gang_scheduling:
        spec_d["enableGangScheduling"] = True
    if spec.enable_dynamic_worker:
        spec_d["enableDynamicWorker"] = True
    if spec.autoscaling is not None:
        spec_d["autoscaling"] = _autoscaling_to_dict(spec.autoscaling)
    if spec.scheduling is not None:
        spec_d["scheduling"] = _scheduling_to_dict(spec.scheduling)

    out: Dict[str, Any] = {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": job.metadata.name, "namespace": job.metadata.namespace},
        "spec": spec_d,
    }
    if job.metadata.labels:
        out["metadata"]["labels"] = dict(job.metadata.labels)
    if job.metadata.annotations:
        out["metadata"]["annotations"] = dict(job.metadata.annotations)
    if job.metadata.uid:
        out["metadata"]["uid"] = job.metadata.uid
    if job.status.conditions or job.status.replica_statuses:
        out["status"] = status_to_dict(job.status)
    return out


def _replica_spec_to_dict(rs: ReplicaSpec) -> Dict[str, Any]:
    out: Dict[str, Any] = {"template": _template_to_dict(rs.template)}
    if rs.replicas is not None:
        out["replicas"] = rs.replicas
    if rs.restart_policy is not None:
        out["restartPolicy"] = rs.restart_policy.value
    if rs.tpu_topology:
        out["tpuTopology"] = rs.tpu_topology
    if rs.hosts_per_replica is not None:
        out["hostsPerReplica"] = rs.hosts_per_replica
    return out


def status_to_dict(st: TPUJobStatus) -> Dict[str, Any]:
    out = {
        "conditions": [
            {
                "type": c.type.value,
                "status": "True" if c.status else "False",
                "reason": c.reason,
                "message": c.message,
                "lastUpdateTime": c.last_update_time,
                "lastTransitionTime": c.last_transition_time,
            }
            for c in st.conditions
        ],
        "replicaStatuses": {
            rt.value: {"active": rs.active, "succeeded": rs.succeeded, "failed": rs.failed}
            for rt, rs in st.replica_statuses.items()
        },
        "startTime": st.start_time,
        "completionTime": st.completion_time,
        "restartCount": st.restart_count,
    }
    if st.observed_health:
        out["observedHealth"] = dict(st.observed_health)
    return out


def status_from_dict(d: Dict[str, Any]) -> TPUJobStatus:
    st = TPUJobStatus(
        start_time=d.get("startTime"),
        completion_time=d.get("completionTime"),
        restart_count=d.get("restartCount", 0),
        observed_health=dict(d.get("observedHealth", {})),
    )
    for c in d.get("conditions", []):
        st.conditions.append(
            JobCondition(
                type=JobConditionType(c["type"]),
                status=c.get("status") in (True, "True"),
                reason=c.get("reason", ""),
                message=c.get("message", ""),
                last_update_time=c.get("lastUpdateTime", 0.0),
                last_transition_time=c.get("lastTransitionTime", 0.0),
            )
        )
    for tname, rs in d.get("replicaStatuses", {}).items():
        st.replica_statuses[ReplicaType.from_str(tname)] = ReplicaStatus(
            active=rs.get("active", 0),
            succeeded=rs.get("succeeded", 0),
            failed=rs.get("failed", 0),
        )
    return st
