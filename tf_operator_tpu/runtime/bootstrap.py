"""jax.distributed bootstrap from injected TPUJOB_* env."""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from tf_operator_tpu.bootstrap.tpu_env import (
    ENV_COORDINATOR,
    ENV_JOB_NAME,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ENV_REPLICA_INDEX,
    ENV_REPLICA_TYPE,
)


@dataclass
class JobContext:
    job_name: str
    replica_type: str
    replica_index: int
    process_id: int
    num_processes: int
    coordinator_address: str

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def from_env(environ=None) -> Optional[JobContext]:
    """Parse the injected env; None when not running under the operator."""

    e = environ if environ is not None else os.environ
    if ENV_COORDINATOR not in e:
        return None
    return JobContext(
        job_name=e.get(ENV_JOB_NAME, ""),
        replica_type=e.get(ENV_REPLICA_TYPE, ""),
        replica_index=int(e.get(ENV_REPLICA_INDEX, "0")),
        process_id=int(e.get(ENV_PROCESS_ID, "0")),
        num_processes=int(e.get(ENV_NUM_PROCESSES, "1")),
        coordinator_address=e[ENV_COORDINATOR],
    )


def initialize(platform: Optional[str] = None) -> Optional[JobContext]:
    """Join the job's collective world.  Call before any jax device use.

    - single-process jobs (or no operator env): no-op, returns context
      (or None) without touching jax.distributed.
    - multi-process: ``jax.distributed.initialize(coordinator, n, pid)``;
      on CPU the gloo collectives implementation is selected so
      cross-process psum/allgather work in tests (the ICI-equivalent
      path during local development; SURVEY.md §4 tier 3).
    """

    ctx = from_env()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if ctx is None or ctx.num_processes <= 1:
        return ctx

    # Select gloo for the CPU client whenever we're multi-process.  The
    # CPU backend exists even alongside TPU, and which platform wins is
    # resolved inside jax (env/config/plugins) — keying off our own env
    # would miss hosts that default to CPU without declaring it.  gloo
    # only activates for cross-process CPU arrays, so this is a no-op on
    # TPU-resolved jobs.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    jax.distributed.initialize(
        coordinator_address=ctx.coordinator_address,
        num_processes=ctx.num_processes,
        process_id=ctx.process_id,
    )
    return ctx
