"""Worker-side runtime: what training processes call to join the job.

Parity: the user-side bootstrap of the reference's examples
(SURVEY.md §3.3): where dist-mnist parses TF_CONFIG and builds
tf.train.Server, a TPU-native workload calls
``tf_operator_tpu.runtime.initialize()`` which reads the injected
``TPUJOB_*`` env (SURVEY.md §2c: coordinator bootstrap) and brings up
``jax.distributed`` so every process sees the global device set and XLA
collectives ride ICI (TPU) or gloo (CPU testing).
"""

from tf_operator_tpu.runtime.bootstrap import JobContext, initialize  # noqa: F401
