"""Shared training-example plumbing (VERDICT r2 weak #8).

Every example was re-rolling the same argparse flags, batch-size math,
and train loop; the examples are the user-facing contract, so drift
there becomes doc-rot.  The shared bits live here — examples keep only
what they demonstrate (model, loss, sharding choice, data source).
"""

from __future__ import annotations

import argparse
from typing import Dict, Iterable, List, Optional


def standard_parser(description: str, **defaults) -> argparse.ArgumentParser:
    """The flag set every training example shares.

    ``defaults`` overrides any of: steps, batch_per_device,
    learning_rate.
    """

    p = argparse.ArgumentParser(description=description)
    p.add_argument("--steps", type=int, default=defaults.get("steps", 30))
    p.add_argument(
        "--batch-per-device",
        type=int,
        default=defaults.get("batch_per_device", 32),
    )
    p.add_argument(
        "--learning-rate",
        type=float,
        default=defaults.get("learning_rate", 0.1),
    )
    return p


def gather_params(trainer):
    """Host-local copy of the (possibly globally-sharded) params.

    COLLECTIVE: every process must call this.  A jitted identity with
    fully-replicated out_shardings makes XLA all-gather the shards
    (ICI/DCN — or gloo on CPU worlds); afterwards each process holds an
    addressable replica that device_get can fetch.  This is the right
    primitive for post-training single-host work (generation, export) —
    `process_allgather` would stack a bogus leading process axis on
    already-global arrays.
    """

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(trainer.mesh, PartitionSpec())
    rep = jax.jit(lambda t: t, out_shardings=replicated)(trainer.state.params)
    return jax.device_get(rep)


def batch_sizes(batch_per_device: int):
    """(global, per-process) batch sizes for the current world."""

    import jax

    global_batch = batch_per_device * len(jax.devices())
    local_batch = max(global_batch // jax.process_count(), 1)
    return global_batch, local_batch


def train_loop(
    trainer,
    batch_or_batches,
    steps: int,
    *,
    start_step: int = 0,
    tag: str = "train",
    assert_decreasing: bool = True,
    tracer=None,
) -> List[float]:
    """Run ``steps`` steps, print the standard per-process summary, and
    (by default) fail loudly if the loss did not decrease — the examples
    double as e2e workloads, so silent divergence must exit non-zero.

    ``batch_or_batches``: one device-resident batch (reused every step)
    or an iterator of batches (a live input pipeline).

    Traced (utils/trace): the run is one ``train <tag>`` trace with a
    span per step, split into ``data.load`` and ``train.step`` children
    — the training-side end of the operator's trace story, so a slow
    step shows *which half* (input pipeline vs device step) ate the
    time.  Long runs truncate at the store's per-trace span cap; the
    waterfall reports how many spans were dropped.
    """

    import sys

    import jax
    import numpy as np

    from tf_operator_tpu.utils.trace import default_tracer

    tr = tracer if tracer is not None else default_tracer

    batches: Optional[Iterable[Dict]] = None
    fixed = None
    if hasattr(batch_or_batches, "__next__"):
        batches = batch_or_batches
    else:
        fixed = batch_or_batches

    losses: List[float] = []
    with tr.span(
        f"train {tag}", attributes={"startStep": start_step, "steps": steps}
    ):
        for step in range(start_step, steps):
            with tr.span(f"step {step}"):
                if batches is not None:
                    with tr.span("data.load"):
                        batch = next(batches)
                else:
                    batch = fixed
                with tr.span("train.step"):
                    metrics = trainer.train_step(batch)
            losses.append(float(metrics["loss"]))

    if losses:
        first, last = losses[0], float(np.mean(losses[-5:]))
        print(
            f"process {jax.process_index()}/{jax.process_count()} [{tag}]: "
            f"steps {start_step}..{steps} loss {first:.4f} -> {last:.4f}",
            flush=True,
        )
        if (
            assert_decreasing
            and start_step == 0
            and steps >= 20
            and not last < first
        ):
            print("loss did not decrease", file=sys.stderr, flush=True)
            raise SystemExit(1)
    return losses
