"""Shared training-example plumbing (VERDICT r2 weak #8).

Every example was re-rolling the same argparse flags, batch-size math,
and train loop; the examples are the user-facing contract, so drift
there becomes doc-rot.  The shared bits live here — examples keep only
what they demonstrate (model, loss, sharding choice, data source).
"""

from __future__ import annotations

import argparse
from typing import Dict, Iterable, List, Optional


def standard_parser(description: str, **defaults) -> argparse.ArgumentParser:
    """The flag set every training example shares.

    ``defaults`` overrides any of: steps, batch_per_device,
    learning_rate.
    """

    p = argparse.ArgumentParser(description=description)
    p.add_argument("--steps", type=int, default=defaults.get("steps", 30))
    p.add_argument(
        "--batch-per-device",
        type=int,
        default=defaults.get("batch_per_device", 32),
    )
    p.add_argument(
        "--learning-rate",
        type=float,
        default=defaults.get("learning_rate", 0.1),
    )
    p.add_argument(
        "--steps-per-sync",
        type=int,
        default=defaults.get("steps_per_sync", 8),
        help="K: fuse K train steps per host dispatch (lax.scan) and "
        "resolve metrics once per window — 0 blocking syncs per "
        "steady-state step.  1 = the per-step legacy path (debugging)",
    )
    return p


def gather_params(trainer):
    """Host-local copy of the (possibly globally-sharded) params.

    COLLECTIVE: every process must call this.  A jitted identity with
    fully-replicated out_shardings makes XLA all-gather the shards
    (ICI/DCN — or gloo on CPU worlds); afterwards each process holds an
    addressable replica that device_get can fetch.  This is the right
    primitive for post-training single-host work (generation, export) —
    `process_allgather` would stack a bogus leading process axis on
    already-global arrays.
    """

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(trainer.mesh, PartitionSpec())
    rep = jax.jit(lambda t: t, out_shardings=replicated)(trainer.state.params)
    return jax.device_get(rep)


def batch_sizes(batch_per_device: int):
    """(global, per-process) batch sizes for the current world."""

    import jax

    global_batch = batch_per_device * len(jax.devices())
    local_batch = max(global_batch // jax.process_count(), 1)
    return global_batch, local_batch


def _resolve_losses(ledger, phase: str, pending) -> List[float]:
    """Resolve a window's device-side loss arrays (scalars and/or
    stacked [n] vectors) to a flat host float list through the sync
    ledger — the ONE device→host route the training loop uses."""

    import numpy as np

    out: List[float] = []
    for v in ledger.resolve(phase, pending):
        a = np.asarray(v)
        out.extend(a.reshape(-1).tolist() if a.ndim else [a.item()])
    return out


def train_loop(
    trainer,
    batch_or_batches,
    steps: int,
    *,
    start_step: int = 0,
    tag: str = "train",
    assert_decreasing: bool = True,
    tracer=None,
    steps_per_sync: int = 1,
    sync_ledger=None,
    watchdog=None,
) -> List[float]:
    """Run ``steps`` steps, print the standard per-process summary, and
    (by default) fail loudly if the loss did not decrease — the examples
    double as e2e workloads, so silent divergence must exit non-zero.

    ``batch_or_batches``: one device-resident batch (reused every step)
    or an iterator of batches (a live input pipeline).

    ``steps_per_sync`` (K) is the training twin of serving's
    steps_per_sync knob: with K > 1 the loop keeps losses as DEVICE
    arrays and resolves them to floats once per K-step window — always
    the *previous* window, after the next one is already dispatched, so
    the host never waits on work it just enqueued.  On a fixed batch the
    window itself is ONE compiled program (``Trainer.train_steps``'s
    K-step ``lax.scan``); on a live pipeline each step still dispatches
    (the prefetch buffer owns the batches) but metric resolution stays
    windowed.  Steady-state steps therefore perform exactly 0 blocking
    host↔device syncs — counted, per phase, by ``sync_ledger`` (a
    ``utils/metrics.StepSyncLedger``; one is created against the
    default metrics registry when not passed).  K=1 is the legacy
    per-step path, bit-identical losses to the pre-windowing loop (and
    1 honest ``step``-phase sync per step on the ledger) — keep it for
    debugging.  The loss-decrease e2e guard always runs on the fully
    resolved series at the end.

    Traced (utils/trace): one ``train <tag>`` trace; K=1 keeps a span
    per step, K>1 emits a span per window, each split into
    ``data.load`` / ``train.step`` children, with the ledger's
    ``sync.window`` / ``sync.final`` spans marking the deferred
    resolves.  Long runs truncate at the store's per-trace span cap;
    the waterfall reports how many spans were dropped.

    Observability (r8): ``data.load`` waits are ALSO recorded on the
    sync ledger (``train_sync_total{phase="data.load"}`` + the shared
    ``train_sync_seconds`` histogram family) — a starved input
    pipeline shows up next to the window resolves it delays; and the
    loop registers a ``train.<tag>`` heartbeat on ``watchdog``
    (default: the process watchdog, utils/watchdog.py), beaten once
    per resolved window — a wedged step or data iterator past the
    deadline dumps thread stacks + the flight recorder.
    """

    import sys
    import time

    import jax

    from tf_operator_tpu.runtime.telemetry import (
        maybe_start_from_env as _maybe_start_telemetry,
        trace_context_from_env,
    )
    from tf_operator_tpu.utils.metrics import StepSyncLedger, default_metrics
    from tf_operator_tpu.utils.trace import default_tracer
    from tf_operator_tpu.utils.watchdog import default_watchdog

    # fleet telemetry (ISSUE 15): when the reconciler injected
    # TPUJOB_TELEMETRY_PORT this worker serves /metrics, /traces and
    # /debug/flightrecorder so the operator's scraper can federate its
    # pod-scope signals; without the env this is a no-op (library
    # users get no server and no port bind).  Host-side only — boots
    # BEFORE the step loop, so the no-hot-sync gate is untouched.
    _maybe_start_telemetry()
    # trace stitching: root this run's trace under the reconciler's
    # pod.create span context when it rode in on the env — the scraper
    # folds our spans back, and /traces/<id> shows ONE vertical
    # reconcile -> boot -> train waterfall
    env_trace_id, env_parent_id = trace_context_from_env()

    tr = tracer if tracer is not None else default_tracer
    ledger = (
        sync_ledger
        if sync_ledger is not None
        else StepSyncLedger(metrics=default_metrics, tracer=tr)
    )
    dog = watchdog if watchdog is not None else default_watchdog
    hb = dog.register(f"train.{tag}")

    batches: Optional[Iterable[Dict]] = None
    fixed = None
    if hasattr(batch_or_batches, "__next__"):
        batches = batch_or_batches
    else:
        fixed = batch_or_batches

    k = max(1, int(steps_per_sync))
    # fused scan windows need a fixed batch and a trainer that ships
    # train_steps; custom trainers without it keep per-step dispatch
    # (windowed resolution still applies — dispatch is async anyway)
    fused = fixed is not None and callable(
        getattr(trainer, "train_steps", None)
    )

    # ONE ledger covers the whole run: the trainer's own fetches
    # (summary-interval scalar resolves) must land on the same ledger
    # as the loop's window resolves, or the embedded snapshot
    # under-reports the run's real syncs
    prev_trainer_ledger = getattr(trainer, "sync_ledger", None)
    if prev_trainer_ledger is not None:
        trainer.sync_ledger = ledger

    losses: List[float] = []
    pending: List = []  # previous window's device-side loss arrays
    #: recent-throughput gauge (host-side wall arithmetic only — the
    #: no-hot-sync gate stays satisfied): steps dispatched per second
    #: since the previous window (per step when K=1), on the ledger's
    #: registry.  Served on THIS process's /metrics exposition — and,
    #: under the operator, federated into the operator registry as
    #: train_window_steps_per_second{job,replica_type,replica_index}
    #: by the telemetry scraper (docs/ARCHITECTURE.md "Fleet
    #: telemetry"); the health rollup's job-level throughput still
    #: reads the summary series (reconciler._recent_throughput)
    mreg = getattr(ledger, "metrics", None)
    t_prev = time.perf_counter()
    # ISSUE 20 step-time sentinel: the per-step wall of each window
    # (same host clock delta as the throughput gauge, normalized per
    # step so K=1 and K=8 runs share one reference) feeds the
    # step_time_* drift gauges the step-time-regression rule binds.
    # A sentinel bound to the ledger's registry when one exists, so a
    # harness under test sees its own gauges, not the process global's.
    from tf_operator_tpu.utils.costplane import (
        StepTimeSentinel, default_costplane,
    )

    sentinel = (
        StepTimeSentinel(metrics=mreg)
        if mreg is not None else default_costplane.sentinel
    )

    def _observe_throughput(n_steps: int) -> None:
        nonlocal t_prev
        now_t = time.perf_counter()
        if mreg is not None and now_t > t_prev:
            mreg.set(
                "train_window_steps_per_second", n_steps / (now_t - t_prev)
            )
        if now_t > t_prev:
            sentinel.observe("train_sync", (now_t - t_prev) / n_steps)
        t_prev = now_t

    try:
        with tr.span(
            f"train {tag}",
            trace_id=env_trace_id,
            parent_id=env_parent_id,
            attributes={
                "startStep": start_step, "steps": steps, "stepsPerSync": k,
            },
        ):
            if k == 1:
                # legacy per-step path: resolve EVERY step (one counted
                # sync per step — the debugging baseline the ledger's
                # steady-state invariant is measured against)
                for step in range(start_step, steps):
                    with tr.span(f"step {step}"):
                        if batches is not None:
                            with tr.span("data.load"):
                                t_load = time.perf_counter()
                                batch = next(batches)
                                ledger.record(
                                    "data.load",
                                    time.perf_counter() - t_load,
                                )
                        else:
                            batch = fixed
                        with tr.span("train.step"):
                            metrics = trainer.train_step(batch)
                    ledger.step()
                    hb.beat()
                    _observe_throughput(1)
                    losses.extend(_resolve_losses(ledger, "step", [metrics["loss"]]))
            else:
                step = start_step
                while step < steps:
                    n = min(k, steps - step)
                    window: List = []
                    with tr.span(
                        f"steps {step}..{step + n}", attributes={"k": n}
                    ):
                        if fused:
                            with tr.span("train.step"):
                                metrics = trainer.train_steps(fixed, n)
                            window.append(metrics["loss"])  # stacked [n]
                        else:
                            for _ in range(n):
                                if batches is not None:
                                    with tr.span("data.load"):
                                        t_load = time.perf_counter()
                                        batch = next(batches)
                                        ledger.record(
                                            "data.load",
                                            time.perf_counter() - t_load,
                                        )
                                else:
                                    batch = fixed
                                with tr.span("train.step"):
                                    m = trainer.train_step(batch)
                                window.append(m["loss"])
                    ledger.step(n)
                    hb.beat()
                    _observe_throughput(n)
                    # deferred resolution: fetch the PREVIOUS window now
                    # that this one is dispatched — its arrays are (almost
                    # always) already finished, so the host rides behind
                    # the device instead of gating it
                    if pending:
                        losses.extend(_resolve_losses(ledger, "window", pending))
                    pending = window
                    step += n
            if pending:
                losses.extend(_resolve_losses(ledger, "final", pending))

    finally:
        dog.unregister(hb.name)
        if prev_trainer_ledger is not None:
            trainer.sync_ledger = prev_trainer_ledger

    if losses:
        first, last = losses[0], sum(losses[-5:]) / len(losses[-5:])
        print(
            f"process {jax.process_index()}/{jax.process_count()} [{tag}]: "
            f"steps {start_step}..{steps} loss {first:.4f} -> {last:.4f}",
            flush=True,
        )
        if (
            assert_decreasing
            and start_step == 0
            and steps >= 20
            and not last < first
        ):
            print("loss did not decrease", file=sys.stderr, flush=True)
            raise SystemExit(1)
    return losses
