"""Pod-side telemetry exporter (ISSUE 15, the fleet plane's pod half).

Every training pod the reconciler launches is its own process with its
own metrics registry and TraceStore, so until now pod-scope signals —
``train_window_steps_per_second``, the ``train_dcn_*{fabric=}`` grad
sync families, the checkpoint durability stamp — were invisible to the
operator's alert engine, health rollup, and autoscaler (the PR-6
process-scope gap).  This module is the export side of closing it:

- :class:`PodTelemetryServer` — a lightweight HTTP server over the
  process-global observability singletons:

      GET /metrics               Prometheus text (utils/metrics)
      GET /traces                finished spans as JSONL (utils/trace)
      GET /debug/flightrecorder  black-box rings (utils/flight)
      GET /debug/compiles        compile ledger (utils/costplane)
      GET /debug/memory          HBM accountant (utils/costplane)
      GET /healthz               liveness

- :func:`maybe_start_from_env` — boots the server exactly once when
  the reconciler injected ``TPUJOB_TELEMETRY_PORT`` (bootstrap/tpu_env
  names the contract).  Library users who never run under the operator
  get NO server and NO port bind — telemetry is off by default.

- :func:`trace_context_from_env` — the stitching half: the
  reconciler's ``pod.create`` span context rides the pod env
  (``TPUJOB_TRACE_ID`` / ``TPUJOB_PARENT_SPAN_ID``); the harness roots
  its train-loop trace under it so the operator-side scraper can fold
  the pod's spans into ONE reconcile→boot→train waterfall.

Everything here is host-side (threads + sockets); nothing imports jax
or touches the device, so the no-hot-sync training invariant is
untouched by serving telemetry from inside a training process.
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from tf_operator_tpu.bootstrap.tpu_env import (
    ENV_PARENT_SPAN_ID,
    ENV_TELEMETRY_PORT,
    ENV_TRACE_ID,
)


def trace_context_from_env(environ=None) -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, parent_span_id) injected by the reconciler at pod
    create, or (None, None) outside the operator — the env twin of
    ``utils/trace.extract_headers``."""

    e = environ if environ is not None else os.environ
    return e.get(ENV_TRACE_ID) or None, e.get(ENV_PARENT_SPAN_ID) or None


class PodTelemetryServer:
    """Threaded HTTP exporter over one process's observability state.

    ``metrics`` / ``tracer`` / ``recorder`` default to the
    process-global singletons (the values every harness-launched
    trainer actually writes), injectable for tests.
    """

    def __init__(
        self,
        metrics=None,
        tracer=None,
        recorder=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        if metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            metrics = default_metrics
        if tracer is None:
            from tf_operator_tpu.utils.trace import default_tracer

            tracer = default_tracer
        if recorder is None:
            from tf_operator_tpu.utils.flight import default_recorder

            recorder = default_recorder
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = "tpu-pod-telemetry/1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                try:
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):
                route = self.path.split("?")[0]
                try:
                    if route == "/healthz":
                        return self._send(200, "ok\n", "text/plain")
                    if route == "/metrics":
                        return self._send(
                            200, outer.metrics.exposition(), "text/plain"
                        )
                    if route == "/traces":
                        import io

                        buf = io.StringIO()
                        outer.tracer.store.export_jsonl(buf)
                        return self._send(
                            200, buf.getvalue(), "application/x-ndjson"
                        )
                    if route == "/debug/flightrecorder":
                        return self._send(
                            200,
                            outer.recorder.dump_text(),
                            "application/x-ndjson",
                        )
                    if route == "/debug/compiles":
                        # device cost plane (ISSUE 20): this pod's
                        # compile ledger — `tpujob top JOB` probes
                        # every pod's telemetry port for these two
                        import json

                        from tf_operator_tpu.utils.costplane import (
                            default_costplane,
                        )

                        return self._send(
                            200,
                            json.dumps(
                                default_costplane.compiles.snapshot()
                            ),
                            "application/json",
                        )
                    if route == "/debug/memory":
                        # lazy jax import at request time (host-side
                        # metadata reads only) — the module itself
                        # still never imports jax
                        import json

                        from tf_operator_tpu.utils.costplane import (
                            default_costplane,
                        )

                        return self._send(
                            200,
                            json.dumps(default_costplane.hbm.snapshot()),
                            "application/json",
                        )
                    return self._send(404, "not found\n", "text/plain")
                except Exception as e:  # noqa: BLE001 - HTTP boundary
                    return self._send(
                        500, f"{type(e).__name__}: {e}\n", "text/plain"
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "PodTelemetryServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                daemon=True,
                name="pod-telemetry",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


#: the once-per-process server maybe_start_from_env boots
_started: Optional[PodTelemetryServer] = None
_start_lock = threading.Lock()


def maybe_start_from_env(environ=None) -> Optional[PodTelemetryServer]:
    """Boot the pod telemetry server when ``TPUJOB_TELEMETRY_PORT`` is
    set to a positive port; None (and no socket bind) otherwise.
    Idempotent — the first successful boot wins; later calls return it.
    A bind failure (port taken, restricted env) logs and disables
    rather than killing training: telemetry must never take the
    workload down."""

    global _started
    e = environ if environ is not None else os.environ
    raw = e.get(ENV_TELEMETRY_PORT, "")
    try:
        port = int(raw or "0")
    except ValueError:
        port = 0
    if port <= 0:
        return _started
    with _start_lock:
        if _started is not None:
            return _started
        try:
            _started = PodTelemetryServer(port=port).start()
        except OSError as exc:
            from tf_operator_tpu.utils.logging import FieldLogger, _root

            FieldLogger(_root, component="telemetry").warning(
                "pod telemetry server disabled: cannot bind port %d: %s",
                port, exc,
            )
            return None
    return _started
