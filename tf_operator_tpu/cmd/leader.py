"""File-lock leader election.

Parity: the reference gates the controller behind resourcelock-based
leader election so N operator replicas yield one active controller
(SURVEY.md §3.1).  Without a kube-apiserver the shared medium on one
host is the filesystem: an ``fcntl.flock``-held lease file.  Lock
ownership is kernel-managed, so a crashed leader's lease releases
immediately — no TTL renewal loop is needed for the local backends.
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from typing import Optional


class FileLease:
    def __init__(self, path: str, identity: str):
        self.path = path
        self.identity = identity
        self._fd: Optional[int] = None

    def try_acquire(self) -> bool:
        """Non-blocking acquisition attempt; True when this process leads."""

        if self._fd is not None:
            return True
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(
            fd,
            json.dumps(
                {"holderIdentity": self.identity, "acquireTime": time.time()}
            ).encode(),
        )
        self._fd = fd
        return True

    def acquire(self, poll_interval: float = 0.5) -> None:
        """Block until leadership is acquired."""

        while not self.try_acquire():
            time.sleep(poll_interval)

    def holder(self) -> Optional[str]:
        """Identity of the current leader, if the lease file is readable."""

        try:
            with open(self.path) as f:
                return json.load(f).get("holderIdentity")
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    @property
    def is_leader(self) -> bool:
        return self._fd is not None
