"""File-lock leader election.

Parity: the reference gates the controller behind resourcelock-based
leader election so N operator replicas yield one active controller
(SURVEY.md §3.1).  Without a kube-apiserver the shared medium on one
host is the filesystem: an ``fcntl.flock``-held lease file.  Lock
ownership is kernel-managed, so a crashed leader's lease releases
immediately — no TTL renewal loop is needed for the local backends.
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from typing import Optional

from tf_operator_tpu.backend.retry import NETWORK_ERRORS

#: transport failures a lease client must absorb (keep polling / judge
#: against the lease deadline), not crash on: connection-level OSErrors
#: AND http.client's own exceptions (IncompleteRead etc. — raised by a
#: reset mid-response and NOT OSError subclasses), plus bad JSON.  A
#: renew thread dying on an uncaught one of these with _leading still
#: True is exactly the split-brain the lease exists to prevent.
_TRANSIENT_ERRORS = NETWORK_ERRORS + (ValueError,)


class FileLease:
    def __init__(self, path: str, identity: str):
        self.path = path
        self.identity = identity
        self._fd: Optional[int] = None

    def try_acquire(self) -> bool:
        """Non-blocking acquisition attempt; True when this process leads."""

        if self._fd is not None:
            return True
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(
            fd,
            json.dumps(
                {"holderIdentity": self.identity, "acquireTime": time.time()}
            ).encode(),
        )
        self._fd = fd
        return True

    def acquire(self, poll_interval: float = 0.5) -> None:
        """Block until leadership is acquired."""

        while not self.try_acquire():
            time.sleep(poll_interval)

    def holder(self) -> Optional[str]:
        """Identity of the current leader, if the lease file is readable."""

        try:
            with open(self.path) as f:
                return json.load(f).get("holderIdentity")
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    @property
    def is_leader(self) -> bool:
        return self._fd is not None


class KubeLease:
    """coordination.k8s.io/v1 Lease leader election over the real
    Kubernetes HTTP protocol (the client-go ``leaderelection`` +
    ``resourcelock`` tier; SURVEY.md §3.1 "leader election
    (resourcelock via configmap/lease)").

    The FileLease above is single-host by construction (flock); this
    is the multi-host half, runnable today against
    ``backend/kubesim.py``'s mini apiserver and against anything else
    speaking the subset.  Semantics follow client-go:

    - acquire: create the Lease if absent; else take over only when
      ``renewTime`` is older than ``leaseDurationSeconds``.  Takeover
      and renewal PATCH with ``metadata.resourceVersion`` as an
      optimistic-concurrency precondition — two candidates racing for
      an expired lease serialize through the apiserver's 409, so
      exactly one wins (no distributed-lock primitive needed beyond
      the apiserver itself).
    - renew: a daemon thread re-PATCHes renewTime every duration/3
      while leading.  A failed renewal (another holder, network gone
      longer than the lease) demotes immediately and fires
      ``on_lost`` — the operator wires that to its stop event, the
      client-go "OnStoppedLeading: exit" convention, because a
      controller that kept reconciling without the lease could fight
      the new leader's writes.
    """

    def __init__(
        self,
        base_url: str,
        identity: str,
        name: str = "tpu-operator",
        namespace: str = "default",
        lease_duration: float = 15.0,
        on_lost=None,
        retry=None,
        metrics=None,
        tracer=None,
    ):
        import urllib.parse

        from tf_operator_tpu.backend.retry import RetryPolicy
        from tf_operator_tpu.utils.metrics import default_metrics
        from tf_operator_tpu.utils.trace import default_tracer

        u = urllib.parse.urlparse(base_url)
        self.host, self.port = u.hostname or "127.0.0.1", u.port or 80
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.duration = float(lease_duration)
        self.on_lost = on_lost
        # retry budget deliberately SHORTER than the renew cadence
        # (duration/3): a flaky apiserver gets a few jittered tries per
        # renewal tick without one tick's retries spanning the next.
        # The deadline gates dispatching further attempts; an in-flight
        # attempt can still overrun it by the 5s transport timeout, so
        # the renew loop's own lease-deadline check stays the arbiter.
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3,
            base_delay=0.05,
            max_delay=0.5,
            deadline=min(self.duration / 3.0, max(0.2, self.duration / 6.0)),
        )
        self.metrics = metrics if metrics is not None else default_metrics
        self.tracer = tracer if tracer is not None else default_tracer
        self._leading = False
        self._stop = None  # renew-thread stop event while leading
        self._lock = __import__("threading").Lock()

    def _transition(self, event: str, **attrs) -> None:
        """Leadership transitions as instant root spans: acquired /
        lost / released show up in the trace store next to the syncs
        they gate, and the transition counter gets the trace exemplar."""

        span = self.tracer.start_span(
            "leader.transition", root=True,
            attributes={"event": event, "identity": self.identity, **attrs},
        )
        if event == "lost":
            span.set_error(f"leadership lost ({attrs.get('reason', '?')})")
        span.end()
        self.metrics.inc(
            "leader_transitions_total", exemplar=span.trace_id, event=event
        )

    # -- wire ---------------------------------------------------------------

    @property
    def _path(self) -> str:
        return (
            f"/apis/coordination.k8s.io/v1/namespaces/{self.namespace}"
            f"/leases/{self.name}"
        )


    def _request(self, method: str, path: str, body=None):
        """One (status, obj) round-trip under the retry policy: network
        errors and 5xx/429 replies retry with jittered backoff; the
        semantic statuses the election logic branches on (404 absent,
        409 lost-the-CAS, 200/201) return untouched.  Replays are safe:
        every mutating call here is a create-if-absent POST or a
        resourceVersion-preconditioned PATCH (a duplicate of either
        lands as 409, which the caller already treats as 'lost')."""

        from http.client import HTTPConnection

        def attempt():
            conn = HTTPConnection(self.host, self.port, timeout=5.0)
            try:
                payload = (
                    json.dumps(body).encode() if body is not None else None
                )
                headers = (
                    {"Content-Type": "application/json"} if payload else {}
                )
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                text = resp.read().decode(errors="replace")
                ra = resp.getheader("Retry-After")
                try:
                    ra = None if ra is None else float(ra)
                except ValueError:
                    ra = None
                return resp.status, (json.loads(text) if text else {}), ra
            finally:
                conn.close()

        def verdict(res):
            # the policy's own status set, so a narrowed injected
            # policy narrows BOTH classification paths consistently;
            # 404/409 are election semantics and return untouched.  A
            # float verdict floors the next sleep at the server's
            # Retry-After (backpressure an overloaded apiserver sends
            # precisely so clients like this stop hammering it).
            status, _, retry_after = res
            if status not in self.retry.retry_status:
                return False
            return retry_after if retry_after is not None else True

        status, obj, _ = self.retry.call(
            attempt,
            client="kube-lease",
            metrics=self.metrics,
            retryable_result=verdict,
        )
        return status, obj

    def _spec(self, transitions: int) -> dict:
        now = time.time()
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.duration),
            "renewTime": now,
            "acquireTime": now,
            "leaseTransitions": transitions,
        }

    # -- election -----------------------------------------------------------

    def try_acquire(self) -> bool:
        """Non-blocking: True when this process leads (and renewal is
        running).  Connection-level failures read as "not leading" —
        a standby must keep polling through an apiserver blip, not
        crash out of the operator loop."""

        try:
            return self._try_acquire()
        except _TRANSIENT_ERRORS:
            return False

    def _try_acquire(self) -> bool:
        with self._lock:
            if self._leading:
                return True
            status, obj = self._request("GET", self._path)
            if status == 404:
                status, obj = self._request(
                    "POST",
                    self._path.rsplit("/", 1)[0],
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {
                            "name": self.name,
                            "namespace": self.namespace,
                        },
                        "spec": self._spec(0),
                    },
                )
                if status != 201:
                    return False  # lost the create race
            elif status == 200:
                spec = obj.get("spec", {})
                renew = float(spec.get("renewTime", 0.0))
                if (
                    spec.get("holderIdentity") != self.identity
                    and time.time() - renew < self.duration
                ):
                    return False  # a live leader holds it
                # expired (or our own stale lease): compare-and-swap
                rv = obj.get("metadata", {}).get("resourceVersion", "")
                status, _ = self._request(
                    "PATCH",
                    self._path,
                    {
                        "metadata": {"resourceVersion": rv},
                        "spec": self._spec(
                            int(spec.get("leaseTransitions", 0)) + 1
                        ),
                    },
                )
                if status != 200:
                    return False  # 409: another candidate won the swap
            else:
                return False  # apiserver unreachable/unhappy
            self._leading = True
            self._start_renewer()
            self._transition("acquired")
            return True

    def acquire(self, poll_interval: float = 0.5) -> None:
        while not self.try_acquire():
            time.sleep(poll_interval)

    def _start_renewer(self) -> None:
        import threading

        self._stop = threading.Event()
        stop = self._stop

        def renew_loop():
            # transient-vs-fatal policy (client-go's): a rival holder
            # demotes IMMEDIATELY; a connection failure retries until
            # the lease deadline — a single apiserver blip must not
            # silently kill this thread (a dead renewer with
            # _leading=True is exactly the split-brain the lease
            # exists to prevent).
            last_ok = time.time()
            while not stop.wait(self.duration / 3.0):
                usurped = False
                renewed = False
                try:
                    status, obj = self._request("GET", self._path)
                    if status == 200:
                        holder = obj.get("spec", {}).get("holderIdentity")
                        if holder != self.identity:
                            usurped = True
                        else:
                            rv = obj.get("metadata", {}).get(
                                "resourceVersion", ""
                            )
                            spec = dict(obj.get("spec", {}))
                            spec["renewTime"] = time.time()
                            status, _ = self._request(
                                "PATCH",
                                self._path,
                                {
                                    "metadata": {"resourceVersion": rv},
                                    "spec": spec,
                                },
                            )
                            renewed = status == 200
                    elif status == 404:
                        usurped = True  # lease deleted under us
                except _TRANSIENT_ERRORS:
                    pass  # transient: judged against the deadline below
                if renewed:
                    last_ok = time.time()
                    continue
                if usurped or time.time() - last_ok > self.duration:
                    with self._lock:
                        self._leading = False
                    stop.set()
                    self._transition(
                        "lost",
                        reason="usurped" if usurped else "lease-deadline",
                    )
                    if self.on_lost is not None:
                        self.on_lost()
                    return

        threading.Thread(
            target=renew_loop, daemon=True, name="kube-lease-renew"
        ).start()

    def holder(self) -> Optional[str]:
        try:
            status, obj = self._request("GET", self._path)
        except _TRANSIENT_ERRORS:
            return None
        if status != 200:
            return None
        return obj.get("spec", {}).get("holderIdentity")

    def release(self) -> None:
        with self._lock:
            was_leading = self._leading
            self._leading = False
            if self._stop is not None:
                self._stop.set()
        if was_leading:
            self._transition("released")
            # hand off immediately: zero the renewTime so the next
            # candidate's expiry check passes without waiting out the
            # lease duration.  Best-effort — at shutdown the apiserver
            # (an embedded sim, say) may already be gone, and an
            # unreleased lease simply expires.
            try:
                status, obj = self._request("GET", self._path)
                if status == 200 and (
                    obj.get("spec", {}).get("holderIdentity") == self.identity
                ):
                    rv = obj.get("metadata", {}).get("resourceVersion", "")
                    spec = dict(obj.get("spec", {}))
                    spec["renewTime"] = 0.0
                    self._request(
                        "PATCH",
                        self._path,
                        {"metadata": {"resourceVersion": rv}, "spec": spec},
                    )
            except _TRANSIENT_ERRORS:
                pass

    @property
    def is_leader(self) -> bool:
        return self._leading
