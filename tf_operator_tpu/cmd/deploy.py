"""Operator deployment launcher — the Deployment-controller analogue.

Parity: the reference ships a Kubernetes Deployment manifest for the
operator itself (SURVEY.md §2 "Deploy manifests", §1 L6): N replicas of
the operator binary, leader election picking one active controller,
restarts on crash.  Without a kube-apiserver, this launcher IS that
deployment controller for one host: it spawns ``replicas`` operator
processes from an ``OperatorDeployment`` manifest, restarts any that
die (crash-loop backoff), and tears the set down on SIGTERM/SIGINT.

Run:  python -m tf_operator_tpu.cmd.deploy examples/manifests/operator.yaml

With replicas > 1 the manifest must enable leader election — standbys
serve /healthz and refuse the job API with 503 + the leader's identity
(server/api.py), exactly one process runs the controller.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

import yaml


def load_deployment(path: str) -> dict:
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if doc.get("kind") != "OperatorDeployment":
        raise ValueError(f"{path}: kind must be OperatorDeployment")
    replicas = int(doc.get("replicas", 1))
    cfg = doc.get("config", {}) or {}
    if replicas > 1 and not cfg.get("leaderElect"):
        raise ValueError(
            f"{path}: replicas={replicas} requires config.leaderElect: true "
            "(standbys must not each run a controller)"
        )
    return doc


def spawn(path: str, doc: dict, index: int, replicas: int) -> subprocess.Popen:
    """One operator replica.  Each gets its own monitoring port
    (base + index) so /healthz of every replica is scrapeable.  ``doc``
    is the manifest main() already parsed — re-reading the file here
    would let a mid-run edit crash the supervision loop on a routine
    restart."""

    cmd = [sys.executable, "-m", "tf_operator_tpu.cmd.operator", "--config", path]
    base_port = int((doc.get("config") or {}).get("monitoringPort", 8080))
    if replicas > 1 and base_port:
        cmd += ["--monitoring-port", str(base_port + index)]
    env = dict(os.environ)
    env["TPU_OPERATOR_REPLICA"] = str(index)
    proc = subprocess.Popen(cmd, env=env)
    print(f"replica {index} pid {proc.pid}", flush=True)
    return proc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-operator-deploy", description=__doc__.split("\n")[0]
    )
    ap.add_argument("manifest", help="OperatorDeployment yaml")
    ap.add_argument(
        "--max-restarts",
        type=int,
        default=None,
        help="per-replica restart budget (default: unlimited)",
    )
    args = ap.parse_args(argv)

    doc = load_deployment(args.manifest)
    replicas = int(doc.get("replicas", 1))

    procs: Dict[int, subprocess.Popen] = {}
    restarts: Dict[int, int] = {i: 0 for i in range(replicas)}
    backoff: Dict[int, float] = {i: 1.0 for i in range(replicas)}
    next_start: Dict[int, float] = {i: 0.0 for i in range(replicas)}
    stop = {"flag": False}

    def handle(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)

    print(f"deploying {replicas} operator replica(s) from {args.manifest}", flush=True)
    try:
        while not stop["flag"]:
            for i in range(replicas):
                proc = procs.get(i)
                if proc is not None and proc.poll() is None:
                    continue
                if proc is not None:  # died
                    rc = proc.returncode
                    restarts[i] += 1
                    print(
                        f"replica {i} exited rc={rc} "
                        f"(restart {restarts[i]})",
                        flush=True,
                    )
                    if args.max_restarts is not None and restarts[i] > args.max_restarts:
                        print(f"replica {i}: restart budget exhausted", flush=True)
                        stop["flag"] = True
                        break
                    # crash-loop backoff, reset on a healthy stretch
                    next_start[i] = time.time() + backoff[i]
                    backoff[i] = min(backoff[i] * 2, 30.0)
                    procs.pop(i, None)
                    continue
                if time.time() >= next_start[i]:
                    procs[i] = spawn(args.manifest, doc, i, replicas)
            # a replica that stays up 60s earns its backoff reset
            for i, proc in procs.items():
                if proc.poll() is None and time.time() - next_start[i] > 60:
                    backoff[i] = 1.0
            time.sleep(0.2)
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 10
        for proc in procs.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        print("deployment stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
