"""``tpujob`` — the user-facing client CLI.

Parity: the reference's user flow is ``kubectl apply/get/describe/delete``
against the TFJob CRD plus the dashboard's list view (SURVEY.md §1 L6/L9).
This client speaks the operator's HTTP job API instead:

    tpujob submit -f job.yaml            # kubectl apply
    tpujob list [-n ns]                  # kubectl get tfjobs
    tpujob get NAME [-n ns]              # kubectl get tfjob NAME -o json
    tpujob describe NAME [-n ns]         # kubectl describe (status + events)
    tpujob delete NAME [-n ns]           # kubectl delete
    tpujob logs NAME POD [-n ns]         # kubectl logs (local backend)
    tpujob alerts [RULE]                 # alert-engine state (firing first)
    tpujob autoscaler [JOB]              # scale decisions + policy state
    tpujob queue [JOB]                   # fleet queue + scheduling decisions
    tpujob telemetry [JOB]               # fleet scrape targets (stale first)
    tpujob fabric [JOB]                  # cross-pod KV fabric catalogs
    tpujob top [JOB]                     # device cost plane: HBM headroom
                                         # (worst first) + compile ledger
    tpujob compile -f job.yaml           # TPUJob -> real Kubernetes YAML
                                         # (backend/gke.py; offline, no server)

Manifests are the serde camelCase shape, YAML or JSON.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

import yaml


def _request(method: str, url: str, payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method=method, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = resp.read().decode()
    except urllib.error.HTTPError as e:
        detail = e.read().decode()
        try:
            detail = json.loads(detail).get("error", detail)
        except ValueError:
            pass
        raise SystemExit(f"error: {e.code} {detail}")
    except urllib.error.URLError as e:
        raise SystemExit(f"error: cannot reach operator at {url}: {e.reason}")
    try:
        return json.loads(body)
    except ValueError:
        return body


def _jobs_url(server: str, ns: str, name: str = "", sub: str = "") -> str:
    url = f"{server}/apis/v1/namespaces/{ns}/tpujobs"
    if name:
        url += f"/{name}"
    if sub:
        url += f"/{sub}"
    return url


def _is_true(cond: dict) -> bool:
    # the wire format is the k8s-style string "True"/"False"
    return cond.get("status") in (True, "True")


def _condition_summary(job: dict) -> str:
    conds = job.get("status", {}).get("conditions", [])
    active = [c["type"] for c in conds if _is_true(c)]
    for terminal in ("Succeeded", "Failed"):
        if terminal in active:
            return terminal
    # live health outranks phase for a non-terminal job: a running job
    # burning its SLO budget shows Degraded, not Running
    if "Degraded" in active:
        return "Degraded"
    for c in reversed(conds):
        if _is_true(c):
            return c["type"]
    return "Pending"


def cmd_submit(args) -> int:
    with open(args.filename) as f:
        manifest = yaml.safe_load(f)
    ns = manifest.get("metadata", {}).get("namespace", args.namespace)
    job = _request("POST", _jobs_url(args.server, ns), manifest)
    name = job["metadata"]["name"]
    print(f"tpujob.dist/{name} created")
    if not args.wait:
        return 0
    deadline = time.time() + args.timeout
    while time.time() < deadline:
        job = _request("GET", _jobs_url(args.server, ns, name))
        phase = _condition_summary(job)
        if phase in ("Succeeded", "Failed"):
            print(f"tpujob.dist/{name} {phase}")
            return 0 if phase == "Succeeded" else 1
        time.sleep(1.0)
    print(f"tpujob.dist/{name} timed out after {args.timeout}s", file=sys.stderr)
    return 2


def cmd_list(args) -> int:
    if args.namespace == "":
        jobs = _request("GET", f"{args.server}/apis/v1/tpujobs")["items"]
    else:
        jobs = _request("GET", _jobs_url(args.server, args.namespace))["items"]
    fmt = "{:<12} {:<24} {:<12} {:<10}"
    print(fmt.format("NAMESPACE", "NAME", "STATE", "RESTARTS"))
    for j in jobs:
        print(
            fmt.format(
                j["metadata"].get("namespace", ""),
                j["metadata"].get("name", ""),
                _condition_summary(j),
                str(j.get("status", {}).get("restartCount", 0)),
            )
        )
    return 0


def cmd_get(args) -> int:
    job = _request("GET", _jobs_url(args.server, args.namespace, args.name))
    print(json.dumps(job, indent=2))
    return 0


def cmd_describe(args) -> int:
    job = _request("GET", _jobs_url(args.server, args.namespace, args.name))
    print(f"Name:      {job['metadata']['name']}")
    print(f"Namespace: {job['metadata'].get('namespace', '')}")
    print(f"State:     {_condition_summary(job)}")
    st = job.get("status", {})
    print("Replica statuses:")
    for rtype, rs in st.get("replicaStatuses", {}).items():
        print(
            f"  {rtype}: active={rs.get('active', 0)} "
            f"succeeded={rs.get('succeeded', 0)} failed={rs.get('failed', 0)}"
        )
    print("Conditions:")
    for c in st.get("conditions", []):
        print(
            f"  {c['type']:<12} {str(c.get('status')):<6} "
            f"{c.get('reason', ''):<24} {c.get('message', '')}"
        )
    health = st.get("observedHealth") or {}
    if health:
        # the live rollup the reconciler publishes (alert engine +
        # watchdog + checkpoint age): health, not just phase
        print("Health:")
        firing = health.get("firingAlerts", [])
        print(f"  firingAlerts:     {', '.join(firing) if firing else '(none)'}")
        for key, label in (
            ("throughputStepsPerSec", "throughput"),
            ("lastCheckpointAgeSeconds", "checkpointAge"),
            ("stallCount", "stalls"),
            ("restartCount", "restarts"),
        ):
            if key in health:
                print(f"  {label + ':':<18}{health[key]}")
        for row in health.get("pods", []):
            # fleet telemetry per-pod rows (ISSUE 15): scrape health
            # and federated step rate, one line per pod
            bits = []
            if "scrapeAgeSeconds" in row:
                bits.append(f"scraped {row['scrapeAgeSeconds']}s ago")
            if "stepsPerSec" in row:
                bits.append(f"{row['stepsPerSec']} steps/s")
            if row.get("failures"):
                bits.append(f"{row['failures']} scrape failures")
            if row.get("stale"):
                bits.append("STALE")
            print(f"  {'pod/' + row.get('replica', '?') + ':':<18}"
                  f"{', '.join(bits) if bits else 'no data'}")
        for rtype, blk in (health.get("autoscaler") or {}).items():
            line = (
                f"{blk.get('desiredReplicas')} desired "
                f"(spec {blk.get('specReplicas')}, "
                f"{blk.get('minReplicas')}..{blk.get('maxReplicas')})"
            )
            if blk.get("breaching"):
                line += "  BREACHING"
            if blk.get("lastDecision"):
                d = blk["lastDecision"]
                line += f"  last: {d.get('direction')} -> {d.get('to')}"
            print(f"  {'autoscale/' + rtype + ':':<18}{line}")
        sched = health.get("scheduler")
        if sched:
            # fleet-scheduler state (ISSUE 16): class/quota always,
            # queue position + wait while parked, preemption history
            print("Scheduling:")
            print(f"  class:            {sched.get('priorityClass', '')}"
                  f"  quota: {sched.get('quotaGroup', '')}")
            if sched.get("phase") == "queued":
                line = f"position {sched.get('queuePosition', '?')}"
                since = sched.get("queuedSinceUnix")
                if since is not None:
                    line += f", waiting {max(0, time.time() - since):.0f}s"
                if sched.get("reason"):
                    line += f" ({sched['reason']})"
                print(f"  queued:           {line}")
            if sched.get("shedTo") is not None:
                print(f"  shedTo:           {sched['shedTo']} replicas")
            if sched.get("preemptions"):
                print(f"  preemptions:      {sched['preemptions']}")
            lp = sched.get("lastPreemption")
            if lp:
                print(f"  lastPreemption:   {lp.get('action', '')} "
                      f"({lp.get('reason', '')})")
    events = _request(
        "GET", _jobs_url(args.server, args.namespace, args.name, "events")
    )["items"]
    print("Events:")
    for e in events:
        print(f"  {e['type']:<8} {e['reason']:<24} {e['message']}")
    series = _request(
        "GET", _jobs_url(args.server, args.namespace, args.name, "metrics")
    ).get("items", [])
    if series:
        print(f"Metrics (last 10 of {len(series)}):")
        for m in series[-10:]:
            rest = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in m.items()
                if k not in ("step", "time")
            )
            print(f"  step {m['step']:<8} {rest}")
    return 0


def cmd_delete(args) -> int:
    _request("DELETE", _jobs_url(args.server, args.namespace, args.name))
    print(f"tpujob.dist/{args.name} deleted")
    return 0


def cmd_logs(args) -> int:
    out = _request(
        "GET",
        _jobs_url(args.server, args.namespace, args.name, f"pods/{args.pod}/log"),
    )
    print(out if isinstance(out, str) else json.dumps(out))
    return 0


def _fmt_signal_values(value: dict) -> str:
    return " ".join(
        f"{k}={v:.2f}" if isinstance(v, float) else f"{k}={v}"
        for k, v in value.items()
    )


def cmd_alerts(args) -> int:
    """kubectl-get-style view of GET /alerts: the server orders firing
    first (the Degraded-first convention — what needs acting on leads);
    with a RULE argument, a describe-style single-rule dump."""

    snap = _request("GET", f"{args.server}/alerts")
    items = snap.get("alerts", [])
    if args.rule:
        matches = [a for a in items if a["name"] == args.rule]
        if not matches:
            raise SystemExit(f"error: no alert rule named {args.rule!r}")
        a = matches[0]
        print(f"Name:      {a['name']}")
        print(f"State:     {a['state']}")
        print(f"Kind:      {a['kind']}")
        print(f"Metric:    {a['metric']}")
        print(f"Severity:  {a['severity']}")
        print(f"Episodes:  {a.get('episodes', 0)}")
        if a.get("labels"):
            print(f"Labels:    {a['labels']}")
        if a.get("value"):
            print(f"Value:     {_fmt_signal_values(a['value'])}")
        if a.get("message"):
            print(f"Message:   {a['message']}")
        return 0
    fmt = "{:<28} {:<10} {:<8} {:<10} {}"
    print(fmt.format("RULE", "STATE", "SEVERITY", "EPISODES", "VALUE"))
    for a in items:
        print(
            fmt.format(
                a["name"], a["state"], a["severity"],
                str(a.get("episodes", 0)),
                _fmt_signal_values(a.get("value", {})),
            )
        )
    firing = snap.get("firing", [])
    if firing:
        print(f"\n{len(firing)} firing: {', '.join(firing)}")
    return 0


def cmd_autoscaler(args) -> int:
    """GET /autoscaler: per-policy live state (breaching first, the
    server's ordering) and the decision log newest first; with a JOB
    argument, filtered to that job's policies and decisions."""

    snap = _request("GET", f"{args.server}/autoscaler")
    policies = snap.get("policies", [])
    decisions = snap.get("decisions", [])
    if args.job:
        want = args.job if "/" in args.job else f"{args.namespace}/{args.job}"
        policies = [p for p in policies if p["job"] == want]
        decisions = [d for d in decisions if d["job"] == want]
    fmt = "{:<24} {:<10} {:<8} {:<9} {:<8} {}"
    print(fmt.format("JOB", "TYPE", "DESIRED", "BREACHING", "RESHARD", "SIGNALS"))
    for p in policies:
        sig = " ".join(
            f"{name}:{'breach' if v.get('breaching') else 'ok'}"
            for name, v in sorted(p.get("signals", {}).items())
        )
        print(
            fmt.format(
                p["job"], p["replicaType"],
                "-" if p.get("desiredReplicas") is None else str(p["desiredReplicas"]),
                "yes" if p.get("breaching") else "no",
                "yes" if p.get("reshardPending") else "no",
                sig,
            )
        )
        if p.get("lastSkip"):
            print(f"  last skip: {p['lastSkip'].get('reason', '')}")
    if not policies:
        print("  (no autoscaled jobs)")
    print("\nDECISIONS (newest first):")
    for d in decisions[: args.limit]:
        print(
            f"  {d['job']:<24} {d['replicaType']:<10} {d['direction']:<5} "
            f"{d['from']} -> {d['to']}  {d['reason']}"
        )
    if not decisions:
        print("  (none)")
    return 0


def cmd_queue(args) -> int:
    """GET /scheduler: the fleet queue priority-then-age (the server's
    ordering — position 1 admits next), admitted gangs below it, and
    the decision log newest first; with a JOB argument, filtered to
    that job's queue entry and decisions."""

    snap = _request("GET", f"{args.server}/scheduler")
    queue = snap.get("queue", [])
    admitted = snap.get("admitted", [])
    decisions = snap.get("decisions", [])
    if args.job:
        want = args.job if "/" in args.job else f"{args.namespace}/{args.job}"
        queue = [q for q in queue if q["job"] == want]
        admitted = [a for a in admitted if a["job"] == want]
        decisions = [d for d in decisions if d["job"] == want]
    fmt = "{:<4} {:<24} {:<10} {:<16} {:<7} {:<9} {}"
    print(fmt.format("POS", "JOB", "CLASS", "QUOTA", "CHIPS", "WAIT(S)",
                     "REASON"))
    for q in queue:
        print(
            fmt.format(
                str(q["position"]), q["job"], q["priorityClass"],
                q["quotaGroup"], str(q["demandChips"]),
                f"{q['waitSeconds']:.0f}", q.get("reason", ""),
            )
        )
    if not queue:
        print("  (queue empty)")
    print("\nADMITTED:")
    for a in admitted:
        line = (
            f"  {a['job']:<24} {a['priorityClass']:<10} "
            f"{a['quotaGroup']:<16} {a['demandChips']} chips"
        )
        if a.get("shedTo") is not None:
            line += f"  shed to {a['shedTo']} replicas"
        print(line)
    if not admitted:
        print("  (none)")
    quotas = snap.get("quotas", {})
    if quotas and not args.job:
        print("\nQUOTAS:")
        for key, q in sorted(quotas.items()):
            limit = q.get("limitChips")
            print(f"  {key:<24} {q.get('usedChips', 0)}"
                  f"/{'-' if limit is None else limit} chips")
    print("\nDECISIONS (newest first):")
    for d in decisions[: args.limit]:
        print(
            f"  {d['job']:<24} {d['action']:<7} [{d['priorityClass']}]  "
            f"{d['reason']}"
        )
    if not decisions:
        print("  (none)")
    return 0


def cmd_telemetry(args) -> int:
    """GET /federate/targets: per-pod scrape state, stale-first (the
    server's ordering — what needs attention leads, the alerts /
    autoscaler subcommand convention); with a JOB argument, filtered
    to that job's targets."""

    snap = _request("GET", f"{args.server}/federate/targets")
    targets = snap.get("targets", [])
    if args.job:
        want = args.job if "/" in args.job else f"{args.namespace}/{args.job}"
        targets = [t for t in targets if t["job"] == want]
    fmt = "{:<24} {:<14} {:<8} {:<10} {:<10} {}"
    print(fmt.format("JOB", "REPLICA", "SLICE", "AGE(S)", "FAILURES", "STATE"))
    for t in targets:
        age = t.get("lastScrapeAgeSeconds")
        print(
            fmt.format(
                t["job"], t["replica"], t.get("slice") or "-",
                "-" if age is None else f"{age:.1f}",
                str(t.get("failures", 0)),
                "stale" if t.get("stale") else "ok",
            )
        )
    if not targets:
        print("  (no scrape targets)")
        return 0
    stale = sum(1 for t in targets if t.get("stale"))
    if stale:
        print(f"\n{stale}/{len(targets)} targets stale")
    fams = snap.get("families", [])
    if fams and not args.job:
        print(f"\nfederated families: {', '.join(fams)}")
    return 0


def cmd_fabric(args) -> int:
    """Cross-pod KV fabric state (ISSUE 17).

    Without a JOB argument, reads the serving pod's own
    ``GET /debug/fabric`` off ``--server`` (point it at a serve_lm
    address): peer table liveness-first plus the pull ledger.  With a
    JOB argument, resolves the job's pods through the operator API,
    reads each pod's reconciler-stamped ``tpujob.dist/fabric-port``
    annotation, and probes every fabric server's ``/fabric/index``
    directly — one catalog row per pod, unreachable servers flagged.
    """

    if not args.job:
        snap = _request("GET", f"{args.server}/debug/fabric")
        fab = snap.get("fabric", {})
        print(f"Model:      {snap.get('model', '')}")
        print(f"Advertise:  {fab.get('advertise', '') or '(not serving)'}")
        print(
            f"Catalog:    {fab.get('blocks', 0)} blocks "
            f"(generation {fab.get('generation', 0)}, "
            f"{fab.get('publishes', 0)} publishes, "
            f"{fab.get('evictions', 0)} evictions, "
            f"{fab.get('pin_expiries', 0)} pin expiries)"
        )
        pulls = fab.get("pulls", {})
        print(
            f"Pulls:      hit={pulls.get('hit', 0)} "
            f"miss={pulls.get('miss', 0)} failed={pulls.get('failed', 0)} "
            f"({fab.get('bytes_pulled', 0)} bytes over the wire)"
        )
        fails = fab.get("pull_failures", {})
        if fails:
            print("Failures:   " + " ".join(
                f"{r}={n}" for r, n in sorted(fails.items())
            ))
        peers = fab.get("peers", [])
        fmt = "{:<24} {:<8} {:<8} {}"
        print("\n" + fmt.format("PEER", "STATE", "KEYS", "GENERATION"))
        # down peers first — the what-needs-acting-on-leads convention
        for p in sorted(peers, key=lambda p: p.get("up") is not False):
            up = p.get("up")
            print(fmt.format(
                p.get("peer", ""),
                "unknown" if up is None else ("up" if up else "DOWN"),
                str(p.get("keys", 0)), str(p.get("generation", 0)),
            ))
        if not peers:
            print("  (no peers — local-only fabric)")
        return 0

    want_ns = args.namespace
    name = args.job
    if "/" in name:
        want_ns, name = name.split("/", 1)
    pods = _request(
        "GET", _jobs_url(args.server, want_ns, name, "pods")
    )["items"]
    fmt = "{:<24} {:<8} {:<8} {:<8} {:<12} {}"
    print(fmt.format("POD", "PORT", "STATE", "KEYS", "GENERATION",
                     "ADVERTISE"))
    rows = 0
    for pod in pods:
        port = (pod.get("annotations") or {}).get("tpujob.dist/fabric-port")
        if not port:
            continue
        rows += 1
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fabric/index", timeout=5
            ) as resp:
                idx = json.loads(resp.read())
            print(fmt.format(
                pod["name"], port, "up", str(len(idx.get("keys", []))),
                str(idx.get("generation", 0)), idx.get("advertise", ""),
            ))
        except (OSError, ValueError) as e:
            print(fmt.format(pod["name"], port, "DOWN", "-", "-", str(e)))
    if not rows:
        print("  (no pods carry a tpujob.dist/fabric-port annotation)")
    return 0


def _gib(nbytes) -> str:
    return "?" if nbytes is None else f"{nbytes / (1 << 30):.2f}Gi"


def _print_costplane(mem: dict, comp: dict, indent: str = "") -> None:
    """One process's cost-plane read: the HBM device table (the wire
    is already headroom-worst-first) then the compile-ledger digest."""

    devices = (mem or {}).get("devices", [])
    fmt = indent + "{:<28} {:<10} {:<10} {:<9} {}"
    print(fmt.format("DEVICE", "ACCOUNTED", "HEADROOM", "COVERAGE",
                     "COMPONENTS"))
    for d in devices:
        comps = " ".join(
            f"{c}={_gib(b)}"
            for c, b in sorted(
                (d.get("components") or {}).items(),
                key=lambda kv: -kv[1],
            )
            if b > 0
        )
        cov = d.get("coverage")
        print(fmt.format(
            d.get("device", "?"),
            _gib(d.get("accounted_bytes")),
            _gib(d.get("headroom_bytes")),
            "?" if cov is None else f"{100 * cov:.1f}%",
            comps or "-",
        ))
    if not devices:
        print(indent + "  (nothing accounted yet)")
    total = (comp or {}).get("total", 0)
    progs = sorted(
        ((comp or {}).get("byProgram") or {}).items(),
        key=lambda kv: -kv[1]["total"],
    )
    digest = " ".join(f"{p}:{s['total']}" for p, s in progs[:6])
    print(indent + f"compiles: {total}" + (f"  ({digest})" if digest else ""))


def cmd_top(args) -> int:
    """Device cost plane (ISSUE 20) — the fleet's HBM headroom and
    compile churn at a glance.

    Without a JOB argument, reads ``--server``'s own ``GET
    /debug/memory`` + ``GET /debug/compiles`` (the operator API and
    serve_lm both serve them).  With a JOB argument, resolves the
    job's pods through the operator API and probes every pod's
    reconciler-stamped ``tpujob.dist/telemetry-port`` — one section
    per pod, devices headroom-worst-first within each (the server's
    ordering), unreachable pods flagged rather than skipped."""

    if not args.job:
        mem = _request("GET", f"{args.server}/debug/memory")
        comp = _request("GET", f"{args.server}/debug/compiles")
        _print_costplane(mem, comp)
        return 0

    want_ns = args.namespace
    name = args.job
    if "/" in name:
        want_ns, name = name.split("/", 1)
    pods = _request(
        "GET", _jobs_url(args.server, want_ns, name, "pods")
    )["items"]
    rows = 0
    for pod in pods:
        port = (pod.get("annotations") or {}).get(
            "tpujob.dist/telemetry-port"
        )
        if not port:
            continue
        rows += 1
        print(f"{pod['name']} (telemetry :{port})")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/memory", timeout=5
            ) as resp:
                mem = json.loads(resp.read())
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/compiles", timeout=5
            ) as resp:
                comp = json.loads(resp.read())
        except (OSError, ValueError) as e:
            print(f"  UNREACHABLE: {e}")
            continue
        _print_costplane(mem, comp, indent="  ")
    if not rows:
        print("  (no pods carry a tpujob.dist/telemetry-port annotation)")
    return 0


def cmd_compile(args) -> int:
    from tf_operator_tpu.backend.gke import compile_manifest

    with open(args.filename) as f:
        manifest = yaml.safe_load(f)
    out = compile_manifest(manifest)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
        print(f"wrote {args.output}")
    else:
        print(out, end="")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpujob", description=__doc__.split("\n")[0])
    p.add_argument(
        "--server",
        default="http://127.0.0.1:8080",
        help="operator API address",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("submit", help="create a TPUJob from a manifest")
    sp.add_argument("-f", "--filename", required=True)
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--wait", action="store_true", help="block until terminal")
    sp.add_argument("--timeout", type=float, default=600.0)
    sp.set_defaults(fn=cmd_submit)

    cp = sub.add_parser(
        "compile", help="translate a TPUJob manifest to Kubernetes YAML"
    )
    cp.add_argument("-f", "--filename", required=True)
    cp.add_argument("-o", "--output", default="")
    cp.set_defaults(fn=cmd_compile)

    lp = sub.add_parser("list", help="list TPUJobs")
    lp.add_argument("-n", "--namespace", default="")
    lp.set_defaults(fn=cmd_list)

    ap = sub.add_parser("alerts", help="alert-engine state (firing first)")
    ap.add_argument("rule", nargs="?", default="")
    ap.set_defaults(fn=cmd_alerts)

    asp = sub.add_parser(
        "autoscaler", help="autoscaler decisions + policy state"
    )
    asp.add_argument("job", nargs="?", default="")
    asp.add_argument("-n", "--namespace", default="default")
    asp.add_argument("--limit", type=int, default=20,
                     help="decision-log rows shown")
    asp.set_defaults(fn=cmd_autoscaler)

    qp = sub.add_parser(
        "queue", help="fleet scheduler queue + decisions"
    )
    qp.add_argument("job", nargs="?", default="")
    qp.add_argument("-n", "--namespace", default="default")
    qp.add_argument("--limit", type=int, default=20,
                    help="decision-log rows shown")
    qp.set_defaults(fn=cmd_queue)

    tp = sub.add_parser(
        "telemetry", help="fleet scrape targets + federated families"
    )
    tp.add_argument("job", nargs="?", default="")
    tp.add_argument("-n", "--namespace", default="default")
    tp.set_defaults(fn=cmd_telemetry)

    fp = sub.add_parser(
        "fabric", help="cross-pod KV fabric catalogs + pull ledger"
    )
    fp.add_argument("job", nargs="?", default="")
    fp.add_argument("-n", "--namespace", default="default")
    fp.set_defaults(fn=cmd_fabric)

    top = sub.add_parser(
        "top", help="device cost plane: HBM headroom (worst first) "
                    "+ compile ledger"
    )
    top.add_argument("job", nargs="?", default="")
    top.add_argument("-n", "--namespace", default="default")
    top.set_defaults(fn=cmd_top)

    for name, fn, extra in (
        ("get", cmd_get, []),
        ("describe", cmd_describe, []),
        ("delete", cmd_delete, []),
        ("logs", cmd_logs, ["pod"]),
    ):
        cp = sub.add_parser(name)
        cp.add_argument("name")
        for a in extra:
            cp.add_argument(a)
        cp.add_argument("-n", "--namespace", default="default")
        cp.set_defaults(fn=fn)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
