"""``tpujob`` — the user-facing client CLI.

Parity: the reference's user flow is ``kubectl apply/get/describe/delete``
against the TFJob CRD plus the dashboard's list view (SURVEY.md §1 L6/L9).
This client speaks the operator's HTTP job API instead:

    tpujob submit -f job.yaml            # kubectl apply
    tpujob list [-n ns]                  # kubectl get tfjobs
    tpujob get NAME [-n ns]              # kubectl get tfjob NAME -o json
    tpujob describe NAME [-n ns]         # kubectl describe (status + events)
    tpujob delete NAME [-n ns]           # kubectl delete
    tpujob logs NAME POD [-n ns]         # kubectl logs (local backend)
    tpujob compile -f job.yaml           # TPUJob -> real Kubernetes YAML
                                         # (backend/gke.py; offline, no server)

Manifests are the serde camelCase shape, YAML or JSON.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

import yaml


def _request(method: str, url: str, payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method=method, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = resp.read().decode()
    except urllib.error.HTTPError as e:
        detail = e.read().decode()
        try:
            detail = json.loads(detail).get("error", detail)
        except ValueError:
            pass
        raise SystemExit(f"error: {e.code} {detail}")
    except urllib.error.URLError as e:
        raise SystemExit(f"error: cannot reach operator at {url}: {e.reason}")
    try:
        return json.loads(body)
    except ValueError:
        return body


def _jobs_url(server: str, ns: str, name: str = "", sub: str = "") -> str:
    url = f"{server}/apis/v1/namespaces/{ns}/tpujobs"
    if name:
        url += f"/{name}"
    if sub:
        url += f"/{sub}"
    return url


def _is_true(cond: dict) -> bool:
    # the wire format is the k8s-style string "True"/"False"
    return cond.get("status") in (True, "True")


def _condition_summary(job: dict) -> str:
    conds = job.get("status", {}).get("conditions", [])
    active = [c["type"] for c in conds if _is_true(c)]
    for terminal in ("Succeeded", "Failed"):
        if terminal in active:
            return terminal
    # live health outranks phase for a non-terminal job: a running job
    # burning its SLO budget shows Degraded, not Running
    if "Degraded" in active:
        return "Degraded"
    for c in reversed(conds):
        if _is_true(c):
            return c["type"]
    return "Pending"


def cmd_submit(args) -> int:
    with open(args.filename) as f:
        manifest = yaml.safe_load(f)
    ns = manifest.get("metadata", {}).get("namespace", args.namespace)
    job = _request("POST", _jobs_url(args.server, ns), manifest)
    name = job["metadata"]["name"]
    print(f"tpujob.dist/{name} created")
    if not args.wait:
        return 0
    deadline = time.time() + args.timeout
    while time.time() < deadline:
        job = _request("GET", _jobs_url(args.server, ns, name))
        phase = _condition_summary(job)
        if phase in ("Succeeded", "Failed"):
            print(f"tpujob.dist/{name} {phase}")
            return 0 if phase == "Succeeded" else 1
        time.sleep(1.0)
    print(f"tpujob.dist/{name} timed out after {args.timeout}s", file=sys.stderr)
    return 2


def cmd_list(args) -> int:
    if args.namespace == "":
        jobs = _request("GET", f"{args.server}/apis/v1/tpujobs")["items"]
    else:
        jobs = _request("GET", _jobs_url(args.server, args.namespace))["items"]
    fmt = "{:<12} {:<24} {:<12} {:<10}"
    print(fmt.format("NAMESPACE", "NAME", "STATE", "RESTARTS"))
    for j in jobs:
        print(
            fmt.format(
                j["metadata"].get("namespace", ""),
                j["metadata"].get("name", ""),
                _condition_summary(j),
                str(j.get("status", {}).get("restartCount", 0)),
            )
        )
    return 0


def cmd_get(args) -> int:
    job = _request("GET", _jobs_url(args.server, args.namespace, args.name))
    print(json.dumps(job, indent=2))
    return 0


def cmd_describe(args) -> int:
    job = _request("GET", _jobs_url(args.server, args.namespace, args.name))
    print(f"Name:      {job['metadata']['name']}")
    print(f"Namespace: {job['metadata'].get('namespace', '')}")
    print(f"State:     {_condition_summary(job)}")
    st = job.get("status", {})
    print("Replica statuses:")
    for rtype, rs in st.get("replicaStatuses", {}).items():
        print(
            f"  {rtype}: active={rs.get('active', 0)} "
            f"succeeded={rs.get('succeeded', 0)} failed={rs.get('failed', 0)}"
        )
    print("Conditions:")
    for c in st.get("conditions", []):
        print(
            f"  {c['type']:<12} {str(c.get('status')):<6} "
            f"{c.get('reason', ''):<24} {c.get('message', '')}"
        )
    health = st.get("observedHealth") or {}
    if health:
        # the live rollup the reconciler publishes (alert engine +
        # watchdog + checkpoint age): health, not just phase
        print("Health:")
        firing = health.get("firingAlerts", [])
        print(f"  firingAlerts:     {', '.join(firing) if firing else '(none)'}")
        for key, label in (
            ("throughputStepsPerSec", "throughput"),
            ("lastCheckpointAgeSeconds", "checkpointAge"),
            ("stallCount", "stalls"),
            ("restartCount", "restarts"),
        ):
            if key in health:
                print(f"  {label + ':':<18}{health[key]}")
    events = _request(
        "GET", _jobs_url(args.server, args.namespace, args.name, "events")
    )["items"]
    print("Events:")
    for e in events:
        print(f"  {e['type']:<8} {e['reason']:<24} {e['message']}")
    series = _request(
        "GET", _jobs_url(args.server, args.namespace, args.name, "metrics")
    ).get("items", [])
    if series:
        print(f"Metrics (last 10 of {len(series)}):")
        for m in series[-10:]:
            rest = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in m.items()
                if k not in ("step", "time")
            )
            print(f"  step {m['step']:<8} {rest}")
    return 0


def cmd_delete(args) -> int:
    _request("DELETE", _jobs_url(args.server, args.namespace, args.name))
    print(f"tpujob.dist/{args.name} deleted")
    return 0


def cmd_logs(args) -> int:
    out = _request(
        "GET",
        _jobs_url(args.server, args.namespace, args.name, f"pods/{args.pod}/log"),
    )
    print(out if isinstance(out, str) else json.dumps(out))
    return 0


def cmd_compile(args) -> int:
    from tf_operator_tpu.backend.gke import compile_manifest

    with open(args.filename) as f:
        manifest = yaml.safe_load(f)
    out = compile_manifest(manifest)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
        print(f"wrote {args.output}")
    else:
        print(out, end="")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpujob", description=__doc__.split("\n")[0])
    p.add_argument(
        "--server",
        default="http://127.0.0.1:8080",
        help="operator API address",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("submit", help="create a TPUJob from a manifest")
    sp.add_argument("-f", "--filename", required=True)
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--wait", action="store_true", help="block until terminal")
    sp.add_argument("--timeout", type=float, default=600.0)
    sp.set_defaults(fn=cmd_submit)

    cp = sub.add_parser(
        "compile", help="translate a TPUJob manifest to Kubernetes YAML"
    )
    cp.add_argument("-f", "--filename", required=True)
    cp.add_argument("-o", "--output", default="")
    cp.set_defaults(fn=cmd_compile)

    lp = sub.add_parser("list", help="list TPUJobs")
    lp.add_argument("-n", "--namespace", default="")
    lp.set_defaults(fn=cmd_list)

    for name, fn, extra in (
        ("get", cmd_get, []),
        ("describe", cmd_describe, []),
        ("delete", cmd_delete, []),
        ("logs", cmd_logs, ["pod"]),
    ):
        cp = sub.add_parser(name)
        cp.add_argument("name")
        for a in extra:
            cp.add_argument(a)
        cp.add_argument("-n", "--namespace", default="default")
        cp.set_defaults(fn=fn)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
