"""The operator binary — process entry point.

Parity: ``cmd/tf-operator.v1/main.go`` + ``app/server.go`` +
``app/options/options.go`` (SURVEY.md §2 "Operator entrypoint", §3.1):
flag parsing, backend/client setup, leader election, controller start
with ``--threadiness`` workers, monitoring/API port, graceful signal
shutdown.  The reference's flag set is mirrored where it still makes
sense without a kube-apiserver.

Run:  python -m tf_operator_tpu.cmd.operator --backend local --port 8080
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.local import LocalProcessBackend
from tf_operator_tpu.cmd.leader import FileLease
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig
from tf_operator_tpu.server.api import ApiServer
from tf_operator_tpu.utils import logging as oplog


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-operator",
        description="TPU-native distributed training job operator",
    )
    p.add_argument(
        "--backend",
        choices=["local", "fake"],
        default="local",
        help="cluster backend: local subprocesses or in-memory fake",
    )
    p.add_argument(
        "--namespace",
        default="",
        help="restrict the API surface to one namespace ('' = all)",
    )
    p.add_argument("--threadiness", type=int, default=4, help="reconcile workers")
    p.add_argument(
        "--enable-gang-scheduling",
        action="store_true",
        help="create gang groups and require all-or-nothing admission",
    )
    p.add_argument(
        "--monitoring-port",
        type=int,
        default=8080,
        help="port for /healthz /metrics and the job API (0 = ephemeral)",
    )
    p.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address.  TRUST MODEL: the job API is unauthenticated "
        "and POSTed manifests run as subprocesses on this host (local "
        "backend) — binding a non-loopback address exposes remote "
        "command execution to anyone who can reach the port",
    )
    p.add_argument(
        "--json-log", action="store_true", help="structured JSON log lines"
    )
    p.add_argument(
        "--leader-elect",
        action="store_true",
        help="gate the controller behind a file-lease leader election",
    )
    p.add_argument(
        "--lease-file",
        default="/tmp/tpu-operator-leader.lock",
        help="lease path for --leader-elect",
    )
    p.add_argument(
        "--log-dir", default=None, help="pod log directory (local backend)"
    )
    p.add_argument(
        "--total-chips",
        type=int,
        default=None,
        help="fake backend: chip capacity for gang admission tests",
    )
    p.add_argument("--version", action="store_true", help="print version and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        from tf_operator_tpu import __version__

        print(f"tpu-operator {__version__}")
        return 0

    oplog.configure(json_log=args.json_log)
    log = oplog.logger_for_job("-", "operator")

    store = JobStore()
    if args.backend == "local":
        backend = LocalProcessBackend(log_dir=args.log_dir)
        config = ReconcilerConfig(
            enable_gang_scheduling=args.enable_gang_scheduling,
            resolver=backend.resolver,
        )
    else:
        backend = FakeCluster(delivery="sync", total_chips=args.total_chips)
        config = ReconcilerConfig(
            enable_gang_scheduling=args.enable_gang_scheduling
        )

    if args.host not in ("127.0.0.1", "localhost", "::1"):
        log.warning(
            "binding %s: the job API is UNAUTHENTICATED and job manifests "
            "execute as local subprocesses — anyone who can reach this "
            "port can run commands as this user (see --host help)",
            args.host,
        )

    lease = None
    if args.leader_elect:
        lease = FileLease(args.lease_file, identity=f"pid-{os.getpid()}")

    controller = TPUJobController(store, backend, config=config)
    api = ApiServer(
        store,
        backend,
        controller.metrics,
        controller.recorder,
        host=args.host,
        port=args.monitoring_port,
        namespace=args.namespace,
        leadership=(
            None if lease is None else (lambda: (lease.is_leader, lease.holder()))
        ),
    )

    stop = threading.Event()

    def handle_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, handle_signal)
    signal.signal(signal.SIGINT, handle_signal)

    # monitoring/API surface is up regardless of leadership (reference
    # parity: the monitoring port serves on standbys too); only the
    # controller is gated behind the lease
    api.start()
    print(f"tpu-operator listening on {args.host}:{api.port}", flush=True)

    controller_started = False
    if lease is not None:
        log.info("waiting for leader lease at %s", args.lease_file)

    try:
        while not stop.is_set():
            if not controller_started and (lease is None or lease.try_acquire()):
                controller.run(threadiness=args.threadiness)
                controller_started = True
                log.info(
                    "controller up: backend=%s threadiness=%d native=%s leader=%s",
                    args.backend,
                    args.threadiness,
                    controller.native,
                    "yes" if lease else "n/a",
                )
            stop.wait(0.5)
    finally:
        if controller_started:
            controller.stop()
        api.stop()
        close = getattr(backend, "close", None)
        if close:
            close()
        if lease:
            lease.release()
        log.info("operator stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
