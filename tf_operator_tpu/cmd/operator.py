"""The operator binary — process entry point.

Parity: ``cmd/tf-operator.v1/main.go`` + ``app/server.go`` +
``app/options/options.go`` (SURVEY.md §2 "Operator entrypoint", §3.1):
flag parsing, backend/client setup, leader election, controller start
with ``--threadiness`` workers, monitoring/API port, graceful signal
shutdown.  The reference's flag set is mirrored where it still makes
sense without a kube-apiserver.

Run:  python -m tf_operator_tpu.cmd.operator --backend local --port 8080
  or: python -m tf_operator_tpu.cmd.operator --config examples/manifests/operator.yaml

Config-file layering (SURVEY.md §2 "Deploy manifests" equivalent):
built-in defaults < --config file < explicitly passed CLI flags.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.local import LocalProcessBackend
from tf_operator_tpu.cmd.leader import FileLease
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig
from tf_operator_tpu.server.api import ApiServer
from tf_operator_tpu.utils import logging as oplog


#: config-file key (camelCase, manifest style) -> argparse dest
CONFIG_KEYS = {
    "backend": "backend",
    "namespace": "namespace",
    "threadiness": "threadiness",
    "enableGangScheduling": "enable_gang_scheduling",
    "monitoringPort": "monitoring_port",
    "host": "host",
    "jsonLog": "json_log",
    "leaderElect": "leader_elect",
    "leaseFile": "lease_file",
    "leaseDuration": "lease_duration",
    "kubeUrl": "kube_url",
    "logDir": "log_dir",
    "totalChips": "total_chips",
}


def load_operator_config(path: str) -> dict:
    """Parse an operator config/deployment manifest into argparse dests.

    Accepts ``kind: OperatorConfig`` (flat keys) or
    ``kind: OperatorDeployment`` (keys under ``config:``; ``replicas``
    is consumed by cmd/deploy.py, not here).  Unknown keys are an error
    — a typoed key silently reverting to a default is how operators lose
    leader election in production.
    """

    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: config must be a mapping")
    kind = doc.get("kind", "OperatorConfig")
    if kind == "OperatorDeployment":
        cfg = doc.get("config", {}) or {}
    elif kind == "OperatorConfig":
        cfg = {k: v for k, v in doc.items() if k not in ("apiVersion", "kind")}
    else:
        raise ValueError(f"{path}: unknown kind {kind!r}")
    out = {}
    for key, value in cfg.items():
        if key not in CONFIG_KEYS:
            raise ValueError(
                f"{path}: unknown config key {key!r} (valid: {sorted(CONFIG_KEYS)})"
            )
        if value is None:
            continue  # null value = unset; the flag default applies
        out[CONFIG_KEYS[key]] = value

    # values must pass the same type=/choices= validation flags get —
    # set_defaults() bypasses argparse checking, so a `backend: kube`
    # or `threadiness: "four"` would otherwise slip through silently
    argv = []
    for dest, value in out.items():
        if value is None:
            continue
        flag = "--" + dest.replace("_", "-")
        if isinstance(value, bool):
            if value:
                argv.append(flag)
        else:
            argv += [flag, str(value)]
    probe = build_parser()
    probe.exit_on_error = False
    try:
        probe.parse_args(argv)
    except (argparse.ArgumentError, SystemExit) as e:
        raise ValueError(f"{path}: invalid config value: {e}") from None
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-operator",
        description="TPU-native distributed training job operator",
    )
    p.add_argument(
        "--config",
        default=None,
        help="operator config file (YAML/JSON; kind OperatorConfig or "
        "OperatorDeployment).  Explicit CLI flags override file values",
    )
    p.add_argument(
        "--backend",
        choices=["local", "fake", "kube-sim", "kube"],
        default="local",
        help="cluster backend: local subprocesses, in-memory fake, an "
        "embedded mini kube-apiserver spoken to over real Kubernetes "
        "HTTP (kube-sim), or an external apiserver at --kube-url "
        "speaking the same protocol (kube)",
    )
    p.add_argument(
        "--kube-url",
        default=None,
        help="apiserver base URL for --backend kube (e.g. "
        "http://127.0.0.1:6443)",
    )
    p.add_argument(
        "--namespace",
        default="",
        help="restrict the API surface to one namespace ('' = all)",
    )
    p.add_argument("--threadiness", type=int, default=4, help="reconcile workers")
    p.add_argument(
        "--enable-gang-scheduling",
        action="store_true",
        help="create gang groups and require all-or-nothing admission",
    )
    p.add_argument(
        "--monitoring-port",
        type=int,
        default=8080,
        help="port for /healthz /metrics and the job API (0 = ephemeral)",
    )
    p.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address.  TRUST MODEL: the job API is unauthenticated "
        "and POSTed manifests run as subprocesses on this host (local "
        "backend) — binding a non-loopback address exposes remote "
        "command execution to anyone who can reach the port",
    )
    p.add_argument(
        "--json-log", action="store_true", help="structured JSON log lines"
    )
    p.add_argument(
        "--leader-elect",
        action="store_true",
        help="gate the controller behind a file-lease leader election",
    )
    p.add_argument(
        "--lease-file",
        default="/tmp/tpu-operator-leader.lock",
        help="lease path for --leader-elect (local/fake backends)",
    )
    p.add_argument(
        "--lease-duration",
        type=float,
        default=15.0,
        help="Lease expiry in seconds for --leader-elect on kube "
        "backends (takeover latency after a leader crash)",
    )
    p.add_argument(
        "--log-dir", default=None, help="pod log directory (local backend)"
    )
    p.add_argument(
        "--total-chips",
        type=int,
        default=None,
        help="fake backend: chip capacity for gang admission tests",
    )
    p.add_argument("--version", action="store_true", help="print version and exit")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    # two-pass parse: --config values become parser defaults, so flags
    # passed explicitly on the command line still win
    pre, _ = parser.parse_known_args(argv)
    if pre.config:
        parser.set_defaults(**load_operator_config(pre.config))
    args = parser.parse_args(argv)
    if args.version:
        from tf_operator_tpu import __version__

        print(f"tpu-operator {__version__}")
        return 0

    oplog.configure(json_log=args.json_log)
    log = oplog.logger_for_job("-", "operator")


    store = JobStore()
    sim = None
    if args.backend == "local":
        backend = LocalProcessBackend(log_dir=args.log_dir)
        config = ReconcilerConfig(
            enable_gang_scheduling=args.enable_gang_scheduling,
            resolver=backend.resolver,
        )
    elif args.backend in ("kube-sim", "kube"):
        from tf_operator_tpu.backend.kube import KubeBackend
        from tf_operator_tpu.backend.kubejobs import KubeJobStore

        if args.backend == "kube-sim":
            from tf_operator_tpu.backend.kubesim import MiniApiServer

            sim = MiniApiServer(
                total_chips=args.total_chips, log_dir=args.log_dir
            ).start()
            # capacity revocations route through the fleet scheduler's
            # victim policy (lowest priority first) instead of LIFO
            from tf_operator_tpu.controller.scheduler import (
                default_scheduler as _sched,
            )

            sim.scheduler = _sched
            url = sim.url
            log.info("embedded mini apiserver listening on %s", url)
        else:
            if not args.kube_url:
                parser.error("--backend kube requires --kube-url")
            url = args.kube_url
        # jobs live IN the apiserver (the reference's TFJob-CRD tier):
        # operator restarts and leader failover resume them from there
        store = KubeJobStore(url)
        if args.backend == "kube-sim":
            # the embedded sim owns the chip pool server-side; surface
            # it as the backend's total_chips so the controller's
            # capacity probe — and with it fleet queueing/preemption —
            # tracks set_total_chips shrink/return live
            class _SimCapacityBackend(KubeBackend):
                @property
                def total_chips(self):
                    return sim.total_chips

            backend = _SimCapacityBackend(url)
        else:
            backend = KubeBackend(url)
        config = ReconcilerConfig(
            enable_gang_scheduling=args.enable_gang_scheduling,
            resolver=backend.resolver,
        )
    else:
        backend = FakeCluster(delivery="sync", total_chips=args.total_chips)
        config = ReconcilerConfig(
            enable_gang_scheduling=args.enable_gang_scheduling
        )

    if args.host not in ("127.0.0.1", "localhost", "::1"):
        log.warning(
            "binding %s: the job API is UNAUTHENTICATED and job manifests "
            "execute as local subprocesses — anyone who can reach this "
            "port can run commands as this user (see --host help)",
            args.host,
        )

    stop = threading.Event()

    lease = None
    if args.leader_elect:
        if args.backend in ("kube-sim", "kube"):
            # multi-host election through the apiserver itself
            # (coordination.k8s.io/v1 Lease, compare-and-swap on
            # resourceVersion).  Lost leadership = shut down, the
            # client-go OnStoppedLeading convention: a controller
            # reconciling without the lease would fight the new
            # leader's writes.
            from tf_operator_tpu.cmd.leader import KubeLease

            def _lost():
                log.warning("leader lease lost: shutting down")
                stop.set()

            lease = KubeLease(
                url, identity=f"pid-{os.getpid()}", on_lost=_lost,
                lease_duration=args.lease_duration,
            )
        else:
            lease = FileLease(args.lease_file, identity=f"pid-{os.getpid()}")

    recorder = None
    if args.backend in ("kube-sim", "kube"):
        # events are REAL v1 Event objects in the apiserver: visible
        # to external tooling and to the next leader after a failover
        from tf_operator_tpu.backend.kubejobs import KubeEventRecorder

        recorder = KubeEventRecorder(url)

    # SLO alert engine (utils/alerts.py): the stock burn-rate +
    # threshold rules evaluated over the controller's registry on a
    # background thread.  The controller rolls the firing set into
    # TPUJob.status (Degraded condition + observedHealth) and the API
    # serves GET /alerts; a pending→firing transition dumps the flight
    # recorder once per episode.  The PROCESS-GLOBAL default_engine
    # (default rules over default_metrics — exactly this binary's
    # registry) is used rather than a private instance so kubesim's
    # own /alerts debug route reports the engine that actually runs,
    # not a never-started twin.
    from tf_operator_tpu.utils.alerts import default_engine as alert_engine

    # elastic autoscaler (controller/autoscaler.py): consumes the alert
    # engine + metrics registry and scales jobs that declare
    # spec.autoscaling — serving replicas into pressure, training
    # replicas elastically (re-shard + checkpoint resume) away from
    # distress.  The PROCESS-GLOBAL default_autoscaler for the same
    # reason the engine is: kubesim's /autoscaler debug route must
    # report the instance that actually runs.
    from tf_operator_tpu.controller.autoscaler import (
        default_autoscaler as autoscaler,
    )

    # fleet telemetry scraper (controller/telemetry.py): discovers the
    # pod-side exporters the reconciler injects ports for, federates
    # their pod-scope families into this registry (so the alert engine,
    # autoscaler and health rollup see the FLEET), and stitches pod
    # traces into the operator store.  PROCESS-GLOBAL for the same
    # reason the engine/autoscaler are: /federate must report the
    # instance that actually runs.
    from tf_operator_tpu.controller.telemetry import (
        default_scraper as telemetry,
    )

    # fleet scheduler (controller/scheduler.py): priority quota queues +
    # cross-job gang preemption for jobs that declare spec.scheduling.
    # PROCESS-GLOBAL for the same reason the autoscaler is: kubesim's
    # /scheduler debug route and the operator's GET /scheduler must
    # report the instance that actually runs.
    from tf_operator_tpu.controller.scheduler import (
        default_scheduler as scheduler,
    )

    controller = TPUJobController(
        store, backend, config=config, recorder=recorder,
        alerts=alert_engine, autoscaler=autoscaler, telemetry=telemetry,
        scheduler=scheduler,
    )
    api = ApiServer(
        store,
        backend,
        controller.metrics,
        controller.recorder,
        alerts=alert_engine,
        autoscaler=autoscaler,
        telemetry=telemetry,
        scheduler=scheduler,
        host=args.host,
        port=args.monitoring_port,
        namespace=args.namespace,
        leadership=(
            None
            if lease is None
            # holder() can be a blocking apiserver GET (KubeLease):
            # only look it up on the 503 path, never per leader request
            else (
                lambda: (True, None)
                if lease.is_leader
                else (False, lease.holder())
            )
        ),
    )

    def handle_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, handle_signal)
    signal.signal(signal.SIGINT, handle_signal)

    # black-box flight recorder: recent spans/logs/metric deltas dump
    # on SIGTERM (chaining into the graceful-shutdown handler above)
    # and on fatal exceptions; /debug/flightrecorder serves the rings
    # live.  TPUJOB_WATCHDOG=1 starts the stall monitor on top.
    from tf_operator_tpu.utils import flight
    from tf_operator_tpu.utils.watchdog import maybe_start_from_env

    flight.install(metrics=controller.metrics)
    maybe_start_from_env(metrics=controller.metrics)
    alert_engine.start()
    autoscaler.start()
    scheduler.start()
    telemetry.start()

    # monitoring/API surface is up regardless of leadership (reference
    # parity: the monitoring port serves on standbys too); only the
    # controller is gated behind the lease
    api.start()
    print(f"tpu-operator listening on {args.host}:{api.port}", flush=True)

    controller_started = False
    if lease is not None:
        log.info(
            "waiting for leader lease (%s)",
            "apiserver Lease" if args.backend in ("kube-sim", "kube")
            else args.lease_file,
        )

    try:
        while not stop.is_set():
            if not controller_started and (lease is None or lease.try_acquire()):
                controller.run(threadiness=args.threadiness)
                controller_started = True
                log.info(
                    "controller up: backend=%s threadiness=%d native=%s leader=%s",
                    args.backend,
                    args.threadiness,
                    controller.native,
                    "yes" if lease else "n/a",
                )
            stop.wait(0.5)
    finally:
        telemetry.stop()
        scheduler.stop()
        autoscaler.stop()
        alert_engine.stop()
        if controller_started:
            controller.stop()
        api.stop()
        close = getattr(backend, "close", None)
        if close:
            close()
        store_close = getattr(store, "close", None)
        if store_close:
            store_close()
        if recorder is not None:
            recorder.close()  # drain the async event buffer
        # release BEFORE stopping the embedded apiserver: a KubeLease
        # hand-off is an HTTP call to it
        if lease:
            lease.release()
        if sim is not None:
            sim.stop()
        log.info("operator stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
