"""ResNet for ImageNet-scale benchmarks.

Parity: the reference's headline configs run ResNet-50 under
MultiWorkerMirroredStrategy (4 GPU workers) and Horovod+NCCL (8 workers)
— BASELINE.md configs 2 and 4; the rebuild's north star is
"TFJob-launched ResNet-50 on v5e-16" (BASELINE.json).  Written
TPU-first: bfloat16 activations (MXU-native), float32 params and
batch-norm statistics, NHWC layout (XLA's preferred conv layout on TPU),
no data-dependent control flow.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last norm's scale: residual branch starts as
        # identity, the standard trick for large-batch ResNet training
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: Callable
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        x = conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(
                    filters=self.width * 2**i, conv=conv, norm=norm, strides=strides
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block=BasicBlock, num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block=BottleneckBlock, num_classes=num_classes, **kw)
