"""ResNet for ImageNet-scale benchmarks.

Parity: the reference's headline configs run ResNet-50 under
MultiWorkerMirroredStrategy (4 GPU workers) and Horovod+NCCL (8 workers)
— BASELINE.md configs 2 and 4; the rebuild's north star is
"TFJob-launched ResNet-50 on v5e-16" (BASELINE.json).  Written
TPU-first: bfloat16 activations (MXU-native), float32 params and
batch-norm statistics, NHWC layout (XLA's preferred conv layout on TPU),
no data-dependent control flow.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.ops.fused_batchnorm import (
    FUSEDBN_IMPLS,
    fused_batchnorm,
    fusedbn_available,
)

ModuleDef = Any

#: ``ResNet.norm_impl`` spellings (``interpret`` is sugar for the
#: ladder name, same aliasing serve_lm uses for ``--paged-kernel``)
_NORM_IMPL_ALIASES = {"interpret": "pallas-interpret"}


class BatchNorm(nn.Module):
    """Train-mode BatchNorm with the block epilogue (ReLU / residual
    add) fused in — the module face of ``ops.fused_batchnorm`` (ISSUE
    19 tentpole).

    Deliberately named ``BatchNorm``: flax auto-naming derives scopes
    from the class name, so instances land in the same ``BatchNorm_i``
    scopes as ``flax.linen.BatchNorm`` — param/stat trees stay
    isomorphic between ``norm="batchnorm"`` and ``norm="fused"``
    models (checkpoints interchange, ``fold_batchnorm``'s scope map
    keeps working, and stock-vs-fused trainer comparisons need no
    tree surgery).  Same variables, same shapes, same initializers:
    ``params/{scale,bias}`` and ``batch_stats/{mean,var}`` at
    ``param_dtype`` / f32.

    Train mode routes through ``fused_batchnorm`` with the module's
    ``impl`` (already RESOLVED by the caller — "auto" never reaches
    here, the PR 10 fail-don't-downgrade rule lives in ``ResNet``).
    Eval mode is the running-stats affine composition regardless of
    ``impl`` — a documented contract, not a downgrade: with no batch
    reductions there is no stats pass to fuse, and the real eval-mode
    answer is ``bn_fold`` (PR 14), which removes the BN entirely.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    impl: str = "xla"
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, relu: bool = False, residual: Optional[jax.Array] = None):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), self.param_dtype)
        bias = self.param("bias", self.bias_init, (c,), self.param_dtype)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (c,)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (c,)
        )
        if self.use_running_average:
            # eval: nn.BatchNorm's exact _normalize op order on the
            # running stats, epilogue appended
            y = x - ra_mean.value
            mul = jax.lax.rsqrt(ra_var.value + self.epsilon)
            mul = mul * scale
            y = y * mul
            y = y + bias
            y = y.astype(self.dtype)
            if residual is not None:
                y = residual + y
            if relu:
                y = nn.relu(y)
            return y
        y, mean, var = fused_batchnorm(
            x,
            scale,
            bias,
            eps=self.epsilon,
            relu=relu,
            residual=residual,
            impl=self.impl,
        )
        if not self.is_initializing():
            # flax's exact running-stats update; mean/var are the
            # primitive's bookkeeping outputs (cotangent-free by the
            # VJP contract — batch_stats is mutable state, jax.grad
            # never sees this)
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        return y


FusedBatchNorm = BatchNorm


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """NHWC space-to-depth: [N,H,W,C] -> [N,H/b,W/b,b*b*C].

    Channel order is (row-in-block, col-in-block, C)-major, matching the
    kernel transform in `_SpaceToDepthStem`.
    """

    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


class _SpaceToDepthStem(nn.Module):
    """The ResNet 7x7/stride-2 stem, computed on space-to-depth input.

    The canonical stem conv (7x7, stride 2, 3 input channels) is
    MXU-hostile: 3 channels against a 128-wide systolic array, and the
    spatial stride defeats XLA's window tiling.  The standard TPU fix
    (used by MLPerf ResNet submissions) is to transform the input
    [N,224,224,3] -> [N,112,112,12] and convolve with an equivalent
    4x4/stride-1 kernel.  The parameter keeps the canonical [7,7,3,F]
    layout so checkpoints are interchangeable with the conv7 stem; the
    kernel transform below is exact (zero-padded tap -1), so outputs are
    bit-comparable to the plain conv up to reduction order.
    """

    features: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, 3, self.features),
            jnp.float32,
        )
        # pad taps 7->8 so tap index t = 2p+s splits into cell offset
        # p (0..3) and subpixel s (0..1); original tap d = t-1
        k = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k = k.reshape(4, 2, 4, 2, 3, self.features)
        k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 12, self.features)
        x = space_to_depth(x, 2)
        return jax.lax.conv_general_dilated(
            x,
            k.astype(self.dtype),
            window_strides=(1, 1),
            padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=self.dtype,
        )


class BottleneckBlock(nn.Module):
    """Blocks hand the whole epilogue to the norm factory: every norm
    call site passes ``relu=`` / ``residual=`` so ``norm="fused"`` can
    run BN+ReLU(+add) as ONE kernel while ``norm="batchnorm"`` expands
    to the identical stock op sequence (``bn → [+residual] → relu``).
    The projection branch is computed BEFORE the last norm call (the
    epilogue consumes it); flax param rngs are path-keyed, so the
    creation-order shift changes no initial values or scope names."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y, relu=True)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y, relu=True)
        y = self.conv(self.filters * 4, (1, 1))(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        # zero-init the last norm's scale: residual branch starts as
        # identity, the standard trick for large-batch ResNet training
        return self.norm(scale_init=nn.initializers.zeros_init())(
            y, relu=True, residual=residual
        )


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y, relu=True)
        y = self.conv(self.filters, (3, 3))(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.norm(scale_init=nn.initializers.zeros_init())(
            y, relu=True, residual=residual
        )


class _Identity(nn.Module):
    """Stand-in for a folded-away BatchNorm (``ResNet.bn_fold``): the
    normalization lives inside the preceding conv's kernel/bias."""

    @nn.compact
    def __call__(self, x):
        return x


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: Callable
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    stem: str = "conv7"  # conv7 | space_to_depth
    #: BatchNorm scale/bias/stat dtype.  float32 is the safe default;
    #: bfloat16 is a profiling experiment (benchmarks/mfu_sweep.py
    #: "bnbf16") probing whether the f32 BN chains between bf16 convs
    #: are a material slice of the step (benchmarks/PROFILE.md)
    bn_param_dtype: jnp.dtype = jnp.float32
    #: eval-mode BN-fold (ISSUE 14 satellite / ROADMAP item 2): the
    #: inference graph with every BatchNorm folded into its conv's
    #: kernel + bias (``fold_batchnorm`` maps trained variables onto
    #: this variant) — the whole convert/reduce/elementwise BN chain
    #: the FLOPS.md trace table shows dominating the step disappears
    #: from the graph.  Inference-only by construction: training needs
    #: live batch statistics, so train=True refuses.
    bn_fold: bool = False
    #: train-mode norm flavor (ISSUE 19 tentpole): ``"batchnorm"`` is
    #: the stock ``nn.BatchNorm`` + separate ReLU/add graph;
    #: ``"fused"`` routes every BN call site (+its ReLU/residual
    #: epilogue) through ``ops.fused_batchnorm`` — one two-sweep kernel
    #: per layer instead of the reduce/convert/elementwise chains the
    #: FLOPS.md trace table shows carrying ~83% of the train step
    norm: str = "batchnorm"
    #: fused-norm impl: ``auto`` resolves to the pallas kernel on a
    #: single-device TPU backend and the (bit-comparable) xla
    #: composition elsewhere; explicit ``xla`` | ``pallas`` |
    #: ``interpret``/``pallas-interpret`` are honored or REFUSED with a
    #: config-class ValueError — never silently downgraded (PR 10 rule,
    #: pinned like batching's ``paged_kernel`` validation order)
    norm_impl: str = "auto"

    def _resolve_norm(self) -> "str | None":
        """Validate ``norm``/``norm_impl`` and resolve the fused impl.

        Validation order is the ``paged_kernel`` contract (ISSUE 10
        honesty, pinned in tests/test_fused_batchnorm.py): a bad NAME
        fails as a bad name even when the config is also unservable —
        (1) norm flavor, (2) impl spelling, (3) semantic conflicts,
        (4) kernel availability.  Returns the resolved impl for
        ``norm="fused"``, else None."""

        kind = str(self.norm or "batchnorm").lower()
        if kind not in ("batchnorm", "fused"):
            raise ValueError(
                f"norm must be 'batchnorm'|'fused', got {self.norm!r}"
            )
        req = str(self.norm_impl or "auto").lower()
        req = _NORM_IMPL_ALIASES.get(req, req)
        if req not in ("auto",) + FUSEDBN_IMPLS:
            raise ValueError(
                "norm_impl must be auto|xla|pallas|interpret"
                f"|pallas-interpret, got {self.norm_impl!r}"
            )
        if kind == "batchnorm":
            if req != "auto":
                raise ValueError(
                    f"norm_impl={self.norm_impl!r} applies to "
                    "norm='fused' only — an ignored impl request is a "
                    "silent downgrade"
                )
            return None
        if self.bn_fold:
            raise ValueError(
                "norm='fused' conflicts with bn_fold=True — the fold "
                "removes every BatchNorm from the eval graph; the fused "
                "kernel is the TRAIN-side story"
            )
        if req == "auto":
            ok, _why = fusedbn_available()
            return "pallas" if ok and jax.device_count() == 1 else "xla"
        if req != "xla":
            ok, why = fusedbn_available(interpret=req == "pallas-interpret")
            if not ok:
                raise ValueError(
                    f"norm='fused' norm_impl={self.norm_impl!r} refused: "
                    f"{why} — failing loudly instead of silently "
                    "downgrading to the xla composition"
                )
            if req == "pallas" and jax.device_count() > 1:
                raise ValueError(
                    "norm='fused' norm_impl='pallas' refused: the kernel "
                    f"reduces per shard, but {jax.device_count()} devices "
                    "are visible and train-mode BatchNorm must see batch-"
                    "GLOBAL statistics under pjit — use norm_impl='xla' "
                    "(XLA inserts the cross-device reduction) on "
                    "multi-device meshes"
                )
        return req

    @nn.compact
    def __call__(self, x, train: bool = False):
        fused_impl = self._resolve_norm()
        if self.bn_fold:
            if train:
                raise ValueError(
                    "bn_fold is an eval-mode (inference) path — training "
                    "needs live batch statistics"
                )
            if self.stem == "space_to_depth":
                raise ValueError("bn_fold supports the conv7 stem only")
            # biased convs carry the folded affine; norms become no-ops
            # (the epilogue — residual add + relu — is block semantics,
            # not BN, and stays)
            conv = partial(nn.Conv, use_bias=True, dtype=self.dtype)

            def norm(name=None, **_kw):
                def apply(y, relu=False, residual=None):
                    y = _Identity(name=name)(y)
                    if residual is not None:
                        y = residual + y
                    if relu:
                        y = nn.relu(y)
                    return y

                return apply

        elif fused_impl is not None:
            conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

            def norm(name=None, **kw):
                def apply(y, relu=False, residual=None):
                    return BatchNorm(
                        use_running_average=not train,
                        momentum=0.9,
                        epsilon=1e-5,
                        dtype=self.dtype,
                        param_dtype=self.bn_param_dtype,
                        impl=fused_impl,
                        name=name,
                        **kw,
                    )(y, relu=relu, residual=residual)

                return apply

        else:
            conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

            def norm(name=None, **kw):
                def apply(y, relu=False, residual=None):
                    # the stock graph, op for op: bn → +residual → relu
                    y = nn.BatchNorm(
                        use_running_average=not train,
                        momentum=0.9,
                        epsilon=1e-5,
                        dtype=self.dtype,
                        param_dtype=self.bn_param_dtype,
                        name=name,
                        **kw,
                    )(y)
                    if residual is not None:
                        y = residual + y
                    if relu:
                        y = nn.relu(y)
                    return y

                return apply

        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = _SpaceToDepthStem(self.width, dtype=self.dtype, name="conv_init")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x, relu=True)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(
                    filters=self.width * 2**i, conv=conv, norm=norm, strides=strides
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


#: conv scope -> the norm scope folded into it (flax auto-naming is
#: per-type, so Conv_i pairs with BatchNorm_i inside every block; the
#: explicitly named projection/stem pairs are listed outright)
def _norm_scope_for(conv_scope: str) -> "str | None":
    if conv_scope.startswith("Conv_"):
        return "BatchNorm_" + conv_scope[len("Conv_"):]
    return {"conv_proj": "norm_proj", "conv_init": "bn_init"}.get(conv_scope)


def _is_norm_scope(name: str) -> bool:
    return name.startswith("BatchNorm_") or name in ("norm_proj", "bn_init")


def fold_batchnorm(variables, eps: float = 1e-5):
    """Map trained ``{params, batch_stats}`` onto the parameters of the
    same architecture with ``bn_fold=True``.

    The standard inference transform: ``BN(conv(x)) ==
    conv'(x) + bias'`` with ``kernel' = kernel * gamma/sqrt(var+eps)``
    (broadcast over the output-channel dim of HWIO) and ``bias' =
    beta - mean * gamma/sqrt(var+eps)``.  Computed in f32 and stored at
    the conv's original param dtype — the folded model's logits match
    the unfolded eval pass up to reduction-order float noise (pinned in
    tests/test_models.py).  ``eps`` must match the model's BatchNorm
    epsilon."""

    def fold_pair(conv_p, norm_p, norm_s):
        kernel = jnp.asarray(conv_p["kernel"], jnp.float32)
        gamma = jnp.asarray(norm_p["scale"], jnp.float32)
        beta = jnp.asarray(norm_p["bias"], jnp.float32)
        mean = jnp.asarray(norm_s["mean"], jnp.float32)
        var = jnp.asarray(norm_s["var"], jnp.float32)
        scale = gamma / jnp.sqrt(var + eps)
        out_dtype = jnp.asarray(conv_p["kernel"]).dtype
        return {
            "kernel": (kernel * scale).astype(out_dtype),
            "bias": (beta - mean * scale).astype(out_dtype),
        }

    def walk(p, s):
        out = {}
        for name, sub in p.items():
            if _is_norm_scope(name):
                continue  # folded into its conv below
            norm_scope = _norm_scope_for(name)
            if norm_scope is not None and norm_scope in p:
                out[name] = fold_pair(sub, p[norm_scope], s[norm_scope])
            elif hasattr(sub, "items") and "kernel" not in sub:
                out[name] = walk(sub, s.get(name, {}))
            else:
                out[name] = sub  # Dense head and friends
        return out

    return {"params": walk(variables["params"], variables.get("batch_stats", {}))}


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block=BasicBlock, num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block=BottleneckBlock, num_classes=num_classes, **kw)
