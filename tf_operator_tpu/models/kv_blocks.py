"""Block-granular KV-cache accounting for paged serving (ISSUE 8).

The continuous-batching pool's original per-seat cache pins
``max_len`` KV positions per seat whether a request needs 20 tokens or
2000 — HBM, not compute, caps concurrency.  Paged serving (the vLLM
move, re-shaped for XLA's static-shape world) splits the cache into
fixed-size token BLOCKS over one pre-allocated device arena; a seat
holds a block *table* (logical block i → physical block id) and
admission is gated on blocks free, not slots free.

This module is the HOST side of that story: a free-list allocator with
O(1) alloc/free and per-block refcounts.  Refcounts make copy-free
prefix sharing safe — a block mapped by a live seat AND published in
the prefix cache (models/prefix_cache.py) carries one reference per
holder, and returns to the free list only when the last holder
releases it.  The device side (arena layout, block-table gather/
scatter inside the compiled programs) lives in models/decode.py; the
pool that drives both is models/batching.py's
``PagedContinuousBatchingDecoder``.

Block id 0 is the SCRATCH block: it is never allocated, every unused
block-table entry points at it, and padded/overshoot writes land in it
— reads of scratch content are always masked by ``cache_index``, so
its garbage is never observable.  The allocator therefore manages ids
``1 .. num_blocks-1``.

Conservation invariant (test-pinned, tests/test_kv_blocks.py): at all
times ``free + live == usable`` with no id both free and referenced —
no double-free, no aliasing across live holders.

Speculative decoding (ISSUE 18) allocates its DRAFT model's KV chains
from this same arena: a speculating seat holds a target chain and a
draft chain, both visible to admission pressure and both released on
retire/preempt, so speculation costs blocks the allocator can account
for — never a hidden second cache.  The conservation invariant covers
draft chains too (tests/test_speculative_paged.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


#: the global scratch block id (see module docstring)
SCRATCH_BLOCK = 0


class BlockError(RuntimeError):
    """An allocator-contract violation (double free, release of a
    never-allocated id).  Raised loudly: silent refcount corruption is
    cross-request cache ALIASING, the worst serving bug there is."""


class NotPageableError(ValueError):
    """This MODEL cannot be paged (rolling-window wrap state aliases
    positions; unrecognised cache layout) — serve it through the
    contiguous pool.  Distinct from plain ValueError so callers
    (serve_lm's auto-fallback) can downgrade ONLY for model-shape
    reasons; operator configuration errors (bad --kv-blocks /
    --kv-block-size) stay fatal instead of silently losing the paged
    capacity they asked for."""


class BlockAllocator:
    """Free-list + refcount bookkeeping over ``num_blocks`` arena rows.

    Thread-safe (one lock; every method is O(ids) with O(1) per id).
    ``alloc`` returns None on shortfall instead of raising so callers
    can evict/queue — admission backpressure is the caller's policy.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (scratch + at least one "
                f"usable block), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        # LIFO free list: most-recently-freed block is reused first
        # (warm pages on a real memory system; determinism in tests)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}  # bid -> refcount; absent = free

    # -- queries -----------------------------------------------------------

    @property
    def usable(self) -> int:
        """Blocks the allocator manages (everything but scratch)."""

        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        with self._lock:
            return len(self._refs)

    def pressure(self) -> float:
        """in_use / usable — the blocks-free pressure signal the stock
        serving autoscaling policy binds (controller/autoscaler.py)."""

        with self._lock:
            return len(self._refs) / (self.num_blocks - 1)

    def refcount(self, bid: int) -> int:
        with self._lock:
            return self._refs.get(bid, 0)

    def check(self) -> None:
        """Assert the conservation invariant (cheap; tests call it
        after every random op)."""

        with self._lock:
            free = set(self._free)
            if len(free) != len(self._free):
                raise BlockError("free list holds a duplicate id")
            if free & set(self._refs):
                raise BlockError("an id is both free and referenced")
            if SCRATCH_BLOCK in free or SCRATCH_BLOCK in self._refs:
                raise BlockError("scratch block entered the allocator")
            if len(free) + len(self._refs) != self.num_blocks - 1:
                raise BlockError(
                    f"conservation broken: {len(free)} free + "
                    f"{len(self._refs)} live != {self.num_blocks - 1}"
                )

    # -- lifecycle ---------------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks at refcount 1, or None when fewer than
        ``n`` are free (nothing is allocated on shortfall — all or
        nothing, so a failed admission never leaks)."""

        if n < 0:
            raise ValueError(f"alloc({n})")
        with self._lock:
            if len(self._free) < n:
                return None
            ids = [self._free.pop() for _ in range(n)]
            for bid in ids:
                self._refs[bid] = 1
            return ids

    def retain(self, ids: List[int]) -> None:
        """+1 reference per id (prefix-cache publication, mapping a
        shared block into another seat's table)."""

        with self._lock:
            for bid in ids:
                if bid not in self._refs:
                    raise BlockError(f"retain of unallocated block {bid}")
                self._refs[bid] += 1

    def release(self, ids: List[int]) -> int:
        """-1 reference per id; ids reaching 0 return to the free
        list.  Returns how many blocks were actually freed."""

        freed = 0
        with self._lock:
            for bid in ids:
                rc = self._refs.get(bid)
                if rc is None:
                    raise BlockError(f"double free of block {bid}")
                if rc == 1:
                    del self._refs[bid]
                    self._free.append(bid)
                    freed += 1
                else:
                    self._refs[bid] = rc - 1
        return freed


class SwapArena:
    """Host-side store of swapped-out KV blocks (ISSUE 12).

    Mid-decode preemption frees a victim seat's device blocks by
    parking their CONTENT here: one record per preempted request,
    holding the gathered host copies of its private (refcount-1)
    blocks plus the bookkeeping resume needs (the donation-safe
    device→host snapshot pattern from parallel/checkpoint.py, applied
    per-block).  Prefix-cache-shared blocks are swap-EXEMPT — they
    stay device-resident under their surviving refcounts and re-map
    copy-free at resume — so a record covers only blocks nothing else
    holds.

    ``capacity_blocks`` bounds the host footprint (None = unbounded —
    the default; host RAM dwarfs the arena).  ``admit`` answers
    whether a prospective swap fits; a full swap arena means the
    scheduler PARKS the grower instead of preempting (the documented
    "queue, never crash" honesty rule — docs/SERVING.md).

    Conservation (test-pinned, tests/test_kv_blocks.py): device
    ``free + live`` plus this arena's ``swapped_blocks`` accounts for
    every logical block any request owns — a preempted request's
    committed set is exactly its swapped records + its swap-exempt
    live blocks.
    """

    def __init__(self, capacity_blocks: Optional[int] = None):
        self.capacity_blocks = (
            None if capacity_blocks is None else int(capacity_blocks)
        )
        self._lock = threading.Lock()
        self._records: Dict[int, Dict[str, Any]] = {}  # rid -> record
        self.swapped_blocks = 0
        self.bytes_out_total = 0  # cumulative device→host
        self.bytes_in_total = 0   # cumulative host→device (resumes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def admit(self, n_blocks: int) -> bool:
        """Would ``n_blocks`` more swapped blocks fit the cap?"""

        if self.capacity_blocks is None:
            return True
        with self._lock:
            return self.swapped_blocks + int(n_blocks) <= self.capacity_blocks

    def put(self, rid: int, record: Dict[str, Any], n_blocks: int,
            nbytes: int) -> None:
        """Store a preempted request's swap record (keyed by pool
        rid).  ``n_blocks``/``nbytes`` are the PRIVATE blocks actually
        copied (exempt blocks stay on device and count zero here)."""

        with self._lock:
            if rid in self._records:
                raise BlockError(f"request {rid} already has a swap record")
            record = dict(record)
            record["n_blocks"] = int(n_blocks)
            self._records[rid] = record
            self.swapped_blocks += int(n_blocks)
            self.bytes_out_total += int(nbytes)

    def peek(self, rid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._records.get(rid)

    def pop(self, rid: int, nbytes: int = 0) -> Dict[str, Any]:
        """Remove and return the record at resume (its blocks are
        being uploaded back into freshly allocated device blocks)."""

        with self._lock:
            rec = self._records.pop(rid, None)
            if rec is None:
                raise BlockError(f"request {rid} has no swap record")
            self.swapped_blocks -= rec["n_blocks"]
            self.bytes_in_total += int(nbytes)
            return rec

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "records": len(self._records),
                "swapped_blocks": self.swapped_blocks,
                "capacity_blocks": self.capacity_blocks,
                "bytes_out_total": self.bytes_out_total,
                "bytes_in_total": self.bytes_in_total,
            }


class ArenaTimeline:
    """Bounded ring of block-arena occupancy samples — the time-series
    twin of the instantaneous ``kv_blocks_pressure`` gauge (ISSUE 11).

    The gauge answers "how full is the arena NOW"; a stuck p99 needs
    "how full was it while THAT request waited".  The paged pool
    records one sample per decode window (plus admission/retire gauge
    refreshes) — host arithmetic only, nothing touches the device, so
    the no-hot-sync gate over the paged step loop is unaffected.
    Served at ``GET /debug/arena`` on serve_lm, rendered as an
    occupancy strip on the dashboard, and the tail rides every
    flight-recorder dump (a post-mortem shows the pressure history,
    not just the final value).

    Sample shape (all counts in BLOCKS): ``unix``, ``free``, ``live``
    (allocated: seat-mapped + cache-held), ``prefix_cached`` (blocks
    held by the prefix cache — a subset of live), ``queued_demand``
    (block need of queued-but-unadmitted requests), ``seats_active``,
    and ``swapped`` (blocks whose content currently lives host-side in
    the SwapArena — ISSUE 12: without this series a preempted
    request's occupancy history would silently truncate at its first
    eviction).
    """

    def __init__(self, capacity: int = 512, block_size: int = 0,
                 usable: int = 0, replica: str = "", role: str = "unified"):
        self.capacity = max(1, int(capacity))
        self.block_size = int(block_size)
        self.usable = int(usable)
        self.replica = str(replica)
        #: ISSUE 13: the replica's phase role — a disaggregated fleet's
        #: /debug/arena strips are read per role (a prefill replica's
        #: occupancy is churn, a decode replica's is residency)
        self.role = str(role)
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=self.capacity)
        self.dropped = 0  # samples aged out of the ring

    def sample(
        self,
        *,
        free: int,
        live: int,
        prefix_cached: int,
        queued_demand: int,
        seats_active: int,
        swapped: int = 0,
    ) -> None:
        rec = {
            "unix": time.time(),
            "free": int(free),
            "live": int(live),
            "prefix_cached": int(prefix_cached),
            "queued_demand": int(queued_demand),
            "seats_active": int(seats_active),
            "swapped": int(swapped),
        }
        with self._lock:
            # consecutive identical samples collapse to the first: an
            # IDLE pool refreshes gauges every driver tick (~200/s),
            # and letting that flood the ring would age real
            # transitions out within seconds of going quiet
            if self._samples:
                last = self._samples[-1]
                if all(last[k] == rec[k] for k in rec if k != "unix"):
                    return
            if len(self._samples) == self._samples.maxlen:
                self.dropped += 1
            self._samples.append(rec)

    def tail(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Oldest-first copy of the most recent ``limit`` samples.
        ``None`` = all retained; ``limit <= 0`` = none — never the
        whole ring (the ``[-0:]`` pitfall, same guard as
        RequestLog.recent)."""

        with self._lock:
            items = list(self._samples)
        if limit is None:
            return items
        return items[-limit:] if limit > 0 else []

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """The ``/debug/arena`` read: ring metadata + the sample tail
        (``limit`` semantics as in :meth:`tail`)."""

        with self._lock:
            samples = list(self._samples)
            dropped = self.dropped
        if limit is not None:
            samples = samples[-limit:] if limit > 0 else []
        return {
            "replica": self.replica,
            "role": self.role,
            "block_size": self.block_size,
            "usable": self.usable,
            "capacity": self.capacity,
            "dropped": dropped,
            "samples": samples,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions (ceil division)."""

    return -(-int(tokens) // int(block_size))
