"""Decoder-only causal LM — the long-context flagship.

The reference has no long-context story (SURVEY.md §2b: SP/CP "absent");
this framework makes it first-class: when the config's mesh has sp > 1,
self-attention runs as exact ring attention over the sequence shards
(ops/ring_attention.py), so context length scales with the sp axis while
per-chip KV memory stays O(S/sp).
"""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.transformer import (
    ACT_HIDDEN,
    DecoderLayer,
    Embed,
    LayerNorm,
    TransformerConfig,
    logical_constraint,
    param_with_axes,
)


class CausalLM(nn.Module):
    SUPPORTS_DECODE = True  # autoregressive: models/decode.py can drive it
    SUPPORTS_QTENSOR = True  # dense stack is QDenseGeneral (llama.py note)

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False):
        cfg = self.cfg
        _, s = input_ids.shape
        embed = Embed(cfg, name="tok_embed")
        x = embed(input_ids)
        pos = self.param(
            "pos_embed",
            param_with_axes(nn.initializers.normal(0.02), ("seq", "embed")),
            (cfg.max_len, cfg.hidden),
            jnp.float32,
        )
        if cfg.decode:
            # decode mode: the position slice starts at the running
            # index (the MHA layers keep the authoritative K/V cache;
            # this mirrors their index for the learned table)
            pos_idx = self.variable("cache", "pos_index", lambda: jnp.array(0, jnp.int32))
            i = pos_idx.value
            x = x + jax.lax.dynamic_slice(pos, (i, 0), (s, pos.shape[1]))[None].astype(cfg.dtype)
            pos_idx.value = i + s
        else:
            x = x + pos[None, :s].astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = logical_constraint(x, ACT_HIDDEN)
        for i in range(cfg.n_layers):
            x = DecoderLayer(cfg, cross=False, name=f"layer_{i}")(x, train=train)
        x = LayerNorm(cfg, rms=True, name="ln_final")(x)
        # tied LM head: decode with the embedding table
        logits = embed.attend(x)
        return logits.astype(jnp.float32)


def gpt_small(vocab_size: int = 50257, max_len: int = 1024, mesh=None) -> CausalLM:
    """GPT-2 small shape (124M)."""
    return CausalLM(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=768,
            n_heads=12,
            head_dim=64,
            n_layers=12,
            mlp_dim=3072,
            max_len=max_len,
            mesh=mesh,
        )
    )


def gpt_tiny(vocab_size: int = 1024, max_len: int = 256, mesh=None, **kw) -> CausalLM:
    return CausalLM(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=128,
            n_heads=4,
            head_dim=32,
            n_layers=2,
            mlp_dim=512,
            max_len=max_len,
            mesh=mesh,
            **kw,
        )
    )


def lm_loss(
    params, state, batch: Dict, rng, train: bool = True
) -> Tuple[jax.Array, Dict]:
    """Next-token loss; batch: input_ids [B, S].  train=False gives the
    inference-mode (no dropout) loss for Trainer.eval_step."""

    logits = state.apply_fn(
        {"params": params}, batch["input_ids"], train=train, rngs={"dropout": rng}
    )
    targets = batch["input_ids"][:, 1:]
    logits = logits[:, :-1]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()
    acc = (logits.argmax(-1) == targets).mean()
    return loss, {"metrics": {"token_accuracy": acc}}
