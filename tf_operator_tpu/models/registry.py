"""Self-describing serving artifacts: serialize a decoder family +
config to a plain dict and reconstruct the model from it.

The reference has no model/serving story at all (SURVEY.md §0); this
framework's export→serve leg should not require the server operator to
re-specify the architecture by hand (a mismatched reconstruction fails
at restore time at best, silently at worst).  `export_params` writes
`model.json` via `describe_model`; `serve_lm` rebuilds the exact
architecture via `model_from_description`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax.numpy as jnp

from tf_operator_tpu.models.gpt import CausalLM
from tf_operator_tpu.models.llama import LlamaLM
from tf_operator_tpu.models.moe import MoeConfig, MoeLM
from tf_operator_tpu.models.transformer import TransformerConfig

_FAMILIES = {"gpt": CausalLM, "llama": LlamaLM}


def _cfg_to_dict(cfg: TransformerConfig) -> Dict[str, Any]:
    d = dataclasses.asdict(dataclasses.replace(cfg, mesh=None))
    d.pop("mesh")
    d.pop("decode")  # a serving description is never decode-pinned
    d["dtype"] = jnp.dtype(d["dtype"]).name
    return d


def describe_model(model) -> Optional[Dict[str, Any]]:
    """JSON-safe description of a decoder-family model, or None for
    families without a serving story (encoders, pipelined)."""

    if isinstance(model, MoeLM):
        moe_d = {
            f.name: getattr(model.moe, f.name)
            for f in dataclasses.fields(MoeConfig)
            if f.name != "base"
        }
        return {
            "family": "moe",
            "moe": moe_d,
            "config": _cfg_to_dict(model.moe.base),
        }
    for name, cls in _FAMILIES.items():
        if type(model) is cls:
            return {"family": name, "config": _cfg_to_dict(model.cfg)}
    return None


def model_from_description(
    d: Dict[str, Any], max_len: Optional[int] = None, mesh=None
):
    """Rebuild the exact exported architecture.  ``max_len`` overrides
    the cache length (a server may cap it below the training length);
    ``mesh`` attaches a serving mesh for sharded decode."""

    cfg_d = dict(d["config"])
    cfg_d["dtype"] = jnp.dtype(cfg_d["dtype"])
    if max_len is not None:
        if max_len > cfg_d["max_len"] and not cfg_d.get("rope"):
            # learned position tables have exactly max_len rows; decode
            # past them silently clamps the dynamic slice and reuses
            # the last embeddings — wrong samples, no error.  Only the
            # rope families are defined past their training length.
            raise ValueError(
                f"max_len={max_len} exceeds the trained length "
                f"{cfg_d['max_len']} and family {d['family']!r} uses a "
                f"learned position table — extension is only defined "
                f"for rope models"
            )
        cfg_d["max_len"] = max_len
    cfg = TransformerConfig(mesh=mesh, **cfg_d)
    family = d["family"]
    if family == "moe":
        return MoeLM(MoeConfig(base=cfg, **d["moe"]))
    if family not in _FAMILIES:
        raise ValueError(
            f"unknown model family {family!r}; known: "
            f"{sorted(_FAMILIES) + ['moe']}"
        )
    return _FAMILIES[family](cfg)
