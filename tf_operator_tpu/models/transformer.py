"""Shared transformer components, written for GSPMD sharding.

Every parameter carries logical-axis metadata
(`nn.with_logical_partitioning`) so the trainer can lay the model out
over the named mesh via parallel/sharding.py's LOGICAL_RULES:
megatron-style tensor parallelism (heads/mlp/vocab → tp), ZeRO-style
param sharding (embed → fsdp), sequence parallelism (seq → sp, with
exact ring attention from ops/ring_attention.py).

These components back the BERT (models/bert.py), T5 (models/t5.py) and
causal-LM (models/gpt.py) families — the reference's BERT/T5 target
workloads (BASELINE.md configs 3 and 5) plus the long-context flagship.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tf_operator_tpu.ops import attention, ring_attention, ulysses_attention
from tf_operator_tpu.ops.rotary import apply_rope

param_with_axes = nn.with_logical_partitioning
logical_constraint = nn.with_logical_constraint

ACT_HIDDEN = ("batch", "seq", "act_embed")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32128
    hidden: int = 768
    n_heads: int = 12
    head_dim: int = 64
    n_layers: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dropout: float = 0.1
    dtype: Any = jnp.bfloat16
    # sequence parallelism: mesh to run sharded attention over (None or
    # sp=1 → plain fused attention); sp_impl picks the schedule —
    # "ring" (ppermute K/V hops, S scales unbounded) or "ulysses"
    # (all-to-all head re-shard; needs heads-per-shard % sp == 0)
    mesh: Optional[Mesh] = None
    sp_impl: str = "ring"
    # grouped-query attention: number of K/V heads (None = MHA). K/V
    # are repeated to n_heads before attention dispatch, so GQA
    # composes with ring/ulysses/flash unchanged.
    n_kv_heads: Optional[int] = None
    # rotary position embeddings (llama-style) applied to q/k inside
    # attention; models that set this skip learned position embeddings
    rope: bool = False
    rope_theta: float = 10000.0
    # biases on the attention projections (q/k/v/out).  True = GPT/BERT
    # convention; llama-class models set False; qwen-class would keep
    # True with rope=True — the two knobs are independent.
    attn_bias: bool = True
    # sliding-window (mistral-style) local attention: position i sees
    # [i - window + 1, i].  Causal self-attention only (encoder
    # self-attention raises; cross-attention ignores it); the flash
    # kernels band their grids so FLOPs AND K/V DMA are O(S * window).
    # Composes with sp: ulysses runs the banded kernels on its full
    # local sequence; the ring masks by global offsets (XLA path).
    window: Optional[int] = None
    # autoregressive decode mode: self-attention layers maintain a
    # [B, Hkv, max_len, D] K/V cache ("cache" collection) written at
    # the running index — static shapes throughout, so the whole
    # generate loop jits into one XLA program (models/decode.py).
    # DELIBERATE (ADVICE r3): decode IGNORES sp_impl/sp meshes — the
    # sequence-parallel schedules shard the TRAINING sequence axis,
    # while cached decode queries are s_new<=prompt_len against an
    # unsharded cache, where plain masked attention is the correct
    # (and only sensible) schedule.  An sp-trained model generates
    # fine; its sp mesh axes simply don't participate.  This is a
    # documented no-op, not a silent downgrade: raising here would
    # break generation for every sp-trained model.
    decode: bool = False
    # paged decode (ISSUE 10): self-attention reads K/V straight from
    # the block arena through per-seat block tables instead of a
    # per-seat contiguous cache.  None = contiguous decode; otherwise
    # the ops/paged_attention impl name ("xla" reference / "pallas"
    # kernel / "pallas-interpret" for CI).  The cache collection is
    # built EXTERNALLY (models/decode.paged_arena + the pool's table
    # injection); requires decode=True, batch = seats, s_new = 1.
    paged: Optional[str] = None

    def __post_init__(self):
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_impl must be 'ring' or 'ulysses', got {self.sp_impl!r}"
            )
        if self.paged is not None:
            from tf_operator_tpu.ops.paged_attention import PAGED_IMPLS

            if self.paged not in PAGED_IMPLS:
                raise ValueError(
                    f"paged must be None or one of {PAGED_IMPLS}, "
                    f"got {self.paged!r}"
                )
            if not self.decode:
                raise ValueError("paged attention requires decode=True")
            if self.window is not None and self.window < self.max_len:
                raise ValueError(
                    "rolling-window caches are not pageable (wrap state "
                    "aliases positions)"
                )
        if self.n_kv_heads is not None and self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_heads ({self.n_heads}) must be a multiple of "
                f"n_kv_heads ({self.n_kv_heads})"
            )
        if self.window is not None and self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")

    @property
    def sp_enabled(self) -> bool:
        return self.mesh is not None and self.mesh.shape.get("sp", 1) > 1


class QDenseGeneral(nn.Module):
    """DenseGeneral that also accepts int8 `QTensor` kernels at apply
    time (the serving path: `quantize_tree` → apply, no
    `materialize_tree` — the weight crosses HBM as int8 and
    `ops/quant_matmul` dequantizes per tile in VMEM).

    For plain array kernels this reproduces `nn.DenseGeneral` exactly:
    same param names ('kernel'/'bias'), same shapes, same init calls —
    flax derives param RNG from the scope path only, so existing
    checkpoints and seeded tests see identical parameters.  Only the
    contract-the-last-axes form is implemented (`axis=-1` or
    `(-2, -1)`), which is every call site in this stack."""

    features: Any  # int | tuple
    axis: Any = -1  # int | tuple, must be the trailing axes in order
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        feat = (
            tuple(self.features)
            if isinstance(self.features, (tuple, list))
            else (self.features,)
        )
        axes = (
            tuple(self.axis) if isinstance(self.axis, (tuple, list))
            else (self.axis,)
        )
        n_con = len(axes)
        axes = tuple(a % x.ndim for a in axes)
        if axes != tuple(range(x.ndim - n_con, x.ndim)):
            raise NotImplementedError(
                f"QDenseGeneral contracts trailing axes only, got {axes}"
            )
        in_shape = tuple(x.shape[-n_con:])
        kernel = self.param("kernel", self.kernel_init, in_shape + feat, jnp.float32)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, feat, jnp.float32)
        else:
            bias = None
        from tf_operator_tpu.ops.quant import QTensor
        from tf_operator_tpu.ops.quant_matmul import quant_matmul

        if isinstance(kernel, QTensor):
            k_flat = 1
            for d in in_shape:
                k_flat *= d
            x2 = x.reshape(*x.shape[:-n_con], k_flat).astype(self.dtype)
            qt = QTensor(kernel.q.reshape(k_flat, *feat), kernel.scale)
            out = quant_matmul(x2, qt, dtype=self.dtype)
        else:
            out = jax.lax.dot_general(
                x.astype(self.dtype),
                jnp.asarray(kernel, self.dtype),
                ((axes, tuple(range(n_con))), ((), ())),
            )
        if bias is not None:
            out = out + jnp.asarray(bias, self.dtype)
        return out


def dense(features, cfg: TransformerConfig, axes, name=None, use_bias=True):
    n_feature_dims = len(features) if isinstance(features, (tuple, list)) else 1
    return QDenseGeneral(
        features,
        dtype=cfg.dtype,
        use_bias=use_bias,
        kernel_init=param_with_axes(nn.initializers.lecun_normal(), axes),
        bias_init=param_with_axes(nn.initializers.zeros_init(), axes[-n_feature_dims:]),
        name=name,
    )


class LayerNorm(nn.Module):
    cfg: TransformerConfig
    use_bias: bool = True  # False → RMSNorm-ish (T5 uses RMSNorm)
    rms: bool = False

    @nn.compact
    def __call__(self, x):
        if self.rms:
            return nn.RMSNorm(
                dtype=self.cfg.dtype,
                scale_init=param_with_axes(nn.initializers.ones_init(), ("norm",)),
            )(x)
        return nn.LayerNorm(
            dtype=self.cfg.dtype,
            use_bias=self.use_bias,
            scale_init=param_with_axes(nn.initializers.ones_init(), ("norm",)),
            bias_init=param_with_axes(nn.initializers.zeros_init(), ("norm",)),
        )(x)


class Embed(nn.Module):
    """Token embedding with optional logit-tying (attend method)."""

    cfg: TransformerConfig
    features: Optional[int] = None

    @nn.compact
    def __call__(self, ids):
        cfg = self.cfg
        table = self.param(
            "embedding",
            param_with_axes(nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, self.features or cfg.hidden),
            jnp.float32,
        )
        from tf_operator_tpu.ops.quant import QTensor

        if isinstance(table, QTensor):
            # int8 row gather + per-embed-channel rescale: the table
            # crosses HBM as the gathered int8 rows only — never as a
            # materialized bf16 copy (the decode-loop trap, see
            # ops/quant_matmul.py)
            rows = jnp.take(table.q, ids, axis=0).astype(cfg.dtype)
            return rows * table.scale.reshape(-1).astype(cfg.dtype)
        return jnp.take(table, ids, axis=0).astype(cfg.dtype)

    def attend(self, x):
        from tf_operator_tpu.ops.quant import QTensor

        table = self.get_variable("params", "embedding")
        value = getattr(table, "value", table)  # unbox nn.Partitioned
        if isinstance(value, QTensor):
            # scale is per embed channel (the CONTRACTED axis here), so
            # it applies to x before the int8 contraction:
            # x @ (q·s)^T == (x·s) @ q^T
            xs = x * value.scale.reshape(-1).astype(x.dtype)
            return jnp.einsum("bse,ve->bsv", xs, value.q.astype(x.dtype))
        return jnp.einsum("bse,ve->bsv", x, value.astype(x.dtype))


class MultiHeadAttention(nn.Module):
    """Self- or cross-attention; sequence-parallel attention (ring or
    ulysses per cfg.sp_impl) when the config's mesh has sp > 1
    (self-attention only)."""

    cfg: TransformerConfig
    causal: bool = False

    @nn.compact
    def __call__(self, x, kv: Optional[jax.Array] = None, mask=None, bias=None, train=False):
        cfg = self.cfg
        is_self = kv is None
        kv_in = x if is_self else kv
        h, d = cfg.n_heads, cfg.head_dim
        hkv = cfg.n_kv_heads or h
        bias_p = cfg.attn_bias
        q = dense((h, d), cfg, ("embed", "heads", "kv"), name="query", use_bias=bias_p)(x)
        k = dense((hkv, d), cfg, ("embed", "heads", "kv"), name="key", use_bias=bias_p)(kv_in)
        v = dense((hkv, d), cfg, ("embed", "heads", "kv"), name="value", use_bias=bias_p)(kv_in)
        # [B,S,H,D] -> [B,H,S,D]; heads over tp, seq over sp
        q, k, v = (jnp.transpose(a, (0, 2, 1, 3)) for a in (q, k, v))

        if cfg.decode and is_self and cfg.paged is not None:
            # PAGED decode (ISSUE 10): batch = seats, one token per
            # seat.  K/V live in the per-layer block ARENA
            # [NB, Hkv, bs, D] addressed through per-seat block tables;
            # the new token's K/V is appended IN PLACE to its seat's
            # block (no contiguous view, no scatter-back), and
            # attention runs straight off the arena
            # (ops/paged_attention — the Pallas kernel or its
            # bit-exact XLA reference, per cfg.paged).  The cache
            # collection is built externally (decode.paged_arena +
            # decode.paged_cache_tree) — batch-1 init shapes would be
            # wrong here, so missing leaves raise.
            from tf_operator_tpu.ops.paged_attention import (
                paged_attention,
                paged_attention_multi,
            )

            if mask is not None or bias is not None:
                raise ValueError(
                    "paged decode builds its own masks; caller-supplied "
                    "mask/bias is not supported"
                )
            # s_new == 1 is the plain decode step; s_new == K > 1 is the
            # speculative VERIFY window (ISSUE 18): K draft tokens are
            # appended per seat and scored in ONE multi-query dispatch.
            # Prefill still runs through the gathered-view admission
            # path (models/batching.py) — this branch never sees it.
            seats, _, s_new, _ = q.shape

            def _missing(name):
                def init(*a):
                    raise ValueError(
                        f"paged decode cache leaf {name!r} missing — the "
                        "cache collection must be built via models/"
                        "decode.paged_arena + paged_cache_tree, not init()"
                    )
                return init

            arena_k = self.variable("cache", "cached_key", _missing("cached_key"))
            arena_v = self.variable("cache", "cached_value", _missing("cached_value"))
            idx_var = self.variable("cache", "cache_index", _missing("cache_index"))
            tbl_var = self.variable("cache", "block_tables", _missing("block_tables"))
            lengths = idx_var.value  # [S] tokens already cached per seat
            tables = tbl_var.value  # [S, MB] int32
            bs = arena_k.value.shape[2]
            mb = tables.shape[1]
            pos = lengths  # each seat's FIRST new token position
            if cfg.rope:
                # per-seat absolute positions ([S,1,K] broadcasts over
                # heads) — same rotation the contiguous branch applies
                # per slot; token t of the window sits at pos+t
                q, k = apply_rope(
                    q, k,
                    positions=pos[:, None, None]
                    + jnp.arange(s_new, dtype=pos.dtype)[None, None, :],
                    theta=cfg.rope_theta,
                )
            # in-place append: seat s writes token t's K/V row into
            # physical block tables[s, (pos+t)//bs] at offset
            # (pos+t)%bs.  Seats own their tail blocks exclusively
            # (admission reserves prompt+budget; shared prefix blocks
            # are all strictly before the first write position), so
            # only SCRATCH ids can collide across seats — and drifted/
            # overshot positions (retired seats between windows,
            # post-budget steps, rejected speculative appends past the
            # table) are routed to scratch explicitly, whose content is
            # never observable (length-masked).
            poss = pos[:, None] + jnp.arange(s_new, dtype=pos.dtype)[None, :]
            li = jnp.clip(poss // bs, 0, mb - 1)
            bids = jnp.take_along_axis(tables, li, axis=1)  # [S, K]
            bids = jnp.where(poss < mb * bs, bids, 0)  # SCRATCH_BLOCK
            offs = poss % bs
            # k/v are [S, Hkv, K, D] -> [S, K, Hkv, D] rows; advanced
            # indexing over (bids, offs) scatters all K appends at once
            arena_k.value = arena_k.value.at[bids, :, offs, :].set(
                jnp.transpose(k, (0, 2, 1, 3)).astype(arena_k.value.dtype)
            )
            arena_v.value = arena_v.value.at[bids, :, offs, :].set(
                jnp.transpose(v, (0, 2, 1, 3)).astype(arena_v.value.dtype)
            )
            idx_var.value = pos + s_new
            if s_new == 1:
                out = paged_attention(
                    q[:, :, 0, :], arena_k.value, arena_v.value, tables,
                    pos + 1, impl=cfg.paged,
                )  # [S, H, D]
                return self._project_out(out[:, None, :, :], train)
            out = paged_attention_multi(
                jnp.transpose(q, (0, 2, 1, 3)), arena_k.value,
                arena_v.value, tables, pos + s_new, impl=cfg.paged,
            )  # [S, K, H, D]
            return self._project_out(out, train)

        if cfg.decode and is_self:
            if mask is not None or bias is not None:
                raise ValueError(
                    "decode mode builds its own causal/fill mask; "
                    "caller-supplied mask/bias (e.g. ragged-prompt "
                    "padding) is not supported — left-align prompts"
                )
            # autoregressive cache: new K/V written at the running
            # index (hkv width — GQA cache stays small), q attends to
            # every filled slot.  Works uniformly for prefill
            # (s_new = prompt len) and decode steps (s_new = 1).
            #
            # Sliding-window models get a ROLLING cache: only `window`
            # slots are ever visible, so the cache is a circular buffer
            # of that size — serving memory O(window) instead of
            # O(max_len), the decode counterpart of the banded training
            # kernels.  Each slot remembers its absolute position
            # (cached_pos) so masking stays exact across wraps; RoPE is
            # applied at write time with absolute positions, so wrapped
            # slots need no re-rotation.
            b, _, s_new, _ = q.shape
            rolling = cfg.window is not None and cfg.window < cfg.max_len
            cache_len = cfg.window if rolling else cfg.max_len
            if rolling and s_new > cache_len:
                raise ValueError(
                    f"windowed rolling decode prefills at most window="
                    f"{cfg.window} tokens per apply (got {s_new}); feed "
                    "the prompt in chunks <= window — models/decode.py's "
                    "generate()/ChunkedServingDecoder do this"
                )
            cached_k = self.variable(
                "cache", "cached_key", jnp.zeros, (b, hkv, cache_len, d), k.dtype
            )
            cached_v = self.variable(
                "cache", "cached_value", jnp.zeros, (b, hkv, cache_len, d), v.dtype
            )
            cache_idx = self.variable(
                "cache", "cache_index", lambda: jnp.array(0, jnp.int32)
            )
            idx = cache_idx.value
            row_pos = idx + jnp.arange(s_new)
            if cfg.rope:
                q, k = apply_rope(q, k, positions=row_pos, theta=cfg.rope_theta)
            if rolling:
                # Attend over [PRE-write buffer, current chunk]: an
                # in-chunk write may land in the slot of an old key
                # that EARLIER rows of this chunk still see (the band
                # reaches back W-1 from each row), so the buffer must
                # be read before any write.  Every position needed by
                # any row is then present exactly once: the pre-write
                # buffer holds the latest position per slot among
                # those < idx (older same-slot positions were already
                # dead to the band), and the chunk carries idx..idx+s-1.
                # Per-slot absolute positions (-1 = empty) drive the
                # mask, so wraps need no special cases.
                cached_pos = self.variable(
                    "cache", "cached_pos",
                    lambda: jnp.full((cache_len,), -1, jnp.int32),
                )
                old_k, old_v = cached_k.value, cached_v.value
                old_pos = cached_pos.value
                slots = (idx + jnp.arange(s_new)) % cache_len
                cached_k.value = old_k.at[:, :, slots].set(k)
                cached_v.value = old_v.at[:, :, slots].set(v)
                cached_pos.value = old_pos.at[slots].set(row_pos)
                k = jnp.concatenate([old_k, k], axis=2)
                v = jnp.concatenate([old_v, v], axis=2)
                kpos = jnp.concatenate([old_pos, row_pos])[None, :]
                qpos = row_pos[:, None]
                vis = (kpos >= 0) & (kpos <= qpos) & (qpos - kpos < cfg.window)
            else:
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, k, (0, 0, idx, 0)
                )
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, v, (0, 0, idx, 0)
                )
                # the dispatcher's attention impls are GQA-native — the
                # Hkv-width cache is consumed directly, never expanded
                k, v = cached_k.value, cached_v.value
                # causal over absolute positions; unfilled slots masked;
                # sliding window drops slots behind the band
                cols = jnp.arange(cache_len)[None, :]
                vis = cols <= row_pos[:, None]
                if cfg.window is not None:
                    vis &= row_pos[:, None] - cols < cfg.window
            cache_idx.value = idx + s_new
            dec_mask = vis[None, None]
            out = attention(q, k, v, mask=dec_mask, mesh=cfg.mesh)
            out = jnp.transpose(out, (0, 2, 1, 3))
            return self._project_out(out, train)

        if cfg.rope and is_self:
            q, k = apply_rope(q, k, theta=cfg.rope_theta)
        q, k, v = (
            logical_constraint(a, ("batch", "act_heads", "seq", "act_kv")) for a in (q, k, v)
        )
        if cfg.window is not None and is_self and not self.causal:
            raise NotImplementedError(
                "sliding-window attention is defined for causal "
                "self-attention; encoder self-attention does not "
                "support it (cross-attention layers ignore it)"
            )
        use_sp = cfg.sp_enabled and is_self and bias is None and mask is None
        if use_sp:
            # GQA-aware schedules: K/V enter at Hkv width and travel
            # the ring / all-to-all that way (the h/hkv bandwidth
            # saving), expanding only inside the local block compute.
            # window composes on BOTH schedules and both ring impls:
            # ulysses applies the banded kernels to its full local
            # sequence; the ring classifies hops by global offsets
            # (banded diagonal kernel / plain kernel in-band / XLA
            # boundary blocks / skipped band-out) on the flash path,
            # and masks per block on the XLA path
            sp_attn = ulysses_attention if cfg.sp_impl == "ulysses" else ring_attention
            out = sp_attn(q, k, v, cfg.mesh, causal=self.causal, window=cfg.window)
        else:
            # dispatcher: pallas flash kernel on TPU when it applies,
            # XLA-fused reference otherwise; the mesh routes multi-device
            # calls through the shard_map wrapper.  All impls are
            # GQA-native, so Hkv-width K/V pass straight through.
            out = attention(
                q, k, v, causal=self.causal, bias=bias, mask=mask, mesh=cfg.mesh,
                window=cfg.window if (self.causal and is_self) else None,
            )
        out = jnp.transpose(out, (0, 2, 1, 3))  # [B,S,H,D]
        return self._project_out(out, train)

    def _project_out(self, out, train):
        cfg = self.cfg
        out = QDenseGeneral(
            cfg.hidden,
            axis=(-2, -1),
            dtype=cfg.dtype,
            use_bias=cfg.attn_bias,
            kernel_init=param_with_axes(nn.initializers.lecun_normal(), ("heads", "kv", "embed")),
            bias_init=param_with_axes(nn.initializers.zeros_init(), ("embed",)),
            name="out",
        )(out)
        out = nn.Dropout(cfg.dropout, deterministic=not train)(out)
        return logical_constraint(out, ACT_HIDDEN)


class MlpBlock(nn.Module):
    cfg: TransformerConfig
    activation: str = "gelu"  # "gelu" | "relu" | "swiglu"

    @nn.compact
    def __call__(self, x, train=False):
        cfg = self.cfg
        if self.activation == "swiglu":
            # llama-style gated MLP: silu(gate) * up, no biases
            gate = dense(cfg.mlp_dim, cfg, ("embed", "mlp"), name="wi_gate", use_bias=False)(x)
            up = dense(cfg.mlp_dim, cfg, ("embed", "mlp"), name="wi_up", use_bias=False)(x)
            y = nn.silu(gate) * up
            y = logical_constraint(y, ("batch", "seq", "act_mlp"))
            y = dense(cfg.hidden, cfg, ("mlp", "embed"), name="wo", use_bias=False)(y)
        else:
            y = dense(cfg.mlp_dim, cfg, ("embed", "mlp"), name="wi")(x)
            y = logical_constraint(y, ("batch", "seq", "act_mlp"))
            y = nn.gelu(y) if self.activation == "gelu" else nn.relu(y)
            y = dense(cfg.hidden, cfg, ("mlp", "embed"), name="wo")(y)
        y = nn.Dropout(cfg.dropout, deterministic=not train)(y)
        return logical_constraint(y, ACT_HIDDEN)


class EncoderLayer(nn.Module):
    """Pre-LN encoder block (BERT here is pre-LN — a deliberate
    TPU-era modernisation over the original post-LN; trains stably in
    bf16 without warmup gymnastics).  `rms`/`activation` give the T5
    flavour (RMSNorm + relu)."""

    cfg: TransformerConfig
    rms: bool = False
    activation: str = "gelu"

    @nn.compact
    def __call__(self, x, mask=None, bias=None, train=False):
        cfg = self.cfg
        y = LayerNorm(cfg, rms=self.rms, name="ln_attn")(x)
        x = x + MultiHeadAttention(cfg, name="attn")(y, mask=mask, bias=bias, train=train)
        y = LayerNorm(cfg, rms=self.rms, name="ln_mlp")(x)
        x = x + MlpBlock(cfg, activation=self.activation, name="mlp")(y, train=train)
        return logical_constraint(x, ACT_HIDDEN)


class DecoderLayer(nn.Module):
    """Pre-LN decoder block: causal self-attention (+ optional
    cross-attention for encoder-decoder models)."""

    cfg: TransformerConfig
    cross: bool = False
    activation: str = "relu"

    @nn.compact
    def __call__(self, x, enc=None, self_bias=None, enc_mask=None, train=False):
        cfg = self.cfg
        y = LayerNorm(cfg, rms=True, name="ln_self")(x)
        x = x + MultiHeadAttention(cfg, causal=True, name="self_attn")(
            y, bias=self_bias, train=train
        )
        if self.cross:
            y = LayerNorm(cfg, rms=True, name="ln_cross")(x)
            x = x + MultiHeadAttention(cfg, name="cross_attn")(
                y, kv=enc, mask=enc_mask, train=train
            )
        y = LayerNorm(cfg, rms=True, name="ln_mlp")(x)
        x = x + MlpBlock(cfg, activation=self.activation, name="mlp")(y, train=train)
        return logical_constraint(x, ACT_HIDDEN)
