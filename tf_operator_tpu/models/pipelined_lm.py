"""Pipeline-parallel causal LM: the transformer family over the pp axis.

Composes parallel/pipeline.py's GPipe schedule with the CausalLM block
stack (SURVEY.md §2b PP row): the decoder layers are split into
``pp`` stages, each stage's layer parameters stacked with leading dims
[pp, layers_per_stage, ...] and laid out ``P("pp")``; within a stage a
``lax.scan`` applies the stage's layers, between stages activations
move by ppermute.  Embedding, position table, final norm and the tied
LM head stay outside the pipeline (replicated — they are small next to
the block stack), exactly like the usual embedding-outside-PP layout.

Function-style (init/apply) rather than an nn.Module: the pipeline
schedule needs direct control of parameter layout and shard_map specs,
which flax's lifted transforms would obscure.  Dropout is disabled
inside the pipelined stages (deterministic apply) — the standard
simplification for GPipe-style schedules.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tf_operator_tpu.models.transformer import (
    DecoderLayer,
    Embed,
    LayerNorm,
    TransformerConfig,
)
from tf_operator_tpu.parallel.mesh import AXIS_PP, BATCH_AXES
from tf_operator_tpu.parallel.pipeline import pipeline_apply


class PipelinedLM:
    """init/apply/loss bundle for a pp-staged CausalLM."""

    def __init__(
        self,
        cfg: TransformerConfig,
        mesh: Mesh,
        *,
        microbatches: int = 4,
        activation: str = "relu",
    ):
        # the cfg carries the family knobs (rope/GQA/attn_bias), so a
        # pipelined LLAMA is cfg(rope=True, attn_bias=False,
        # n_kv_heads=...) + activation="swiglu" — same stages, modern
        # blocks
        self.cfg = cfg
        self.mesh = mesh
        self.pp = mesh.shape[AXIS_PP]
        if cfg.n_layers % self.pp:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by pp {self.pp}"
            )
        self.layers_per_stage = cfg.n_layers // self.pp
        self.microbatches = microbatches
        self._layer = DecoderLayer(cfg, cross=False, activation=activation)
        self._embed = Embed(cfg)
        self._ln = LayerNorm(cfg, rms=True)

    # -- params -------------------------------------------------------------

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.cfg
        dummy_ids = jnp.zeros((1, min(8, cfg.max_len)), jnp.int32)
        dummy_x = jnp.zeros((1, min(8, cfg.max_len), cfg.hidden), cfg.dtype)
        r_embed, r_pos, r_ln, r_layers = jax.random.split(rng, 4)

        embed = self._embed.init(r_embed, dummy_ids)["params"]
        # rope families encode position inside attention — no table
        pos = (
            None
            if cfg.rope
            else jax.random.normal(r_pos, (cfg.max_len, cfg.hidden), jnp.float32) * 0.02
        )
        ln = self._ln.init(r_ln, dummy_x)["params"]

        # one init per layer, stacked [pp, layers_per_stage, ...]
        layer_params = []
        for i in range(cfg.n_layers):
            layer_params.append(
                self._layer.init(jax.random.fold_in(r_layers, i), dummy_x)["params"]
            )
        per_stage = []
        for s in range(self.pp):
            chunk = layer_params[
                s * self.layers_per_stage : (s + 1) * self.layers_per_stage
            ]
            per_stage.append(jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *chunk))
        stages = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)
        out = {"embed": embed, "ln": ln, "stages": stages}
        if pos is not None:
            out["pos"] = pos
        return out

    def shard_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Lay params on the mesh: stages over pp, the rest replicated.

        Multi-process safe: every process calls this with the SAME host
        params (same init seed) and each device receives exactly its
        shard — the multi-host layout a pp mesh spanning processes
        needs (each host holding only its stages)."""

        repl = NamedSharding(self.mesh, P())
        stage = NamedSharding(self.mesh, P(AXIS_PP))

        def put(x, sharding):
            x = jnp.asarray(x)
            if jax.process_count() == 1:
                return jax.device_put(x, sharding)
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx]
            )

        out = {
            "embed": jax.tree_util.tree_map(lambda x: put(x, repl), params["embed"]),
            "ln": jax.tree_util.tree_map(lambda x: put(x, repl), params["ln"]),
            "stages": jax.tree_util.tree_map(
                lambda x: put(x, stage), params["stages"]
            ),
        }
        if "pos" in params:
            out["pos"] = put(params["pos"], repl)
        return out

    # -- forward ------------------------------------------------------------

    def apply(self, params: Dict[str, Any], input_ids: jax.Array) -> jax.Array:
        cfg = self.cfg
        _, s = input_ids.shape
        x = self._embed.apply({"params": params["embed"]}, input_ids)
        if not cfg.rope:
            # gate on the config (init's source of truth): a params
            # dict missing "pos" here should KeyError, not silently
            # train position-blind
            x = x + params["pos"][None, :s].astype(cfg.dtype)

        layer = self._layer

        def stage_fn(stage_params, h):
            # scan this stage's layers (leading dim layers_per_stage)
            def body(carry, lp):
                return layer.apply({"params": lp}, carry, train=False), None

            h, _ = jax.lax.scan(body, h, stage_params)
            return h

        x = pipeline_apply(
            stage_fn,
            params["stages"],
            x,
            self.mesh,
            microbatches=self.microbatches,
            batch_axes=BATCH_AXES,
        )
        x = self._ln.apply({"params": params["ln"]}, x)
        logits = self._embed.apply(
            {"params": params["embed"]}, x, method=self._embed.attend
        )
        return logits.astype(jnp.float32)

    def loss(self, params: Dict[str, Any], input_ids: jax.Array) -> jax.Array:
        logits = self.apply(params, input_ids)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], input_ids[:, 1:]
        ).mean()


def lm_reference_apply(model: PipelinedLM, params: Dict[str, Any], input_ids):
    """Same computation WITHOUT the pipeline (sequential layers) — the
    equivalence oracle for tests."""

    cfg = model.cfg
    _, s = input_ids.shape
    x = model._embed.apply({"params": params["embed"]}, input_ids)
    if not cfg.rope:
        x = x + params["pos"][None, :s].astype(cfg.dtype)
    flat = jax.tree_util.tree_map(
        lambda p: p.reshape(cfg.n_layers, *p.shape[2:]), params["stages"]
    )
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda p: p[i], flat)
        x = model._layer.apply({"params": lp}, x, train=False)
    x = model._ln.apply({"params": params["ln"]}, x)
    logits = model._embed.apply(
        {"params": params["embed"]}, x, method=model._embed.attend
    )
    return logits.astype(jnp.float32)
