"""Autoregressive generation with a KV cache — the serving/eval path.

The reference has no inference story (it is a control plane, SURVEY.md
§0); a training framework's users still need to sample from what they
trained.  TPU-first design choices:

- **Static shapes end to end.**  The cache is [B, Hkv, max_len, D]
  allocated once; each step writes one slot via dynamic_update_slice
  and masks unfilled positions.  Nothing reshapes, so the whole
  generate loop compiles to ONE XLA program.
- **lax.scan over steps** — no Python loop per token, no retraces.
- **GQA-width cache**: Hkv heads, h/hkv smaller than the naive cache.
- Prefill and decode share one code path (the MHA cache branch handles
  s_new = prompt_len and s_new = 1 uniformly).

Works with every decoder family built on models/transformer.py:
CausalLM/GPT (learned positions), LlamaLM (RoPE + GQA), and MoeLM
(routed experts — dropless per-token dispatch at decode, see
models/moe.py).  The pipelined family doesn't support decode (its
stage schedule is training-shaped); `_decode_variant` rejects it with
a clear NotImplementedError.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tf_operator_tpu.models.transformer import TransformerConfig
from tf_operator_tpu.ops.quant import materialize_fn


def _decode_variant(model):
    """The same architecture with decode=True (frozen-config swap)."""

    # families opt in via SUPPORTS_DECODE (CausalLM, LlamaLM, MoeLM):
    # rules out pipelined (training-shaped stage schedule) AND the
    # non-decoder TransformerConfig families (T5 needs encoder ids;
    # BERT would "generate" from a bidirectional encoder)
    if not getattr(type(model), "SUPPORTS_DECODE", False):
        raise NotImplementedError(
            f"decode is supported for the autoregressive decoder "
            f"families (CausalLM, LlamaLM, MoeLM — classes with "
            f"SUPPORTS_DECODE=True); got {type(model).__name__}"
        )
    # families whose config nests TransformerConfig (MoeLM) provide the
    # swap themselves
    variant = getattr(model, "decode_variant", None)
    if variant is not None:
        return variant()
    cfg = model.cfg
    assert isinstance(cfg, TransformerConfig)
    return type(model)(dataclasses.replace(cfg, decode=True, dropout=0.0))


def binary_chunks(n: int) -> list:
    """Binary decomposition of n, largest chunk first — the power-of-2
    prefill widths shared by ChunkedServingDecoder and the
    continuous-batching pool (compile count stays logarithmic)."""

    out, bit = [], 1 << n.bit_length()
    while n:
        bit >>= 1
        if n >= bit:
            out.append(bit)
            n -= bit
    return out


def top_k_mask(logits: jax.Array, top_k: int) -> jax.Array:
    """Logits with everything below the k-th largest set to -inf.
    ONE implementation for every sampling path (generate,
    ChunkedServingDecoder, the batching pool's admission).  k is
    clamped to the vocab — lax.top_k raises on k > width."""

    k = min(int(top_k), logits.shape[-1])
    kth = lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def window_chunks(n: int, max_chunk) -> list:
    """binary_chunks capped for a ROLLING cache: widths never exceed
    max_chunk (the largest power of two <= window) because the cache
    accepts at most `window` tokens per apply.  max_chunk None = no cap."""

    if max_chunk is None or n <= max_chunk:
        return binary_chunks(n)
    full, rem = divmod(n, max_chunk)
    return [max_chunk] * full + binary_chunks(rem)


def max_window_chunk(cfg) -> "int | None":
    """Largest power-of-two prefill width a rolling cache accepts, or
    None for non-rolling configs."""

    w = getattr(cfg, "window", None)
    if w is not None and w < cfg.max_len:
        return 1 << (w.bit_length() - 1)
    return None


def set_cache_index(cache, n):
    """Reset every layer's ``cache_index`` scalar to ``n``.

    The rollback primitive shared by speculative decoding (rewind past
    rejected proposals) and the batching pool's fused admission
    (invalidate pad-position writes after a padded-width prefill):
    non-rolling decode attention masks strictly by ``cache_index``
    (transformer.py: ``cols <= row_pos``), so K/V rows at positions
    >= n are invisible after the reset — no recompute, no copies.
    NOT valid for rolling-window caches (their ``cached_pos`` wrap
    state is not index-rollbackable); callers gate on that."""

    def f(path, leaf):
        name = ""
        for entry in reversed(path):
            k = getattr(entry, "key", None)
            if isinstance(k, str):
                name = k
                break
        if name == "cache_index":
            return jnp.asarray(n, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(f, cache)


def _leaf_name(path) -> str:
    """Last string key on a tree path (flax cache leaves are named
    dicts: cached_key / cached_value / cache_index / cached_pos)."""

    for entry in reversed(path):
        k = getattr(entry, "key", None)
        if isinstance(k, str):
            return k
    return ""


# ---------------------------------------------------------------------------
# Paged KV arena (ISSUE 8): the serving cache as fixed-size token
# blocks over ONE pre-allocated device tensor per layer, addressed
# through per-seat block tables.  The gather/scatter helpers below
# build the exact contiguous [1, Hkv, max_len, D] view the flax decode
# branch expects and write back only the touched blocks — a memcpy
# round trip, so paged decode through them is token-identical to the
# contiguous path by construction (test-pinned,
# tests/test_paged_pool.py).  They serve the fused ADMISSION program
# and the CPU/"off" step fallback; since ISSUE 10 the steady-state
# step on the kernel path skips the materialized view entirely
# (transformer.py's paged decode branch + ops/paged_attention, wired
# through paged_decode_variant/paged_cache_tree/split_paged_cache
# below, with tables/lengths device-resident).  The PERSISTENT HBM
# story — what admission is gated on — is the arena either way.
#
# Block id 0 is scratch (models/kv_blocks.SCRATCH_BLOCK): unused table
# entries point at it, overshoot/pad writes land in it, and every read
# of it is masked by cache_index.
# ---------------------------------------------------------------------------


def paged_arena(dmodel, num_blocks: int, block_size: int):
    """Zeroed arena tree for ``dmodel``'s cache: every cached_key /
    cached_value leaf ``[1, H, max_len, D]`` becomes
    ``[num_blocks, H, block_size, D]``; cache_index leaves stay as
    placeholder scalars (per-seat lengths are injected per program —
    paged_cache_tree for the fused step, the gather helpers'
    ``length``/``lengths`` args elsewhere).  Raises for
    rolling-window caches (their wrap state is position-aliased — not
    pageable) and for cache layouts this pager does not understand."""

    from tf_operator_tpu.models.kv_blocks import NotPageableError

    cfg = dmodel.cfg
    w = getattr(cfg, "window", None)
    if w is not None and w < cfg.max_len:
        raise NotPageableError(
            "rolling-window caches are not pageable (cached_pos wrap "
            "state aliases positions); serve windowed models through "
            "the contiguous pool"
        )
    if cfg.max_len % block_size:
        raise ValueError(
            f"max_len={cfg.max_len} must be a multiple of "
            f"block_size={block_size}"
        )
    template = _init_cache_for(dmodel, 1)

    def f(path, leaf):
        name = _leaf_name(path)
        if name == "cache_index":
            return jnp.zeros((), leaf.dtype)
        if name in ("cached_key", "cached_value"):
            if leaf.ndim != 4 or leaf.shape[0] != 1 or \
                    leaf.shape[2] != cfg.max_len:
                raise NotPageableError(
                    f"unpageable cache leaf {name} of shape {leaf.shape} "
                    f"(expected [1, H, max_len={cfg.max_len}, D])"
                )
            return jnp.zeros(
                (num_blocks, leaf.shape[1], block_size, leaf.shape[3]),
                leaf.dtype,
            )
        raise NotPageableError(f"unknown cache leaf {name!r}")

    return jax.tree_util.tree_map_with_path(f, template)


def paged_decode_variant(model, impl: str):
    """The decode variant with the PAGED attention branch enabled
    (ISSUE 10): same parameters, but self-attention reads/writes the
    block arena through per-seat tables instead of a contiguous cache.
    ``impl`` is an ops/paged_attention impl name ("xla" / "pallas" /
    "pallas-interpret").  Only plain-TransformerConfig decoder families
    are pageable (MoeLM's nested config carries extra cache state —
    pos_index — that paged_arena already refuses)."""

    from tf_operator_tpu.models.kv_blocks import NotPageableError

    dmodel = _decode_variant(model)
    cfg = dmodel.cfg
    if not isinstance(cfg, TransformerConfig):
        raise NotPageableError(
            f"{type(model).__name__} is not pageable (non-Transformer"
            "Config cache state)"
        )
    return type(dmodel)(dataclasses.replace(cfg, paged=impl))


def paged_cache_tree(arena, tables, lengths):
    """Inject the per-seat ``block_tables`` [S, MB] and vector
    ``cache_index`` (= lengths [S]) into every attention layer's arena
    dict — the cache collection the paged decode branch
    (transformer.py) consumes.  Pure tree surgery on traced values; it
    runs INSIDE the compiled step program, so tables/lengths stay
    device-resident across the whole decode window."""

    def walk(d):
        if "cached_key" in d:
            out = dict(d)
            out["cache_index"] = lengths
            out["block_tables"] = tables
            return out
        return {
            k: (walk(v) if isinstance(v, dict) else v) for k, v in d.items()
        }

    return walk(arena)


def split_paged_cache(tree):
    """Inverse of :func:`paged_cache_tree` after an apply/scan: returns
    ``(arena, lengths)`` — the arena tree restored to its scalar
    ``cache_index`` placeholders (so the gather/scatter admission
    programs keep consuming it unchanged) and the advanced per-seat
    lengths (every layer advances identically; the first is taken)."""

    found = []

    def walk(d):
        if "cached_key" in d:
            out = dict(d)
            found.append(out.pop("block_tables"))
            lengths = out["cache_index"]
            if len(found) == 1:
                found.append(lengths)
            out["cache_index"] = jnp.zeros((), lengths.dtype)
            return out
        return {
            k: (walk(v) if isinstance(v, dict) else v) for k, v in d.items()
        }

    arena = walk(tree)
    if len(found) < 2:
        raise ValueError("no attention cache leaves in the paged tree")
    return arena, found[1]


def gather_block_view(arena, table, length, block_size: int):
    """Batch-1 contiguous cache view from the arena: K/V leaves
    ``[1, H, MB*bs, D]`` gathered by ``table`` ([MB] int32 block ids),
    cache_index = ``length``.  Traced — runs inside the compiled
    admission program."""

    def f(path, leaf):
        name = _leaf_name(path)
        if name == "cache_index":
            return jnp.asarray(length, leaf.dtype)
        g = jnp.take(leaf, table, axis=0)  # [MB, H, bs, D]
        g = jnp.transpose(g, (1, 0, 2, 3))  # [H, MB, bs, D]
        h, mb, bs, d = g.shape
        return g.reshape(h, mb * bs, d)[None]

    return jax.tree_util.tree_map_with_path(f, arena)


def gather_block_stack(arena, tables, lengths, block_size: int):
    """Stacked (per-seat) view: K/V leaves ``[S, 1, H, MB*bs, D]``
    gathered by ``tables`` ([S, MB]), cache_index = ``lengths`` ([S])
    — exactly the slot-stacked cache the pool's vmapped step body
    consumes."""

    def f(path, leaf):
        name = _leaf_name(path)
        if name == "cache_index":
            return jnp.asarray(lengths, leaf.dtype)
        g = jnp.take(leaf, tables, axis=0)  # [S, MB, H, bs, D]
        g = jnp.transpose(g, (0, 2, 1, 3, 4))  # [S, H, MB, bs, D]
        s, h, mb, bs, d = g.shape
        return g.reshape(s, h, mb * bs, d)[:, None]

    return jax.tree_util.tree_map_with_path(f, arena)


def scatter_block_view(arena, cache, table_pad, start_block, n_blocks: int,
                       block_size: int):
    """Write ``n_blocks`` blocks of a batch-1 cache view back into the
    arena, starting at logical block ``start_block`` (physical ids from
    ``table_pad``, which carries ``n_blocks`` scratch entries past the
    table so the slice never clamps — overshoot lands in scratch)."""

    def f(path, aleaf, cleaf):
        name = _leaf_name(path)
        if name == "cache_index":
            return aleaf
        x = cleaf[0]  # [H, ML, D]
        h, _, d = x.shape
        x = jnp.pad(x, ((0, 0), (0, n_blocks * block_size), (0, 0)))
        win = lax.dynamic_slice(
            x, (0, start_block * block_size, 0),
            (h, n_blocks * block_size, d),
        )
        win = win.reshape(h, n_blocks, block_size, d)
        win = jnp.transpose(win, (1, 0, 2, 3))  # [nb, H, bs, D]
        ids = lax.dynamic_slice(table_pad, (start_block,), (n_blocks,))
        return aleaf.at[ids].set(win.astype(aleaf.dtype))

    return jax.tree_util.tree_map_with_path(f, arena, cache)


def scatter_block_stack(arena, stack, tables_pad, start_blocks,
                        n_blocks: int, block_size: int):
    """Per-seat window write-back for the stacked step view: seat s
    writes its ``n_blocks`` blocks from logical block
    ``start_blocks[s]``.  Live seats' windows are exclusively owned
    (admission reserved through prompt+budget); only scratch ids can
    collide across seats, and scratch content is never observable."""

    def f(path, aleaf, sleaf):
        name = _leaf_name(path)
        if name == "cache_index":
            return aleaf
        x = sleaf[:, 0]  # [S, H, ML, D]
        s, h, _, d = x.shape
        x = jnp.pad(x, ((0, 0), (0, 0), (0, n_blocks * block_size), (0, 0)))

        def per_seat(xs, b0):
            return lax.dynamic_slice(
                xs, (0, b0 * block_size, 0), (h, n_blocks * block_size, d)
            )

        win = jax.vmap(per_seat)(x, start_blocks)  # [S, H, nb*bs, D]
        win = win.reshape(s, h, n_blocks, block_size, d)
        win = jnp.transpose(win, (0, 2, 1, 3, 4))  # [S, nb, H, bs, D]
        ids = jax.vmap(
            lambda row, b0: lax.dynamic_slice(row, (b0,), (n_blocks,))
        )(tables_pad, start_blocks)  # [S, nb]
        return aleaf.at[ids.reshape(-1)].set(
            win.reshape(s * n_blocks, h, block_size, d).astype(aleaf.dtype)
        )

    return jax.tree_util.tree_map_with_path(f, arena, stack)


def gather_blocks_by_id(arena, ids):
    """K/V rows for physical block ``ids`` ([n] int32) from every
    arena leaf — ``[n, H, bs, D]`` per leaf, cache_index placeholders
    passed through.  The device side of a host swap-OUT (ISSUE 12):
    the caller fetches the result inside its ledger dispatch window
    and parks it in the SwapArena.  Pad ids with SCRATCH — the padded
    rows fetch masked scratch garbage the caller trims."""

    return jax.tree_util.tree_map(
        lambda l: jnp.take(l, ids, axis=0) if l.ndim == 4 else l, arena
    )


def scatter_blocks_by_id(arena, bufs, ids):
    """Write ``bufs`` rows (``[n, H, bs, D]`` per K/V leaf) into the
    arena at physical block ``ids`` — the swap-IN inverse of
    :func:`gather_blocks_by_id`, run inside the resume program.  Pad
    ids with SCRATCH: padded rows land in the scratch block, whose
    content is never observable."""

    return jax.tree_util.tree_map(
        lambda a, b: a.at[ids].set(b.astype(a.dtype)) if a.ndim == 4
        else a,
        arena, bufs,
    )


def _init_cache_for(dmodel, batch_size: int):
    dummy = jnp.zeros((batch_size, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: dmodel.init(jax.random.PRNGKey(0), dummy)
    )["cache"]

    def init_leaf(path, s):
        # the rolling-window cache tracks per-slot absolute positions
        # with -1 = empty; zero would alias position 0 and admit
        # garbage K/V slots into the band
        name = str(path[-1])
        fill = -1 if "cached_pos" in name else 0
        return jnp.full(s.shape, fill, s.dtype)

    return jax.tree_util.tree_map_with_path(init_leaf, shapes)


def init_cache(model, batch_size: int):
    """Empty KV cache for `batch_size` rows (no FLOPs — shapes come
    from eval_shape).  K/V and indices are zeros; the rolling-window
    `cached_pos` slots are -1 (the empty sentinel — zero would alias
    position 0 and admit garbage slots into the band).  Build caches
    through this function, not by zeroing the shape tree by hand."""

    return _init_cache_for(_decode_variant(model), batch_size)


def generate(
    model,
    params,
    prompt_ids: jax.Array,  # [B, P] int32
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample `max_new_tokens` continuations.  Returns [B, P + N] ids.

    temperature 0.0 = greedy (argmax); otherwise categorical over
    logits/temperature, optionally truncated to the top_k logits.
    jit-compatible: wrap in jax.jit with static max_new_tokens for the
    single-program path.
    """

    dmodel = _decode_variant(model)  # also the supported-family guard
    # int8-quantized trees: QDense-stack families take the tree AS
    # INT8 straight into apply; others dequantize per apply site (see
    # ops/quant.materialize_fn for the policy + measurements)
    qparams = params
    materialize = materialize_fn(model)
    cfg = dmodel.cfg
    b, p = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if p + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the cache length max_len={cfg.max_len}"
        )
    if rng is None:
        if temperature != 0.0:
            raise ValueError(
                "temperature sampling needs an explicit rng key — "
                "otherwise every call returns identical tokens"
            )
        rng = jax.random.PRNGKey(0)  # greedy: key is never consumed meaningfully
    cache = _init_cache_for(dmodel, b)

    def sample(logits, r):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            logits = top_k_mask(logits, top_k)
        return jax.random.categorical(r, logits).astype(jnp.int32)

    # prefill: the whole prompt primes every layer's cache.  Windowed
    # models with a ROLLING cache (window < max_len) accept at most
    # `window` tokens per apply, so the prompt feeds through in window-
    # sized chunks — cache-equivalent to one-shot prefill, since slots
    # behind the band are dead either way.
    w = cfg.window
    params = materialize(qparams)  # prefill reads weights once
    if w is not None and w < cfg.max_len and p > w:
        vars_ = {"cache": cache}
        logits = None
        for off in range(0, p, w):
            logits, vars_ = dmodel.apply(
                {"params": params, "cache": vars_["cache"]},
                prompt_ids[:, off : off + w],
                mutable=["cache"],
            )
    else:
        logits, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, prompt_ids, mutable=["cache"]
        )
    rng, r0 = jax.random.split(rng)
    tok = sample(logits[:, -1], r0)

    def body(carry, _):
        cache, tok, rng = carry
        logits, vars_ = dmodel.apply(
            {"params": materialize(qparams), "cache": cache},
            tok[:, None],
            mutable=["cache"],
        )
        rng, r = jax.random.split(rng)
        nxt = sample(logits[:, 0], r)
        return (vars_["cache"], nxt, rng), tok

    (cache, last, _), toks = lax.scan(
        body, (vars_["cache"], tok, rng), None, length=max_new_tokens - 1
    )
    gen = jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1)
    return jnp.concatenate([prompt_ids, gen], axis=1)


class ChunkedServingDecoder:
    """Compile-bounded generation for serving (VERDICT r3 next #9).

    `generate()` compiles one XLA program per *prompt shape*, so a
    server facing natural traffic (every distinct prompt length a fresh
    shape) compiles without bound.  Padding prompts to buckets would
    bound it but CHANGES the result (pad tokens land in the KV cache
    and shift positions).  This decoder keeps the semantics exact and
    the compile count logarithmic instead:

    - **Prefill in power-of-2 chunks.**  The KV cache makes prefill
      incremental: feeding the prompt as its binary decomposition
      (e.g. 37 = 32+4+1) through the cache is bit-identical to one-shot
      prefill, and every chunk width is a power of two — at most
      log2(max_len)+1 prefill programs EVER, shared by all requests.
    - **Token budgets rounded up to powers of two.**  The decode scan
      compiles per (budget, sampling config); generating extra tokens
      and slicing the first n is semantics-preserving (the rng chain
      and cache writes for the first n tokens are identical).

    `compile_count` exposes the number of distinct XLA programs built,
    so tests (and capacity planning) can pin the bound.
    """

    def __init__(self, model, params, max_loops: int = 24,
                 prompt_cache: int = 0, ledger=None):
        import threading
        from collections import OrderedDict

        from tf_operator_tpu.utils.metrics import DispatchLedger

        #: device-dispatch accounting (phases: prefill, decode) — the
        #: sequential-serving baseline's "~5 dispatches per request"
        #: becomes a counted number instead of a PROFILE.md estimate
        self.ledger = ledger if ledger is not None else DispatchLedger()
        self.dmodel = _decode_variant(model)
        self.params = params
        self.max_len = self.dmodel.cfg.max_len
        self._materialize = materialize_fn(model)
        # windowed rolling cache accepts at most `window` tokens per
        # apply: cap chunk widths (program count stays logarithmic —
        # widths are still powers of two, just from a smaller set)
        self._max_chunk = max_window_chunk(self.dmodel.cfg)
        #: prompt-KV snapshot reuse: exact prompt -> (primed cache,
        #: last logits).  A repeat prompt (the chat pattern: same
        #: system+context, fresh budget/sampling) skips prefill
        #: entirely.  EXACT — the snapshot holds the same arrays a
        #: fresh prefill would produce, and jax arrays are immutable,
        #: so decode loops can never corrupt a stored entry.  Since
        #: ISSUE 8 this is a CLIENT of the shared content-addressed
        #: prefix cache (models/prefix_cache.py — the paged pool's
        #: block store is the other client): one LRU eviction policy,
        #: one serve_prefix_cache_{hits,misses,evictions}_total metric
        #: family, keyed here by the degenerate whole-prompt chain
        #: (exact_key).  Each entry costs one full B-row KV cache.
        from tf_operator_tpu.models.prefix_cache import PrefixCache

        self._prompt_cache = (
            PrefixCache(
                capacity=int(prompt_cache),
                metrics=self.ledger.metrics,
                mode="chunked",
            )
            if int(prompt_cache) > 0
            else None
        )
        self._prefill = {}  # chunk width -> jitted apply; <= log2(max_len)+1
        #: (budget, temperature, top_k) -> jitted scan.  LRU-bounded:
        #: budgets are powers of two but temperature/top_k are
        #: client-influenced — without a bound an adversarial sweep
        #: (temperature grid x top_k range) would retain one compiled
        #: program per combination forever
        self._loops = OrderedDict()
        self._max_loops = max_loops
        #: serve_lm fronts this with ThreadingHTTPServer — cache
        #: bookkeeping (LRU mutation, compile_count) must not race
        #: across request threads.  XLA execution itself is thread-safe
        #: and runs outside the lock.
        self._lock = threading.Lock()
        self.compile_count = 0

    _binary_chunks = staticmethod(binary_chunks)  # back-compat alias

    @property
    def prompt_cache_hits(self) -> int:
        return 0 if self._prompt_cache is None else self._prompt_cache.hits

    def _chunks(self, n: int) -> list:
        return window_chunks(n, self._max_chunk)

    def _prefill_fn(self, width: int):
        with self._lock:
            if width not in self._prefill:
                dmodel = self.dmodel

                materialize = self._materialize

                def prefill(params, cache, ids):
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": cache},
                        ids,
                        mutable=["cache"],
                    )
                    return vars_["cache"], logits[:, -1]

                # ISSUE 20: chunked-decoder compiles register in the
                # process cost plane (this decoder has no metrics
                # registry of its own — the default ledger is the
                # process view /debug/compiles merges anyway)
                from tf_operator_tpu.utils.costplane import default_costplane

                self._prefill[width] = default_costplane.compiles.wrap(
                    jax.jit(prefill), "chunked.prefill",
                    trigger=f"width={width}",
                )
                self.compile_count += 1
            return self._prefill[width]

    def _loop_fn(self, n_new: int, temperature: float, top_k):
        key = (n_new, temperature, top_k)
        with self._lock:
            return self._loop_fn_locked(key, n_new, temperature, top_k)

    def _loop_fn_locked(self, key, n_new: int, temperature: float, top_k):
        if key in self._loops:
            self._loops.move_to_end(key)
        else:
            while len(self._loops) >= self._max_loops:
                self._loops.popitem(last=False)
            dmodel = self.dmodel
            materialize = self._materialize

            def sample(logits, r):
                if temperature == 0.0:
                    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
                scaled = logits / temperature
                if top_k is not None:
                    scaled = top_k_mask(scaled, top_k)
                return jax.random.categorical(r, scaled).astype(jnp.int32)

            def loop(params, cache, last_logits, rng):
                rng, r0 = jax.random.split(rng)
                tok = sample(last_logits, r0)

                def body(carry, _):
                    cache, tok, rng = carry
                    # QDense families: int8 tree straight into apply
                    # (quant_matmul dequantizes per tile in VMEM);
                    # others dequantize per step here
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": cache},
                        tok[:, None],
                        mutable=["cache"],
                    )
                    rng, r = jax.random.split(rng)
                    nxt = sample(logits[:, 0], r)
                    return (vars_["cache"], nxt, rng), tok

                (_, last, _), toks = lax.scan(
                    body, (cache, tok, rng), None, length=n_new - 1
                )
                return jnp.concatenate(
                    [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1
                )

            from tf_operator_tpu.utils.costplane import default_costplane

            # trigger carries only the pow2 budget class: temperature/
            # top_k are CLIENT-influenced — folding them into a metric
            # label would hand clients unbounded label cardinality
            # (the LRU bounds compiled programs, not counter series)
            self._loops[key] = default_costplane.compiles.wrap(
                jax.jit(loop), "chunked.loop",
                trigger=f"budget={n_new}",
            )
            self.compile_count += 1
        return self._loops[key]

    def generate(
        self,
        prompt_ids: jax.Array,  # [B, P] int32
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        rng: Optional[jax.Array] = None,
    ) -> jax.Array:
        b, p = prompt_ids.shape
        if p < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if p + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len={self.max_len}"
            )
        if temperature == 0.0:
            # greedy ignores top_k — normalising it off the compile key
            # stops distinct greedy requests compiling identical loops
            top_k = None
        # budget stays an exact power of two so the loop-key set is
        # logarithmic even when p + budget overruns max_len.  Overrun
        # steps are harmless because every KEPT token (step <
        # max_new_tokens, position < max_len) is sampled BEFORE any
        # overrun write lands: the full cache clamps its
        # dynamic_update_slice at the edge, and the rolling cache wraps
        # onto live slots — either way only steps whose outputs are
        # discarded observe the corrupted tail, which `[:, :n]` slices
        # away.  Do not read the cache after an overrun generate.
        budget = 1 << (max_new_tokens - 1).bit_length()  # next power of 2
        if rng is None:
            if temperature != 0.0:
                raise ValueError("temperature sampling needs an explicit rng key")
            rng = jax.random.PRNGKey(0)

        key = None
        if self._prompt_cache is not None:
            from tf_operator_tpu.models.prefix_cache import exact_key

            key = exact_key(np.asarray(prompt_ids))
            hit = self._prompt_cache.get(key)  # counts hit/miss
            if hit is not None:
                cache, last = hit
                with self.ledger.dispatch("decode"):
                    toks = self._loop_fn(budget, temperature, top_k)(
                        self.params, cache, last, rng
                    )
                return jnp.concatenate(
                    [prompt_ids, toks[:, :max_new_tokens]], axis=1
                )
        cache = _init_cache_for(self.dmodel, b)
        offset, last = 0, None
        for width in self._chunks(p):
            with self.ledger.dispatch("prefill"):
                cache, last = self._prefill_fn(width)(
                    self.params, cache, prompt_ids[:, offset : offset + width]
                )
            offset += width
        if key is not None:
            self._prompt_cache.put(key, (cache, last))
        with self.ledger.dispatch("decode"):
            toks = self._loop_fn(budget, temperature, top_k)(
                self.params, cache, last, rng
            )
        return jnp.concatenate([prompt_ids, toks[:, :max_new_tokens]], axis=1)
