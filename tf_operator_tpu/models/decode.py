"""Autoregressive generation with a KV cache — the serving/eval path.

The reference has no inference story (it is a control plane, SURVEY.md
§0); a training framework's users still need to sample from what they
trained.  TPU-first design choices:

- **Static shapes end to end.**  The cache is [B, Hkv, max_len, D]
  allocated once; each step writes one slot via dynamic_update_slice
  and masks unfilled positions.  Nothing reshapes, so the whole
  generate loop compiles to ONE XLA program.
- **lax.scan over steps** — no Python loop per token, no retraces.
- **GQA-width cache**: Hkv heads, h/hkv smaller than the naive cache.
- Prefill and decode share one code path (the MHA cache branch handles
  s_new = prompt_len and s_new = 1 uniformly).

Works with every decoder family built on models/transformer.py
(CausalLM/GPT with learned positions, LlamaLM with RoPE).  The MoE and
pipelined families don't support decode yet (their routing/stage
schedules are training-shaped); `_decode_variant` rejects them with a
clear NotImplementedError.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tf_operator_tpu.models.transformer import TransformerConfig


def _decode_variant(model):
    """The same architecture with decode=True (frozen-config swap)."""

    # families opt in via SUPPORTS_DECODE (CausalLM, LlamaLM): rules
    # out MoE/pipelined (training-shaped schedules) AND the non-decoder
    # TransformerConfig families (T5 needs encoder ids; BERT would
    # "generate" from a bidirectional encoder)
    if not getattr(type(model), "SUPPORTS_DECODE", False):
        raise NotImplementedError(
            f"decode is supported for the autoregressive decoder "
            f"families (CausalLM, LlamaLM — classes with "
            f"SUPPORTS_DECODE=True); got {type(model).__name__}"
        )
    cfg = model.cfg
    assert isinstance(cfg, TransformerConfig)
    return type(model)(dataclasses.replace(cfg, decode=True, dropout=0.0))


def _init_cache_for(dmodel, batch_size: int):
    dummy = jnp.zeros((batch_size, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: dmodel.init(jax.random.PRNGKey(0), dummy)
    )["cache"]
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def init_cache(model, batch_size: int):
    """Zero-initialised KV cache for `batch_size` rows (no FLOPs —
    shapes come from eval_shape, zeros from the shape tree)."""

    return _init_cache_for(_decode_variant(model), batch_size)


def generate(
    model,
    params,
    prompt_ids: jax.Array,  # [B, P] int32
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample `max_new_tokens` continuations.  Returns [B, P + N] ids.

    temperature 0.0 = greedy (argmax); otherwise categorical over
    logits/temperature, optionally truncated to the top_k logits.
    jit-compatible: wrap in jax.jit with static max_new_tokens for the
    single-program path.
    """

    dmodel = _decode_variant(model)  # also the supported-family guard
    cfg = dmodel.cfg
    b, p = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if p + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the cache length max_len={cfg.max_len}"
        )
    if rng is None:
        if temperature != 0.0:
            raise ValueError(
                "temperature sampling needs an explicit rng key — "
                "otherwise every call returns identical tokens"
            )
        rng = jax.random.PRNGKey(0)  # greedy: key is never consumed meaningfully
    cache = _init_cache_for(dmodel, b)

    def sample(logits, r):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(r, logits).astype(jnp.int32)

    # prefill: the whole prompt in one pass primes every layer's cache
    logits, vars_ = dmodel.apply(
        {"params": params, "cache": cache}, prompt_ids, mutable=["cache"]
    )
    rng, r0 = jax.random.split(rng)
    tok = sample(logits[:, -1], r0)

    def body(carry, _):
        cache, tok, rng = carry
        logits, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, tok[:, None], mutable=["cache"]
        )
        rng, r = jax.random.split(rng)
        nxt = sample(logits[:, 0], r)
        return (vars_["cache"], nxt, rng), tok

    (cache, last, _), toks = lax.scan(
        body, (vars_["cache"], tok, rng), None, length=max_new_tokens - 1
    )
    gen = jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1)
    return jnp.concatenate([prompt_ids, gen], axis=1)
