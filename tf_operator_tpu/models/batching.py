"""Continuous-batching decoder: concurrent requests share one decode
loop, joining and leaving at STEP granularity.

`ChunkedServingDecoder` serves one request per call: a second request
waits for the first to finish, so a server at concurrency k runs the
weight-bandwidth-bound decode loop k times sequentially.  Continuous
batching (the vLLM idea, re-shaped for XLA's static-shape world) keeps
a fixed pool of `slots` and one compiled step program:

- **Stacked slot caches.**  The KV cache of a batch-1 decode is stacked
  along a new leading slot axis; the per-layer ``cache_index`` scalar
  becomes a per-slot vector, so every slot sits at its own sequence
  position — the thing a plain batched ``generate`` cannot do.
- **One vmapped step.**  ``jax.vmap`` of the batch-1 apply over the
  slot axis: weights broadcast (the projections still execute as one
  ``[slots,1,D]x[D,F]`` dot on the MXU); the per-slot cache write
  lowers to a scatter of one row per layer.  Inactive slots compute
  too (their writes land in already-dead cache rows) — the step cost
  is constant, which is exactly the point: an arriving request rides
  a loop that was already paying for it.
- **Compile count is O(1) + O(log max_len).**  One step program per
  pool; admission compiles one fused program per power-of-2 prompt
  width class (below), the rolling-window legacy path reuses the
  binary-chunk prefill programs.
- **K tokens per host round trip** (``steps_per_sync``): the step
  program scans K decode steps, so a tunneled chip (host↔device rides
  the network here) pays one round trip per K tokens instead of per
  token.  Requests join/retire at K-step granularity — worst case
  K-1 wasted slot-steps per finished request.
- **Single-dispatch admission** (r6, VERDICT r5 next #5).  The old
  admission sequence — chunked prefill into a batch-1 cache (>=1
  dispatch per chunk), a first-token sample, then a scatter-seating
  dispatch — cost >=3 device round trips per request; on a tunneled
  chip (~66 ms RTT each, PROFILE.md "r5 serving") admissions alone
  outweighed the decode they fed.  Admission is now ONE compiled
  program per power-of-2 prompt-width class: the prompt, zero-padded
  to the next power of two, prefills a fresh batch-1 cache in-graph;
  causal masking makes the true last position's logits exact despite
  the pad, and resetting ``cache_index`` back to the true length
  (``decode.set_cache_index`` — the speculative-rollback primitive)
  makes the pad rows invisible to every later step; the first token
  samples and the row scatters into the slot stack in the same
  program.  Exactly 1 dispatch per admitted request, compile count
  still logarithmic.  Cost of the trick: up to 2x prefill compute on
  pad positions (worst case p = 2^k + 1), irrelevant here and cheap
  against a single round trip anywhere.  The fused program needs a
  seat, so it runs in ``_admit`` under the pool lock (``submit`` just
  validates and queues — it never blocks and never touches the
  device); the device serializes programs regardless, so driver-side
  seating loses no throughput, only the old eager-prefill overlap of
  per-chunk dispatch latencies — which is the thing being deleted.
  ROLLING-WINDOW caches keep the legacy staged path (pad writes would
  poison ``cached_pos``, and the wrap state is not index-rollbackable)
  with eager submitter-thread prefill bounded by staging permits at
  2x slots, exactly as before; same for prompts whose padded width
  exceeds max_len.
- **Dispatch ledger.**  Every device call is counted and timed through
  ``utils/metrics.DispatchLedger`` (phases: admission, step, and the
  legacy path's prefill/scatter), so "tunnel overhead" is an auditable
  ``count x RTT`` number — ``measure.py --section batching`` embeds
  the ledger in its JSON and tests pin admission at exactly 1.

Greedy and per-slot temperature sampling (a ``[slots]`` temperature
vector; 0 = argmax).  Requests finish by token budget (byte-level
serving has no universal EOS).  Rolling-window caches (window <
max_len) work unchanged — each slot's wrap state (cached_pos, circular
slots) is slot-local under the vmapped step; admission prefill chunks
cap at the window like ChunkedServingDecoder's.

The reference (SURVEY.md §0) has no serving story at all; this is a
beyond-reference subsystem.  On-chip evidence: aggregate decode
tokens/s at concurrency 8 vs sequential single-request serving —
``benchmarks/measure.py --section batching``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tf_operator_tpu.models.decode import (
    _decode_variant,
    _init_cache_for,
    gather_block_stack,
    gather_block_view,
    gather_blocks_by_id,
    max_window_chunk,
    paged_arena,
    paged_cache_tree,
    paged_decode_variant,
    scatter_block_stack,
    scatter_block_view,
    scatter_blocks_by_id,
    set_cache_index,
    split_paged_cache,
    top_k_mask,
    window_chunks,
)
from tf_operator_tpu.models.kv_blocks import (
    SCRATCH_BLOCK,
    ArenaTimeline,
    BlockAllocator,
    NotPageableError,
    SwapArena,
    blocks_for,
)
from tf_operator_tpu.models.prefix_cache import PrefixCache, chain_keys
from tf_operator_tpu.ops.quant import materialize_fn
from tf_operator_tpu.utils.metrics import DispatchLedger


#: static top-k width: per-slot k thresholds within the top TOP_K_MAX
#: candidates, so one compiled step serves every requested k
TOP_K_MAX = 64

#: SLO tiers (ISSUE 12): admission ordering, preemption policy, and
#: the {tier} label on every serving SLO family key off this closed
#: set.  Higher rank = served first; interactive preempts batch.
SLO_TIERS = ("batch", "interactive")
_TIER_RANK = {t: i for i, t in enumerate(SLO_TIERS)}

#: replica phase roles (ISSUE 13, disaggregated serving): a "prefill"
#: replica chunk-prefills prompts and PUBLISHES the finished blocks
#: into the prefix-cache fabric; a "decode" replica admits by mapping
#: published chains (pulling only the missing tail through the fabric)
#: and runs the unchanged steady-state step loop; "unified" (the
#: default) does both — the pre-ISSUE-13 pool.  The role labels every
#: kv_blocks_* gauge so the autoscaler can scale the two replica
#: classes independently off ``kv_blocks_pressure{role=}``.
REPLICA_ROLES = ("unified", "prefill", "decode")


def _pow2_class(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the width-class trick
    applied to swap uploads/gathers so their compile count stays
    logarithmic."""

    return 1 << max(0, int(n) - 1).bit_length()


def _admission_sample(last, temp, top_k, rng):
    """First-token sampling shared by the contiguous and paged fused
    admission programs (identical math is what makes the paged
    exactness pin possible): in-graph rng split + greedy/temperature
    select + the static top-k trick.  Returns (tok, rng_next)."""

    greedy = jnp.argmax(last, -1).astype(jnp.int32)
    split = jax.random.split(rng)
    rng_next, r = split[0], split[1]
    safe_t = jnp.where(temp > 0.0, temp, 1.0)
    scaled = last / safe_t
    # same static top-k trick as the step body: the runtime k
    # thresholds within the top TOP_K_MAX candidates
    k_max = min(TOP_K_MAX, scaled.shape[-1])
    top_vals = lax.top_k(scaled, k_max)[0]
    kth = top_vals[jnp.clip(top_k - 1, 0, k_max - 1)]
    scaled = jnp.where((top_k > 0) & (scaled < kth), -jnp.inf, scaled)
    samp = jax.random.categorical(r, scaled).astype(jnp.int32)
    return jnp.where(temp > 0.0, samp, greedy), rng_next


def _masked_scaled(logits, temps, top_ks):
    """The per-slot temperature + static-top-k logit transform: [S, V]
    -> [S, V] with sub-threshold candidates at -inf.  ONE definition
    feeding _step_sample AND the speculative draft/verify programs —
    the rejection-sampling distributions q (draft) and p (target) must
    be EXACTLY the distributions the plain sampler would draw from, or
    speculative output drifts from the non-speculative pool's."""

    safe_t = jnp.where(temps > 0.0, temps, 1.0)
    scaled = logits / safe_t[:, None]
    k_max = min(TOP_K_MAX, scaled.shape[-1])
    top_vals = lax.top_k(scaled, k_max)[0]  # [slots, k_max]
    idx = jnp.clip(top_ks - 1, 0, k_max - 1)[:, None]
    kth = jnp.take_along_axis(top_vals, idx, axis=1)
    return jnp.where(
        (top_ks[:, None] > 0) & (scaled < kth),
        -jnp.inf,
        scaled,
    )


def _step_sample(logits, temps, top_ks, rngs):
    """Per-slot next-token sampling for one decode step: [S, V] logits
    -> (next_tokens [S], next_keys [S, 2]).  ONE definition shared by
    the contiguous/emulation scan body (_make_step_body) and the fused
    paged step program — identical math is the paged token-identity
    contract.  Greedy when temps[s] == 0; per-slot top_k thresholds
    within one STATIC top-TOP_K_MAX (compile stays shape-stable)."""

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    split = jax.vmap(jax.random.split)(rngs)
    scaled = _masked_scaled(logits, temps, top_ks)
    sampled = jax.vmap(
        lambda r, l: jax.random.categorical(r, l)
    )(split[:, 0], scaled).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy), split[:, 1]


def _spec_sample_with_dist(logits, temps, top_ks, rngs):
    """_step_sample plus the post-transform categorical distribution —
    the draft side of speculative rejection sampling needs q(tok), and
    it must be the EXACT distribution the token was drawn from (shared
    _masked_scaled transform).  Returns (tok [S], next_keys [S, 2],
    dist [S, V])."""

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    split = jax.vmap(jax.random.split)(rngs)
    scaled = _masked_scaled(logits, temps, top_ks)
    sampled = jax.vmap(
        lambda r, l: jax.random.categorical(r, l)
    )(split[:, 0], scaled).astype(jnp.int32)
    tok = jnp.where(temps > 0.0, sampled, greedy)
    return tok, split[:, 1], jax.nn.softmax(scaled, axis=-1)


class RequestLog:
    """Bounded ring of per-request lifecycle autopsies (ISSUE 11).

    The trace store answers "show me the spans of trace T"; this log
    answers the operator question one level up — "what happened to
    REQUEST R": queue wait, admission accounting (width class, blocks
    reserved, prefix-hit depth, prefill dispatches), decode-window and
    token counts, the per-request dispatch share from the ledger, and
    retirement (blocks freed) — one JSON-safe record per request,
    keyed by the request id (= its trace id), served at
    ``GET /requests/<id>`` on serve_lm and riding flight-recorder
    dumps so a post-mortem names the requests in flight.

    Bounded FIFO (oldest evicted past ``capacity``).  Entries are
    mutated through the log's own lock, so an HTTP read never races a
    driver-thread field write mid-serialization.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.evicted = 0

    def open(self, **fields) -> Dict[str, Any]:
        """Insert a fresh entry (state=queued) and return it; the
        pool mutates it through update/count_dispatch/add_window.

        Id collisions (a client reusing an ``x-trace-id``): the plain
        id resolves to the NEWEST request (matching the exemplar
        store's latest-wins), and the older autopsy survives under
        ``<id>~<rid>`` instead of being silently dropped (``~`` is
        URL-unreserved, so the demoted id stays fetchable at
        ``/requests/<id>~<rid>`` — ``#`` would be eaten as a URI
        fragment)."""

        entry: Dict[str, Any] = {
            "state": "queued",
            "submit_unix": time.time(),
            "queue_wait_seconds": None,
            "ttft_seconds": None,
            "tpot_seconds": None,
            "total_seconds": None,
            "admission": None,
            "windows": 0,
            "tokens": 0,
            "dispatches": {},
            "retire": None,
            "slot": None,
            "tier": "batch",
            # ISSUE 12: a seat can now leave and come back — without
            # these the autopsy would silently truncate at the first
            # preemption
            "preempted": 0,
            "swapped_blocks": 0,
            # ISSUE 13 (disaggregated serving): which replica ran each
            # phase (the router annotates both; pre-split autopsies
            # attributed only the one serving replica), how many prefix
            # blocks arrived over the fabric instead of being computed
            # here, and whether this is an internal fabric-publish
            # prefill (excluded from user-facing SLO observations)
            "prefill_replica": None,
            "decode_replica": None,
            "migrated_blocks": 0,
            "internal": False,
        }
        entry.update(fields)
        with self._lock:
            old = self._entries.pop(entry["id"], None)
            if old is not None:
                # rewrite the demoted entry's id too, so /requests
                # listings and the lookup key agree
                old["id"] = f"{old['id']}~{old['rid']}"
                self._entries[old["id"]] = old
            self._entries[entry["id"]] = entry
            while len(self._entries) > self.capacity:
                # evict finished autopsies first: an IN-FLIGHT entry
                # is exactly the one an operator is debugging, and
                # its dict is still being written — only when every
                # entry is live does oldest-first keep the bound
                victim = next(
                    (k for k, e in self._entries.items()
                     if e["state"] == "done"),
                    None,
                )
                if victim is not None:
                    del self._entries[victim]
                else:
                    self._entries.popitem(last=False)
                self.evicted += 1
        return entry

    def update(self, entry: Dict[str, Any], **fields) -> None:
        with self._lock:
            entry.update(fields)

    def count_dispatch(self, entry: Dict[str, Any], phase: str,
                       n: int = 1) -> None:
        """This request's share of the ledger: +n dispatches under
        ``phase`` (shared dispatches like a decode window count once
        per seated request — the share, not the global total)."""

        with self._lock:
            entry["dispatches"][phase] = (
                entry["dispatches"].get(phase, 0) + n
            )

    def add_migrate(self, entry: Dict[str, Any], blocks: int) -> None:
        """``blocks`` prefix blocks arrived through the fabric instead
        of being prefilled locally (ISSUE 13) — the autopsy shows how
        much of this request's prompt was migration, not compute."""

        with self._lock:
            entry["migrated_blocks"] += int(blocks)

    def annotate(self, request_id: str, **fields) -> None:
        """Update a live entry by id (the router's cross-replica
        attribution hook — it learns the prefill/decode replica split
        only after the pools have opened the entry).  No-op for
        unknown ids."""

        with self._lock:
            entry = self._entries.get(request_id)
            if entry is not None:
                entry.update(fields)

    def add_swap(self, entry: Dict[str, Any], blocks: int) -> None:
        """More of this request's blocks moved host-side WITHOUT a
        seat eviction (the queued-holder demotion path): count them
        without bumping ``preempted``."""

        with self._lock:
            entry["swapped_blocks"] += int(blocks)

    def count_preempt(self, entry: Dict[str, Any],
                      swapped_blocks: int = 0) -> None:
        """The seat left mid-decode (ISSUE 12): one preemption, with
        its swapped-block share; the autopsy stays complete across the
        leave-and-return."""

        with self._lock:
            entry["preempted"] += 1
            entry["swapped_blocks"] += int(swapped_blocks)
            entry["state"] = "preempted"
            entry["slot"] = None

    def add_window(self, entry: Dict[str, Any], tokens: int) -> None:
        with self._lock:
            entry["windows"] += 1
            entry["tokens"] += int(tokens)
            entry["dispatches"]["step"] = (
                entry["dispatches"].get("step", 0) + 1
            )

    def _copy(self, entry: Dict[str, Any]) -> Dict[str, Any]:
        # entries nest at most one dict deep — copy those too so the
        # caller's JSON serialization never races a later mutation
        return {
            k: (dict(v) if isinstance(v, dict) else v)
            for k, v in entry.items()
        }

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._entries.get(request_id)
            return self._copy(entry) if entry is not None else None

    def recent(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first entry copies (list endpoints, flight dumps).
        ``limit <= 0`` returns none — never the whole ring (the
        ``[-0:]`` slice pitfall)."""

        if limit <= 0:
            return []
        with self._lock:
            items = [
                self._copy(e) for e in list(self._entries.values())[-limit:]
            ]
        return items[::-1]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class _Request:
    __slots__ = ("rid", "prompt", "budget", "temperature", "top_k", "rng",
                 "tokens", "done", "slot", "staged_cache", "staged_tok",
                 "has_permit", "t_submit", "t_first", "trace_id", "entry",
                 "t_submit_mono", "queue_waited", "tier", "swapped",
                 "tokens_since_seat", "internal", "t_local",
                 "t_local_mono")

    def __init__(self, rid, prompt, budget, temperature, top_k, rng,
                 tier: str = "batch", internal: bool = False):
        self.rid = rid
        self.prompt = prompt  # np.ndarray [P] int32
        self.budget = budget
        self.temperature = temperature
        self.top_k = top_k  # None = no truncation
        self.rng = rng
        self.tokens: List[int] = []
        self.done = False
        self.slot: Optional[int] = None
        # primed batch-1 cache + first token: staged by the submitter's
        # thread when a staging permit was free (has_permit=True), else
        # primed lazily at admission; consumed by the seating scatter
        self.staged_cache = None
        self.staged_tok = None
        self.has_permit = False
        # SLO clocks (host monotonic): submit time, first-token time —
        # queue-wait/TTFT/time-per-output-token derive from these
        self.t_submit = time.perf_counter()
        self.t_first = None
        # POOL-LOCAL submit clocks (never backdated): queue-wait is a
        # per-replica scheduling signal — under disaggregation the
        # router backdates t_submit so TTFT spans the whole handshake,
        # but the decode replica's queue-wait must measure ITS queue
        # only, or prefill slowness would fire the decode-side
        # queue-wait-burn alert and scale the wrong replica class
        self.t_local = self.t_submit
        self.t_local_mono = None  # set below with t_submit_mono
        # ISSUE 11: first-class request identity (= the trace id every
        # lifecycle span joins; serve_lm adopts the HTTP x-trace-id) +
        # this request's RequestLog autopsy entry
        self.trace_id: Optional[str] = None
        self.entry: Optional[Dict[str, Any]] = None
        self.t_submit_mono = time.monotonic()
        self.t_local_mono = self.t_submit_mono
        self.queue_waited = False  # queue.wait span emitted once
        # ISSUE 12: SLO tier (admission priority, preemption policy,
        # the {tier} label on every SLO observation); swapped marks a
        # preempted request whose KV lives in the pool's SwapArena;
        # tokens_since_seat gates victim eligibility (a seat must make
        # progress between preemptions — the anti-livelock rule)
        self.tier = tier
        self.swapped = False
        self.tokens_since_seat = 0
        # ISSUE 13: a prefill replica's fabric-publish prefills are
        # INTERNAL requests — real pool traffic (they queue, admit, and
        # count dispatches) but not user requests, so they are excluded
        # from the user-facing SLO observations
        self.internal = internal


class ContinuousBatchingDecoder:
    """Fixed-slot continuous batching over one compiled decode step.

    Thread-safe: `submit` may be called from request threads while a
    driver thread calls `step`; all pool state is lock-protected.
    """

    def __init__(self, model, params, slots: int = 8, steps_per_sync: int = 8,
                 ledger: Optional[DispatchLedger] = None,
                 metrics=None, model_label: str = "",
                 replica_label: str = "", role: str = "unified",
                 costplane=None):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        #: ISSUE 13 phase role (REPLICA_ROLES): labels every kv_blocks_*
        #: gauge and — for non-unified replicas — every SLO observation,
        #: so the autoscaler and /metrics see the two replica classes
        #: separately while /slo merges the role away
        self.role = role
        #: device-dispatch accounting (phases: admission, step, and the
        #: legacy rolling-window path's prefill/scatter)
        self.ledger = ledger if ledger is not None else DispatchLedger()
        #: SLO sink (utils/metrics.Metrics or None): every request
        #: observes queue-wait / TTFT / time-per-output-token
        #: histograms labeled {model, mode="pool"}, plus the
        #: serve_admission_queue_depth and serve_tokens_in_flight
        #: gauges — the user-facing latency layer over the ledger's
        #: per-dispatch accounting
        self.metrics = metrics if metrics is not None else self.ledger.metrics
        #: ISSUE 20 device cost plane: every jit cache miss below
        #: registers in the CompileLedger with its trigger (the
        #: width/K/pow2 class), the paged subclass accounts its arena
        #: in the HBM ledger, and the decode-window wall feeds the
        #: step-time sentinel.  serve_lm shares ONE CostPlane across
        #: all replicas so /debug/compiles and /debug/memory merge;
        #: a bare pool gets its own over the pool's metrics registry.
        if costplane is None:
            from tf_operator_tpu.utils.costplane import CostPlane

            costplane = CostPlane(metrics=self.metrics)
        self.costplane = costplane
        self.model_label = model_label or "unknown"
        #: set by the multi-replica router (models/pool_router.py):
        #: non-empty adds a {replica=} label to every SLO observation
        #: and gauge, so /metrics distinguishes replicas while /slo
        #: merges them (utils/metrics.histogram_family_merged)
        self.replica_label = replica_label
        #: ISSUE 11 request-lifecycle observability: the ledger's
        #: tracer (serve_lm shares ONE across all decoders) carries
        #: the per-request queue.wait/admission/decode.window/retire
        #: spans; the RequestLog holds the assembled autopsies the
        #: /requests/<id> endpoint serves
        self.tracer = self.ledger.tracer
        self.request_log = RequestLog()
        self.dmodel = _decode_variant(model)
        self._materialize = materialize_fn(model)
        cfg = self.dmodel.cfg
        # rolling-window caches (window < max_len) work unchanged: each
        # slot's cache — including its wrap state (cached_pos, circular
        # slots) — is independent under the vmapped batch-1 step.  Only
        # PREFILL needs care: the rolling cache accepts at most
        # `window` tokens per apply, so admission chunks cap at the
        # window (ONE rule, shared with ChunkedServingDecoder —
        # decode.window_chunks / max_window_chunk).
        self._max_chunk = max_window_chunk(cfg)
        self.params = params
        self.slots = int(slots)
        #: tokens generated per host round trip.  One device sync per
        #: TOKEN would put a host↔device round trip (a NETWORK round
        #: trip on a tunneled chip) on every step's critical path —
        #: the sequential decoder runs its whole budget in one XLA
        #: program and would win on latency alone.  K steps per sync
        #: amortize that; requests join/retire at K-step granularity
        #: (worst-case waste K-1 steps per finished request).
        self.steps_per_sync = max(1, int(steps_per_sync))
        self.max_len = cfg.max_len
        self._lock = threading.Lock()
        self._done_cond = threading.Condition(self._lock)
        # guards the jitted-fn caches: _prefill now runs on submitter
        # threads with NO pool lock held, so fn creation needs its own
        # (tiny) critical section
        self._compile_lock = threading.Lock()
        # staging backpressure: every submitted-but-unseated request
        # that prefilled EAGERLY holds a primed batch-1 KV cache in
        # DEVICE memory, and serve_lm's ThreadingHTTPServer puts no
        # bound on concurrent submitters — without a cap, a burst of
        # N >> slots requests would pin N full-max_len caches and OOM
        # the chip.  Permits bound eager staging at 2x slots; overflow
        # requests queue host-side (prompt only) and prefill lazily at
        # admission instead (also off the pool lock, in _admit), so
        # submit NEVER blocks and device memory stays bounded at
        # slots + 2*slots caches.
        self._staging = threading.BoundedSemaphore(max(1, 2 * self.slots))
        #: slots picked by an in-flight lazy admission (lock dropped
        #: during its prefill) — excluded from the free list meanwhile
        self._reserved = set()
        self._rid = 0
        self._queue: List[_Request] = []  # submitted, no slot yet
        self._active: Dict[int, _Request] = {}  # slot -> request
        self._results: Dict[int, _Request] = {}
        # device state: stacked batch-1 caches + per-slot last token.
        # Only the SHAPES of the batch-1 row survive on self (the
        # fused admission program builds its fresh cache in-graph from
        # them); keeping the materialized template would pin an extra
        # 1/slots of the pool's cache memory in device HBM for nothing.
        row0 = _init_cache_for(self.dmodel, 1)
        self._row_shapes = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), row0
        )
        self._init_pool_cache(row0)
        self._last_tok = jnp.zeros((self.slots,), jnp.int32)
        self._prefill_fns = {}  # chunk width -> jitted batch-1 prefill
        self._admit_fns = {}  # pow2 prompt width -> fused admission
        self._step_fn = None
        self._scatter_fn = None
        self.compile_count = 0

    def _init_pool_cache(self, row0) -> None:
        """Allocate the contiguous slot-stacked cache (the paged
        subclass overrides this with its block arena instead — the
        whole point is NOT materializing slots × max_len HBM)."""

        self._cache = jax.tree_util.tree_map(
            lambda l: jnp.stack([l] * self.slots), row0
        )

    # -- SLO observations ------------------------------------------------

    def _labels(self, **extra) -> Dict[str, str]:
        """{model[, replica]} + extra.  The replica key appears only
        under the multi-replica router; label-key lint coverage for
        these families comes from the literal serve_lm/metric-gate
        call sites, not from this helper."""

        out = dict(model=self.model_label, **extra)
        if self.replica_label:
            out["replica"] = self.replica_label
        if self.role != "unified":
            # only disaggregated fleets split SLO series by role —
            # unified pools keep the legacy label sets, and the /slo
            # merge drops the key either way (histogram_family_merged)
            out["role"] = self.role
        return out

    # -- request lifecycle (ISSUE 11) ------------------------------------

    def dispatch(self, phase: str, **attrs):
        """``ledger.dispatch`` with this replica's label stamped on
        the span attributes — set in ONE place so no device-call site
        can silently produce a replica-less dispatch span (the
        per-replica waterfall merge keys on it).  Same name as the
        ledger method on purpose: the no-hot-sync lint's sanctioned
        ``with ...dispatch(...)`` window and the phase-taxonomy lint
        both match on the attribute name."""

        attrs.setdefault("replica", self.replica_label or "0")
        return self.ledger.dispatch(phase, **attrs)

    def _request_span(self, req: _Request, name: str, *,
                      start_mono: Optional[float] = None, **attrs):
        """A lifecycle span on ``req``'s trace — a context manager
        (nullcontext when untraced).  Pool lifecycle spans run on the
        DRIVER thread, so they join the request's trace by explicit
        trace id; ledger dispatches issued inside the entered span
        nest under it via contextvars, which is what stitches HTTP →
        router → replica → device dispatch into one waterfall."""

        if self.tracer is None or req.trace_id is None:
            return contextlib.nullcontext(None)
        attrs.setdefault("rid", req.rid)
        attrs.setdefault("replica", self.replica_label or "0")
        return self.tracer.start_span(
            name, trace_id=req.trace_id, attributes=attrs,
            start_mono=start_mono,
        )

    def _emit_span(self, req: _Request, name: str, start_mono: float,
                   end_mono: float, **attrs) -> None:
        """A completed lifecycle span with explicit endpoints (e.g.
        queue.wait backdated to submit, decode.window to the window's
        bounds)."""

        if self.tracer is None or req.trace_id is None:
            return
        attrs.setdefault("rid", req.rid)
        attrs.setdefault("replica", self.replica_label or "0")
        self.tracer.start_span(
            name, trace_id=req.trace_id, attributes=attrs,
            start_mono=start_mono,
        ).end(end_mono=end_mono)

    def _emit_queue_wait(self, req: _Request) -> None:
        """The queue.wait span: submit → first admission work,
        backdated to the submit timestamp so the waterfall shows the
        real wait.  Once per request (guarded like t_first): an
        admission retried after a transient device failure must not
        emit a second span swallowing the first attempt."""

        if req.queue_waited:
            return
        req.queue_waited = True
        # pool-local clock: the handshake phases have their own spans
        self._emit_span(
            req, "queue.wait", req.t_local_mono, time.monotonic(),
        )

    def _finish_request(self, req: _Request, blocks_freed: int = 0) -> None:
        """Retirement bookkeeping shared by every completion path:
        the retire lifecycle span (tagged blocks freed), the autopsy
        entry's final timings, and the SLO observation."""

        now = time.monotonic()
        self._emit_span(
            req, "retire", now, now, blocks_freed=blocks_freed,
            tokens=len(req.tokens),
        )
        if req.entry is not None:
            t_done = time.perf_counter()
            t_first = req.t_first if req.t_first is not None else t_done
            self.request_log.update(
                req.entry,
                state="done",
                retire={"blocks_freed": int(blocks_freed)},
                total_seconds=round(t_done - req.t_submit, 6),
                tpot_seconds=round(
                    (t_done - t_first) / max(1, len(req.tokens) - 1), 6
                ),
                tokens=len(req.tokens),
            )
        self._observe_done(req)

    def _observe_first_token(self, req: _Request, work_start: float) -> None:
        """First output token just landed on the host: observe
        queue-wait (submit → first device work) and TTFT (submit →
        first token), once per request."""

        if req.t_first is not None:
            return
        req.t_first = time.perf_counter()
        if req.entry is not None:
            self.request_log.update(
                req.entry,
                queue_wait_seconds=round(
                    max(0.0, work_start - req.t_local), 6
                ),
                ttft_seconds=round(req.t_first - req.t_submit, 6),
            )
        if self.metrics is None or req.internal:
            # internal fabric-publish prefills are not user requests —
            # observing them would pollute the user-facing quantiles
            return
        # {tier} on every pool SLO observation (ISSUE 12): /slo and
        # the dashboard report per-tier quantiles — "interactive p99
        # TTFT holds while batch degrades" is a query, not a guess
        self.metrics.observe_histogram(
            "serve_queue_wait_seconds",
            max(0.0, work_start - req.t_local),
            exemplar=req.trace_id,
            tier=req.tier,
            **self._labels(mode="pool"),
        )
        self.metrics.observe_histogram(
            "serve_ttft_seconds",
            req.t_first - req.t_submit,
            exemplar=req.trace_id,
            tier=req.tier,
            **self._labels(mode="pool"),
        )

    def _observe_done(self, req: _Request) -> None:
        """Request retired: observe time-per-output-token (first token
        → done, over the tokens after the first)."""

        if self.metrics is None or req.internal:
            return
        t_done = time.perf_counter()
        t_first = req.t_first if req.t_first is not None else t_done
        self.metrics.observe_histogram(
            "serve_time_per_output_token_seconds",
            (t_done - t_first) / max(1, len(req.tokens) - 1),
            exemplar=req.trace_id,
            tier=req.tier,
            **self._labels(mode="pool"),
        )

    def _update_gauges_locked(self) -> None:
        """Admission-queue depth + tokens-in-flight gauges (caller
        holds the pool lock)."""

        if self.metrics is None:
            return
        self.metrics.set(
            "serve_admission_queue_depth",
            float(len(self._queue)),
            **self._labels(),
        )
        inflight = sum(
            r.budget - len(r.tokens) for r in self._active.values()
        ) + sum(r.budget - len(r.tokens) for r in self._queue)
        self.metrics.set(
            "serve_tokens_in_flight",
            float(max(0, inflight)),
            **self._labels(),
        )

    # -- compiled pieces -------------------------------------------------

    def _prefill(self, width: int):
        with self._compile_lock:
            if width not in self._prefill_fns:
                dmodel = self.dmodel
                materialize = self._materialize

                def prefill(params, cache, ids):  # ids [1, width]
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": cache},
                        ids,
                        mutable=["cache"],
                    )
                    return vars_["cache"], logits[0, -1]

                self._prefill_fns[width] = self.costplane.compiles.wrap(
                    jax.jit(prefill), "pool.prefill",
                    trigger=f"width={width}",
                )
                self.compile_count += 1
            return self._prefill_fns[width]

    def _scatter(self):
        """Write one batch-1 cache + token into slot `i` of the stack."""

        with self._compile_lock:
            if self._scatter_fn is None:

                def scatter(stack, row_cache, last_tok, toks, i):
                    stack = jax.tree_util.tree_map(
                        lambda s, r: lax.dynamic_update_index_in_dim(
                            s, r, i, axis=0
                        ),
                        stack,
                        row_cache,
                    )
                    return stack, toks.at[i].set(last_tok)

                self._scatter_fn = self.costplane.compiles.wrap(
                    jax.jit(scatter), "pool.scatter", trigger="singleton"
                )
                self.compile_count += 1
            return self._scatter_fn

    def _fused_width(self, p: int) -> Optional[int]:
        """Padded width class for single-dispatch admission, or None
        when the request must take the legacy staged path (rolling-
        window cache, or a pad-to-pow2 width the cache can't hold)."""

        if self._max_chunk is not None:
            return None  # rolling cache: pad writes poison cached_pos
        w = 1 << max(0, p - 1).bit_length()
        return w if w <= self.max_len else None

    def _admission(self, width: int):
        """The whole admission as ONE compiled program per power-of-2
        prompt-width class: padded prefill into a fresh in-graph
        batch-1 cache, cache_index rollback to the true length (pad
        rows become invisible — set_cache_index, the speculative
        rollback primitive), first-token sample at the true last
        position, and the scatter-seating into slot `slot`.  Returns
        (stack, last_toks, first_token, advanced_rng) — the rng split
        happens in-graph so a sampled admission is still exactly one
        dispatch."""

        with self._compile_lock:
            if width not in self._admit_fns:
                dmodel = self.dmodel
                materialize = self._materialize
                template = self._row_shapes  # ShapeDtypeStructs

                def admit(params, stack, toks, ids, n, slot, temp,
                          top_k, rng):
                    cache = jax.tree_util.tree_map(
                        lambda l: jnp.zeros(l.shape, l.dtype), template
                    )
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": cache},
                        ids,
                        mutable=["cache"],
                    )
                    # causal masking: rows < n never see the pad rows,
                    # so the true last position's logits are exact;
                    # the index reset makes the pad K/V rows invisible
                    # to every later decode step
                    row_cache = set_cache_index(vars_["cache"], n)
                    last = lax.dynamic_index_in_dim(
                        logits[0], n - 1, axis=0, keepdims=False
                    )  # [V]
                    tok, rng_next = _admission_sample(last, temp, top_k, rng)
                    stack = jax.tree_util.tree_map(
                        lambda s, row: lax.dynamic_update_index_in_dim(
                            s, row, slot, axis=0
                        ),
                        stack,
                        row_cache,
                    )
                    return stack, toks.at[slot].set(tok), tok, rng_next

                self._admit_fns[width] = self.costplane.compiles.wrap(
                    jax.jit(admit), "pool.admit",
                    trigger=f"width={width}",
                )
                self.compile_count += 1
            return self._admit_fns[width]

    def _make_step_body(self, params, temps, top_ks):
        """The K-step scan body over the stacked slot cache — ONE
        definition shared by the contiguous step program and the paged
        step program (which feeds it a block-table-gathered view of
        the arena; identical math is the paged exactness contract).
        ``params`` is captured as a closure constant, exactly like the
        pre-refactor body (threading it through the scan carry would
        change the compiled program)."""

        dmodel = self.dmodel
        materialize = self._materialize

        def one_slot(p, cache, tok):
            # batch-1 apply; under vmap the weights broadcast and
            # the per-slot cache_index stays a scalar per slot
            logits, vars_ = dmodel.apply(
                {"params": p, "cache": cache},
                tok[None, None],
                mutable=["cache"],
            )
            return vars_["cache"], logits[0, 0]

        def body(carry, _):
            stack, toks, rngs = carry
            stk, logits = jax.vmap(
                one_slot, in_axes=(None, 0, 0)
            )(materialize(params), stack, toks)
            nxt, rngs_next = _step_sample(logits, temps, top_ks, rngs)
            return (stk, nxt, rngs_next), nxt

        return body

    def _step(self):
        if self._step_fn is None:
            n_inner = self.steps_per_sync
            make_body = self._make_step_body

            def step(params, stack, toks, temps, top_ks, rngs):
                # K decode steps per host round trip: the whole inner
                # loop is ONE XLA program, so a tunneled chip pays one
                # network round trip per K tokens, not per token.
                # Quantized trees: QDense families keep int8 all the
                # way to quant_matmul; others dequantize per step here.
                body = make_body(params, temps, top_ks)
                (stack, toks, _), toks_k = lax.scan(
                    body, (stack, toks, rngs), None, length=n_inner
                )
                return stack, toks, toks_k  # toks_k: [K, slots]

            self._step_fn = self.costplane.compiles.wrap(
                jax.jit(step), "pool.step",
                trigger=f"K={self.steps_per_sync}",
            )
            self.compile_count += 1
        return self._step_fn

    # -- public API ------------------------------------------------------

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        rng: Optional[jax.Array] = None,
        trace_id: Optional[str] = None,
        tier: str = "batch",
        internal: bool = False,
        t_submit: Optional[float] = None,
        t_submit_mono: Optional[float] = None,
    ) -> int:
        """Queue a single request ([P] int32).  Returns a request id;
        collect the output with `result` after `step`s (or `run`).

        ``tier`` is the request's SLO class (ISSUE 12):
        ``"interactive"`` requests are admitted ahead of ``"batch"``
        ones and may preempt batch seats under arena pressure in the
        paged pool; both pools label every SLO observation with it.
        Default ``"batch"`` — opting INTO priority is explicit.

        ``trace_id`` is the request's first-class identity (ISSUE 11):
        serve_lm passes its request span's trace id (which adopted any
        incoming ``x-trace-id``), so every lifecycle span the pool
        emits — queue.wait, admission, decode.window, retire — joins
        the caller's trace, and the autopsy lands in ``request_log``
        under that id.  Without one, the pool mints an id from its
        tracer (or a local fallback), so direct submitters get the
        same lifecycle record.

        ``internal`` marks a fabric-publish prefill (ISSUE 13): a real
        pool request in every mechanical sense, but excluded from the
        user-facing SLO observations.  ``t_submit``/``t_submit_mono``
        backdate the request's SLO clocks to an EARLIER submit (the
        disaggregated router passes its own entry time, so TTFT spans
        the whole prefill→migrate→decode handshake, not just the
        decode replica's slice)."""

        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len={self.max_len}"
            )
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature sampling needs an explicit rng key")
        if temperature == 0.0:
            top_k = None  # greedy ignores top_k (same as generate())
        if top_k is not None:
            top_k = int(top_k)
            if not (1 <= top_k <= TOP_K_MAX):
                raise ValueError(
                    f"top_k must be in [1, {TOP_K_MAX}] (the pool's "
                    f"static top-k width), got {top_k}"
                )
        if tier not in SLO_TIERS:
            raise ValueError(
                f"tier must be one of {SLO_TIERS}, got {tier!r}"
            )
        with self._lock:
            rid = self._rid
            self._rid += 1
        req = _Request(
            rid, prompt, max_new_tokens, float(temperature), top_k, rng,
            tier=tier, internal=internal,
        )
        if t_submit is not None:
            req.t_submit = float(t_submit)
        if t_submit_mono is not None:
            req.t_submit_mono = float(t_submit_mono)
        if trace_id is not None:
            req.trace_id = str(trace_id)
        elif self.tracer is not None:
            req.trace_id = self.tracer.mint_trace_id()
        else:
            req.trace_id = f"treq-{self.replica_label or 0}-{rid}"
        req.entry = self.request_log.open(
            id=req.trace_id, rid=rid,
            replica=self.replica_label or "0", model=self.model_label,
            prompt_tokens=int(prompt.size),
            max_new_tokens=int(max_new_tokens),
            tier=tier, internal=bool(internal),
        )
        # fused-eligible requests (non-rolling cache, pad width fits)
        # queue host-side untouched: their ENTIRE admission — prefill,
        # first token, seating — is one compiled dispatch in _admit,
        # so submit never touches the device.  Only the legacy path
        # (rolling-window caches, oversize pad widths) still prefills
        # eagerly on the submitter's thread under a staging permit;
        # past the permit bound it queues and primes lazily at
        # admission — submit never blocks on either path.
        if self._fused_width(prompt.size) is None and \
                self._staging.acquire(blocking=False):
            req.has_permit = True
            try:
                self._prefill_request(req)
            except BaseException:
                self._staging.release()
                raise
        with self._lock:
            self._results[rid] = req
            if req.staged_cache is not None and len(req.tokens) >= req.budget:
                # budget-1, eagerly prefilled: already complete —
                # never needs a slot
                req.done = True
                self._release_staged_locked(req)
                self._finish_request(req)
                self._done_cond.notify_all()
            else:
                self._queue.append(req)
            self._update_gauges_locked()
        return rid

    def _release_staged_locked(self, req: _Request) -> None:
        req.staged_cache = req.staged_tok = None
        if req.has_permit:
            req.has_permit = False
            self._staging.release()

    def _prefill_request(self, req: _Request) -> None:
        """Device-side admission work for one request — chunked prompt
        prefill into a fresh batch-1 cache plus the first sampled
        token — run with NO pool lock held (VERDICT r4 next #7: the
        old under-lock prefill serialized every concurrent submit()
        and the driver's step() behind a multi-device-call prefill;
        at seq-1k prompts on a tunneled chip that stalled the whole
        pool per admission).  Trade-off: a request waiting for a free
        slot holds its primed batch-1 cache in device memory — bounded
        by the staging semaphore (2x slots permits; see __init__),
        which blocks further submits instead of letting a request
        burst OOM the chip."""

        work_start = time.perf_counter()
        # queue.wait ends HERE — the first device work — matching the
        # serve_queue_wait_seconds metric's clock; the later seating
        # scatter is admission work, not queueing (a span emitted at
        # seating would swallow the prefill into "queue.wait")
        self._emit_queue_wait(req)
        cache = _init_cache_for(self.dmodel, 1)
        last = None
        off = 0
        for width in window_chunks(req.prompt.size, self._max_chunk):
            ids = jnp.asarray(
                req.prompt[off : off + width][None, :], jnp.int32
            )
            with self.dispatch("prefill", rid=req.rid):
                cache, last = self._prefill(width)(self.params, cache, ids)
            if req.entry is not None:
                self.request_log.count_dispatch(req.entry, "prefill")
            off += width
        # the prompt's first sampled token comes from prefill logits.
        # Recorded as one "sample" ledger entry — the un-jitted op
        # group below is 1 (greedy) to ~3 (split+mask+categorical)
        # tiny device calls; the fused admission folds all of this
        # into its single program
        with self.dispatch("sample", rid=req.rid):
            if req.temperature > 0.0:
                req.rng, r = jax.random.split(req.rng)
                scaled = last / req.temperature
                if req.top_k is not None:
                    scaled = top_k_mask(scaled, req.top_k)
                tok = jax.random.categorical(r, scaled).astype(jnp.int32)
            else:
                tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        req.staged_cache = cache
        req.staged_tok = tok
        req.tokens.append(int(tok))
        if req.entry is not None:
            self.request_log.count_dispatch(req.entry, "sample")
        self._observe_first_token(req, work_start)

    def _admit_fused(self, req: _Request, slot: int, width: int) -> None:
        """Seat one request with exactly ONE device dispatch (the fused
        per-width admission program).  Caller holds the pool lock: the
        program rewrites the shared slot stack, so it must serialize
        with step() — the device would serialize the programs anyway;
        the lock only mirrors that ordering on the host."""

        ids = np.zeros((1, width), np.int32)
        ids[0, : req.prompt.size] = req.prompt
        sampled = req.temperature > 0.0
        rng = req.rng if sampled else jnp.zeros((2,), jnp.uint32)
        work_start = time.perf_counter()
        self._emit_queue_wait(req)
        with self._request_span(req, "admission", width=width, slot=slot):
            with self.dispatch("admission", rid=req.rid, width=width):
                stack, toks, tok, rng_next = self._admission(width)(
                    self.params, self._cache, self._last_tok,
                    jnp.asarray(ids), jnp.int32(req.prompt.size),
                    jnp.int32(slot), jnp.float32(req.temperature),
                    jnp.int32(req.top_k or 0), rng,
                )
                tok_h = int(tok)  # host fetch: the ledger RTT includes it
        self._cache, self._last_tok = stack, toks
        if sampled:
            req.rng = rng_next
        req.tokens.append(tok_h)
        if req.entry is not None:
            self.request_log.count_dispatch(req.entry, "admission")
            self.request_log.update(
                req.entry, state="active", slot=slot,
                admission={
                    "width": int(width),
                    "prefill_dispatches": 0,
                    "seconds": round(time.perf_counter() - work_start, 6),
                },
            )
        self._observe_first_token(req, work_start)
        if len(req.tokens) >= req.budget:
            # budget-1: the admission token completed it; the scattered
            # cache rows are dead and the slot stays free
            req.done = True
            self._finish_request(req)
            self._done_cond.notify_all()
        else:
            req.slot = slot
            self._active[slot] = req

    def _admit(self) -> None:
        """Seat queued requests into free slots.

        Fused path (non-rolling caches): the whole admission is ONE
        compiled dispatch under the lock (_admit_fused).  Legacy path
        (rolling-window caches / oversize pad widths): reserve a seat
        under the lock; prefill with the lock DROPPED if the request
        arrived un-staged (permit-exhausted burst took the lazy path);
        then scatter + bookkeeping under the lock — lock-held legacy
        device work is always exactly ONE scatter call."""

        while True:
            with self._lock:
                if not self._queue:
                    return
                free = [
                    s for s in range(self.slots)
                    if s not in self._active and s not in self._reserved
                ]
                if not free:
                    return
                req = self._queue.pop(0)
                slot = free[0]
                width = self._fused_width(req.prompt.size)
                if width is not None and req.staged_cache is None:
                    try:
                        self._admit_fused(req, slot, width)
                        self._update_gauges_locked()
                    except BaseException:
                        # same survival rule as the legacy prefill: a
                        # transient device failure must re-queue the
                        # request, not strand its rid in _results with
                        # waiters blocked forever (_admit_fused mutates
                        # pool state only after a successful dispatch,
                        # so head-of-queue reinsertion is safe)
                        self._queue.insert(0, req)
                        raise
                    continue
                self._reserved.add(slot)
            try:
                if req.staged_cache is None:
                    self._prefill_request(req)  # lazy path, off-lock
            except BaseException:
                # the request must survive a transient prefill failure
                # (device OOM is the exact pressure this path exists
                # for): back to the queue head so a retried step() can
                # admit it; without this the rid would leak in
                # _results and its waiters would hang forever
                with self._lock:
                    self._reserved.discard(slot)
                    self._queue.insert(0, req)
                raise
            with self._lock:
                self._reserved.discard(slot)
                if len(req.tokens) >= req.budget:
                    # budget-1 on the lazy path: the prefill token
                    # completed it — never needs the seat after all
                    req.done = True
                    self._release_staged_locked(req)
                    self._finish_request(req)
                    self._update_gauges_locked()
                    self._done_cond.notify_all()
                    continue
                with self._request_span(req, "admission", slot=slot,
                                        path="staged"):
                    with self.dispatch("scatter", rid=req.rid):
                        self._cache, self._last_tok = self._scatter()(
                            self._cache, req.staged_cache, req.staged_tok,
                            self._last_tok, jnp.int32(slot),
                        )
                self._release_staged_locked(req)
                if req.entry is not None:
                    self.request_log.count_dispatch(req.entry, "scatter")
                    self.request_log.update(
                        req.entry, state="active", slot=slot,
                        admission={
                            "width": None,
                            "prefill_dispatches": req.entry["dispatches"]
                            .get("prefill", 0),
                            "path": "staged",
                        },
                    )
                req.slot = slot
                self._active[slot] = req
                self._update_gauges_locked()

    def load_score(self) -> float:
        """Routing pressure for the multi-replica router
        (models/pool_router.py): active + queued request count.  The
        paged subclass overrides with real memory pressure (blocks in
        use + queued block demand over arena size)."""

        components = self.load_components()
        return components["prefill"] + components["decode"]

    def load_components(self) -> Dict[str, float]:
        """``load_score`` split by PHASE (ISSUE 13): ``prefill`` is
        pending admission work (queued requests — what a prefill
        replica burns down), ``decode`` is resident work (active
        seats).  The disaggregated router routes each phase to the
        replica with the lowest matching component; their sum is the
        legacy scalar ``load_score``."""

        with self._lock:
            return {
                "prefill": float(len(self._queue)),
                "decode": float(len(self._active)),
            }

    def step(self) -> int:
        """Admit waiting requests, run `steps_per_sync` decode steps
        for every active slot (one XLA program, one host round trip),
        append sampled tokens, retire finished requests.  Returns the
        number of still-active slots."""

        self._admit()
        with self._lock:
            if not self._active:
                return 0
            temps = np.zeros((self.slots,), np.float32)
            top_ks = np.zeros((self.slots,), np.int32)  # 0 = no top_k
            # legacy uint32[2] keys vmap as plain rows; dead slots get
            # key 0 but their temps=0 routes them to the greedy branch
            rngs = np.zeros((self.slots, 2), np.uint32)
            for slot, req in self._active.items():
                temps[slot] = req.temperature
                top_ks[slot] = req.top_k or 0
                if req.temperature > 0.0:
                    req.rng, r = jax.random.split(req.rng)
                    rngs[slot] = np.asarray(r)
            seats_active = len(self._active)
            t_window0 = time.monotonic()
            with self.dispatch("step", active=seats_active):
                self._cache, self._last_tok, toks_k = self._step()(
                    self.params,
                    self._cache,
                    self._last_tok,
                    jnp.asarray(temps),
                    jnp.asarray(top_ks),
                    jnp.asarray(rngs),
                )
                host_toks = np.asarray(toks_k)  # [K, slots]
            t_window1 = time.monotonic()
            # ISSUE 20 step-time sentinel: the window wall is already a
            # host monotonic difference — one observation per window,
            # zero extra device traffic
            self.costplane.sentinel.observe(
                "decode.window", t_window1 - t_window0
            )
            finished = False
            for slot in list(self._active):
                req = self._active[slot]
                take = min(len(host_toks), req.budget - len(req.tokens))
                req.tokens.extend(int(t) for t in host_toks[:take, slot])
                self._emit_span(
                    req, "decode.window", t_window0, t_window1,
                    tokens=take, seats_active=seats_active,
                )
                if req.entry is not None:
                    self.request_log.add_window(req.entry, take)
                if len(req.tokens) >= req.budget:
                    # overshoot steps (< K) wrote only this slot's own
                    # dead cache rows; admission scatters a fresh cache
                    req.done = True
                    req.slot = None
                    del self._active[slot]
                    self._finish_request(req)
                    finished = True
            self._update_gauges_locked()
            if finished:
                self._done_cond.notify_all()
            return len(self._active)

    def run(self) -> None:
        """Step until every submitted request has finished."""

        while True:
            with self._lock:
                idle = not self._queue and not self._active
            if idle:
                return
            self.step()

    def result(self, rid: int):
        """[P + n] int32 (prompt + generated), or None if not done.

        A finished request is EVICTED on first read — a long-running
        server submits without bound, so retaining every finished
        request would be a memory leak.  Read once, keep the array."""

        with self._lock:
            req = self._results.get(rid)
            if req is None:
                raise KeyError(
                    f"request {rid} unknown or already collected "
                    "(results evict on first read)"
                )
            if not req.done:
                return None
            del self._results[rid]
        return np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)])

    def result_wait(self, rid: int, timeout: Optional[float] = None):
        """Block (condition wait, no polling) until request `rid`
        finishes; returns the [P + n] int32 row, or None on timeout.
        Evicts on success like `result`; a second wait on a collected
        rid raises KeyError rather than blocking forever."""

        with self._done_cond:
            ok = self._done_cond.wait_for(
                lambda: rid not in self._results or self._results[rid].done,
                timeout=timeout,
            )
            if not ok:
                return None
            req = self._results.pop(rid, None)
            if req is None:
                raise KeyError(
                    f"request {rid} unknown or already collected "
                    "(results evict on first read)"
                )
        return np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)])


class PagedContinuousBatchingDecoder(ContinuousBatchingDecoder):
    """The pool with a PAGED KV cache (ISSUE 8 tentpole): seats no
    longer own contiguous max_len caches — one pre-allocated block
    arena (``models/decode.paged_arena``) backs every seat through a
    per-seat block table, and ADMISSION IS GATED ON BLOCKS FREE, not
    slots free.  A short request reserves only
    ``ceil((prompt+budget)/block_size)`` blocks, so at the same HBM
    budget the paged pool admits strictly more concurrent mixed-length
    requests than the slot pool (the `measure.py --section paged`
    acceptance comparison).

    Reservation is BUDGET-ON-DEMAND (ISSUE 12): admission commits only
    the prompt's blocks plus one decode block (capped at the worst
    case); every later block is allocated lazily at its block
    boundary, in the once-per-window host window, by feeding the table
    delta INTO the single step dispatch (the program writes it
    in-graph before decoding — steady state stays exactly 1
    dispatch/step, ledger- and lint-pinned).  Most requests finish
    well short of their budget, so the arena oversubscribes: strictly
    more concurrent seats at the same HBM than PR 8's worst-case
    reservation (measured, `measure.py --section paged` leg E).  The
    gamble is made SAFE by mid-decode preemption: when a lazy
    allocation finds the arena empty, the scheduler picks a victim
    (lowest tier, then most-blocks, then least-progress — never a
    seat that has not produced a window since seating, the
    anti-livelock rule), snapshots its private blocks to the host-side
    SwapArena (kv_blocks.py; prefix-cache-shared blocks are
    swap-EXEMPT — refcounts keep them device-resident and they re-map
    copy-free at resume), frees the device blocks, resets the seat's
    device row, and re-queues the victim; resume re-admits by
    uploading the swapped blocks into freshly allocated ones in one
    ``swap_in`` dispatch, rng/length/last-token restored exactly — a
    preempted-then-resumed request is token-identical to an
    undisturbed run (test-pinned).  ``reserve="worst-case"`` restores
    the PR 8 full-reservation contract (the measured baseline leg).

    SLO TIERS (ISSUE 12): ``submit(..., tier="interactive"|"batch")``.
    Admission order is priority, not FIFO — interactive first, ties
    FIFO — with a bounded anti-starvation boost: a batch request
    queued longer than ``age_boost_seconds`` is ordered like an
    interactive one (boost affects ORDER only, never preemption
    rights).  Interactive admissions and growths may preempt batch
    seats; batch may preempt only batch.  When the swap arena is also
    exhausted the grower parks (re-queued holding its live blocks,
    zero-copy) — requests queue, the pool never crashes mid-decode
    and never corrupts a seat (the oversubscription honesty rule,
    docs/SERVING.md).

    The admission program gathers a seat's blocks into the exact
    contiguous view the unchanged attention math expects and scatters
    back only the newly written blocks (see decode.py — identity
    re-layout, so paged decode is token-identical to the contiguous
    pool, test-pinned).

    Steady-state decode (ISSUE 10): the step program runs over
    DEVICE-RESIDENT state only — block tables, per-seat lengths,
    sampling params and rng keys are written once at admission (in the
    fused admission program), advanced in-graph per window, and reset
    by one batched ``retire`` dispatch when seats finish — zero
    per-step uploads and zero host gathers beyond the sanctioned token
    fetch inside the ledger's dispatch window.  With ``paged_kernel``
    resolved to a kernel impl ("auto" on the TPU backend, "on" to
    force, "interpret" for CI), the scan body is the PAGED decode
    branch: each step appends the new token's K/V in place to its
    seat's block and attends straight off the arena through the
    ops/paged_attention Pallas kernel — the gather → scan →
    scatter-back emulation (and its ~2x KV traffic) exists only as
    the CPU/"off" fallback, and an explicit "on"/"interpret" FAILS
    where the kernel cannot serve rather than silently downgrading.

    Prefix cache: completed prompt blocks are published under rolling
    token-hash chain keys (models/prefix_cache.py); a new request maps
    its longest cached prefix COPY-FREE into its block table
    (refcounted — a shared block is never reclaimed while any seat
    maps it), and only prefills the remainder, still in ONE fused
    admission dispatch.  A full hit prefills at most one block's worth
    of tokens: ledger-pinned as ``admission == 1, prefill == 0`` per
    request with the admission width collapsed to the remainder class
    (extending the PR-3 single-dispatch contract; the last prompt
    token always re-runs because its logits seed the first sampled
    token).

    Staging backpressure is structural here: submit() never touches
    the device (every admission is fused), queued requests hold host
    prompts only, and arena pressure evicts UNMAPPED prefix-cache
    entries LRU-first before an admission blocks — the documented
    OOM hazard of the legacy eager-staging path cannot exist.

    Rolling-window models are not pageable (their wrap state aliases
    positions); construction refuses them — serve those through the
    contiguous pool.
    """

    def __init__(self, model, params, slots: int = 8,
                 steps_per_sync: int = 8, kv_blocks: Optional[int] = None,
                 kv_block_size: int = 16,
                 ledger: Optional[DispatchLedger] = None,
                 metrics=None, model_label: str = "",
                 replica_label: str = "",
                 prefix_cache_entries: Optional[int] = None,
                 paged_kernel: str = "auto",
                 reserve: str = "lazy",
                 swap_blocks: Optional[int] = None,
                 age_boost_seconds: float = 30.0,
                 role: str = "unified",
                 fabric=None,
                 draft_model=None, draft_params=None,
                 spec_k: int = 4,
                 spec_tiers=("interactive",),
                 costplane=None):
        super().__init__(
            model, params, slots=slots, steps_per_sync=steps_per_sync,
            ledger=ledger, metrics=metrics, model_label=model_label,
            replica_label=replica_label, role=role, costplane=costplane,
        )
        #: ISSUE 13: the shared prefix-cache FABRIC
        #: (models/prefix_cache.PrefixFabric) — the migration transport
        #: of disaggregated serving.  With one attached, admission
        #: pulls missing prefix blocks from it (``migrate_in``) and
        #: ``publish_to_fabric`` pushes finished prompt blocks into it
        #: (``migrate_out``).  None = this replica neither publishes
        #: nor pulls (the pre-split pool).
        self.fabric = fabric
        if role == "prefill" and fabric is None:
            raise ValueError(
                "a prefill-role replica is pointless without a "
                "prefix-cache fabric to publish into — pass fabric="
            )
        # -- paged_kernel mode validation FIRST (ISSUE 10 honesty): a
        # typo'd mode must fail even for models whose pageability
        # checks below raise NotPageableError — serve_lm's model-shape
        # fallback would otherwise swallow the config error.
        mode = str(paged_kernel or "auto").lower()
        if mode not in ("auto", "on", "off", "interpret"):
            raise ValueError(
                f"paged_kernel must be auto|on|off|interpret, got "
                f"{paged_kernel!r}"
            )
        self.paged_kernel_mode = mode
        if reserve not in ("lazy", "worst-case"):
            raise ValueError(
                f"reserve must be 'lazy' or 'worst-case', got {reserve!r}"
            )
        #: ISSUE 12 admission contract: "lazy" commits prompt blocks
        #: (+1 decode block) and grows at block boundaries;
        #: "worst-case" restores the PR 8 full prompt+budget
        #: reservation (the measured baseline — no growth, no
        #: preemption pressure from admitted seats)
        self.reserve = reserve
        self.age_boost_seconds = float(age_boost_seconds)
        try:
            if self._max_chunk is not None:
                raise NotPageableError(
                    "rolling-window caches are not pageable (wrap state "
                    "aliases positions); use ContinuousBatchingDecoder"
                )
            bs = int(kv_block_size)
            if bs < 1 or self.max_len % bs:
                raise ValueError(
                    f"kv_block_size={bs} must divide max_len={self.max_len}"
                )
            self.block_size = bs
            self.max_blocks = self.max_len // bs
            if kv_blocks is None:
                # default arena = the HBM the contiguous pool would pin
                # (slots × max_len): same budget, block-granular
                # admission
                kv_blocks = self.slots * self.max_blocks
            #: arena rows = usable blocks + the scratch block (id 0)
            self.num_blocks = int(kv_blocks) + 1
            self.alloc = BlockAllocator(self.num_blocks, bs)
            self._arena = paged_arena(self.dmodel, self.num_blocks, bs)
            # ISSUE 20 HBM accounting: the arena is this pool's big
            # device allocation — register it (add: replicas sharing
            # one CostPlane each contribute theirs), and keep the
            # per-block host byte size for the swap-staging gauge
            self.costplane.hbm.register_tree("kv_arena", self._arena)
            self._block_host_bytes = sum(
                int(leaf.nbytes)
                for leaf in jax.tree_util.tree_leaves(self._arena)
                if hasattr(leaf, "nbytes")
            ) // max(1, self.num_blocks)
            if fabric is not None and hasattr(fabric, "register_template"):
                # fleet fabric (ISSUE 17): the wire decoder rebuilds
                # pulled block records against this arena's treedef
                fabric.register_template(self._arena)
        except NotPageableError as exc:
            if mode in ("on", "interpret"):
                # an EXPLICIT kernel request on a model that cannot
                # page at all is a config error, not a model-shape
                # fallback — fail instead of letting serve_lm quietly
                # serve the contiguous pool with no kernel
                raise ValueError(
                    f"paged_kernel={mode!r} refused: {exc} — failing "
                    "instead of silently downgrading to the contiguous "
                    "pool"
                ) from exc
            raise
        # -- fused Pallas decode (ISSUE 10): paged_kernel selects the
        # steady-state step program.  "auto" fuses on the TPU backend
        # and falls back to the gather emulation elsewhere; an explicit
        # "on" FAILS when the kernel cannot serve here (the
        # NotPageableError-style honesty rule: never silently
        # downgrade what the operator asked for); "interpret" runs the
        # real kernel through the Pallas interpreter (the CI path);
        # "off" pins the emulation.
        from tf_operator_tpu.ops.paged_attention import (
            paged_kernel_available,
        )

        head_dim = self.dmodel.cfg.head_dim
        self._kernel_impl: Optional[str] = None
        if mode != "off":
            ok, why = paged_kernel_available(
                head_dim, bs, interpret=(mode == "interpret")
            )
            if mode == "auto":
                self._kernel_impl = "pallas" if ok else None
            elif not ok:
                raise ValueError(
                    f"paged_kernel={mode!r} refused: {why} — failing "
                    "instead of silently serving the gather emulation"
                )
            else:
                self._kernel_impl = (
                    "pallas-interpret" if mode == "interpret" else "pallas"
                )
        self._pmodel = (
            paged_decode_variant(model, self._kernel_impl)
            if self._kernel_impl is not None
            else None
        )
        # -- speculative decoding (ISSUE 18): the draft model's KV
        # pages through the SAME BlockAllocator arena — draft blocks
        # are just blocks (refcounted, preemptable, visible in the
        # kv_blocks_pressure gauge), so speculation costs blocks, not
        # a second cache.  Draft tensors live in their own arena TREE
        # (different head/layer shapes) but every physical id comes
        # from self.alloc, and conservation (free + live == usable)
        # covers both trees by construction.
        self.spec_enabled = draft_model is not None
        self.spec_k = int(spec_k)
        self.spec_tiers = tuple(spec_tiers)
        self._draft_dmodel = None
        self._draft_pmodel = None
        self._draft_params = None
        self._draft_materialize = None
        self._draft_arena = None
        self._draft_tables_dev = None
        self._draft_rngs_dev = None
        #: draft twin of _seat_refs: logical-order physical ids behind
        #: a speculating seat's draft table row (all private — the
        #: draft cache is never prefix-shared)
        self._draft_refs: Dict[int, List[int]] = {}
        self._draft_admit_fns: Dict[int, Any] = {}
        self._spec_draft_fn = None
        self._spec_verify_fn = None
        # host counters behind the CPU-honest acceptance metric:
        # dispatches-per-emitted-token = 2 * spec_windows / spec_emitted
        self.spec_windows = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rollbacks = 0
        self.spec_emitted = 0
        if self.spec_enabled:
            # config errors FAIL here (the PR 10 honesty rule): a
            # typo'd tier or an unusable draft must never silently
            # downgrade to non-speculative serving
            if self.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k!r}")
            bad = [t for t in self.spec_tiers if t not in _TIER_RANK]
            if bad:
                raise ValueError(
                    f"spec_tiers {bad} are not SLO tiers {SLO_TIERS} — "
                    "failing instead of silently serving them "
                    "non-speculatively"
                )
            if draft_params is None:
                raise ValueError("draft_model requires draft_params")
            self._draft_dmodel = _decode_variant(draft_model)
            if self._draft_dmodel.cfg.max_len != self.max_len:
                raise ValueError(
                    f"draft max_len={self._draft_dmodel.cfg.max_len} != "
                    f"target max_len={self.max_len} — the shared block "
                    "tables need one geometry"
                )
            try:
                self._draft_arena = paged_arena(
                    self._draft_dmodel, self.num_blocks, bs
                )
            except NotPageableError as exc:
                raise ValueError(
                    f"draft model cannot page: {exc} — failing instead "
                    "of silently serving non-speculatively"
                ) from exc
            # the draft-cache twin is arena memory too (ISSUE 20)
            self.costplane.hbm.register_tree("kv_arena", self._draft_arena)
            self._draft_pmodel = (
                paged_decode_variant(draft_model, self._kernel_impl)
                if self._kernel_impl is not None
                else None
            )
            self._draft_params = draft_params
            self._draft_materialize = materialize_fn(draft_model)
            self._draft_tables_dev = jnp.full(
                (self.slots, self.max_blocks), SCRATCH_BLOCK, jnp.int32
            )
            self._draft_rngs_dev = jnp.zeros((self.slots, 2), jnp.uint32)
        # per-seat block tables + lengths are DEVICE-RESIDENT (ISSUE
        # 10 satellite): written in-graph by the fused admission
        # program, advanced in-graph by the step program, reset by the
        # retire program — zero per-step table uploads and no host
        # mirror to drift out of sync.
        self._tables_dev = jnp.full(
            (self.slots, self.max_blocks), SCRATCH_BLOCK, jnp.int32
        )
        self._lengths_dev = jnp.zeros((self.slots,), jnp.int32)
        #: per-seat sampling state, device-resident for the same
        #: reason: temps/top_ks are static per request (written at
        #: admission), rng keys advance in-graph (the per-window
        #: split that the contiguous pool does host-side happens
        #: inside the step program — same split chain, zero uploads)
        self._temps_dev = jnp.zeros((self.slots,), jnp.float32)
        self._topks_dev = jnp.zeros((self.slots,), jnp.int32)
        self._rngs_dev = jnp.zeros((self.slots, 2), jnp.uint32)
        self._retire_fn = None
        #: logical-block-ordered physical ids per seat: entry i is the
        #: block behind table row position i (admission builds it in
        #: that order, growth appends) — the host mirror preemption
        #: needs to know WHICH physical block sits at which logical
        #: index without fetching the device table
        self._seat_refs: Dict[int, List[int]] = {}
        #: host-side swap arena (ISSUE 12): preempted seats' private
        #: block content lives here until resume re-uploads it
        self.swap = SwapArena(capacity_blocks=swap_blocks)
        self.preemptions = 0  # host counter, mirrored to metrics
        # ONE jitted gather/swap-in each (both are shape-polymorphic —
        # nothing closes over the class), with the pow2 classes seen
        # tracked only so compile_count keeps matching real compiles
        self._swap_gather_fn = None
        self._swap_in_fn = None
        self._swap_gather_classes: set = set()
        self._swap_in_classes: set = set()
        #: step write-back window: K new positions straddle at most
        #: this many blocks (start block + full span + boundary); a
        #: speculative verify window appends spec_k + 1 positions, so
        #: the wider of the two advances sizes the delta arrays
        adv = max(
            self.steps_per_sync,
            (self.spec_k + 1) if self.spec_enabled else 1,
        )
        self._step_nbw = (adv - 1) // bs + 2
        #: shared prefix store — evictable only while NOTHING maps the
        #: block (allocator refcount 1 = the cache's own reference)
        self.prefix = PrefixCache(
            capacity=prefix_cache_entries,
            metrics=self.metrics,
            mode="pool",
            can_evict=lambda bid: self.alloc.refcount(bid) == 1,
            on_evict=lambda bid: self.alloc.release([bid]),
        )
        #: ISSUE 11: bounded occupancy history — one sample per gauge
        #: refresh (every decode window + admission/retire), served at
        #: /debug/arena and carried in flight-recorder dumps; the
        #: time-series twin of the kv_blocks_pressure gauge
        self.timeline = ArenaTimeline(
            block_size=self.block_size, usable=self.alloc.usable,
            replica=self.replica_label or "0", role=self.role,
        )
        # ONE jitted fabric upload (shape-polymorphic like the swap
        # pair); pow2 classes tracked only for compile_count honesty
        self._migrate_scatter_fn = None
        self._migrate_scatter_classes: set = set()
        self._update_kv_gauges()

    def _init_pool_cache(self, row0) -> None:
        self._cache = None  # the arena replaces the slot stack

    # -- accounting --------------------------------------------------------

    def _update_kv_gauges(self) -> None:
        """kv_blocks_{free,total,in_use,queued_demand} +
        kv_blocks_pressure gauges, labeled {model, replica} — the
        blocks-free pressure signal the stock serving autoscaling
        policy and the kv-blocks-pressure alert rule bind
        (tests/test_autoscaling_lint.py pins the names+keys against
        these literal call sites).

        ISSUE 10: pressure includes the block DEMAND already queued,
        i.e. (in_use + queued_need) / usable, and is refreshed every
        decode window — a traffic burst ramps the signal request by
        request as the queue builds (it can exceed 1.0 under backlog),
        instead of step-functioning only when admission/release land.
        The PR-7 autoscaler and the 0.9 alert rule therefore see the
        ramp mid-burst, while an idle pool with cold cache entries
        still reads plain occupancy."""

        free = float(self.alloc.free_count)
        total = float(self.alloc.usable)
        queued = float(self._queued_blocks())
        # ISSUE 12 committed-vs-reserved split: committed = blocks
        # actually allocated (what lazy admission pinned so far);
        # reserved = the worst-case prompt+budget demand of the
        # admitted seats (what PR 8 would have pinned up front).
        # reserved / usable > 1 is the oversubscription gamble made
        # visible; pressure stays COMMITTED-based — the real headroom
        # signal the autoscaler and the 0.9 alert act on.
        reserved = float(sum(
            blocks_for(r.prompt.size + r.budget, self.block_size)
            for r in self._active.values()
        ))
        # timeline sample regardless of a metrics sink: the occupancy
        # history is its own read surface (host arithmetic only)
        self.timeline.sample(
            free=int(free),
            live=int(total - free),
            prefix_cached=len(self.prefix),
            queued_demand=int(queued),
            seats_active=len(self._active),
            swapped=int(self.swap.swapped_blocks),
        )
        # ISSUE 20: swap staging is host RAM pinned by preempted seats'
        # private blocks — the cost plane accounts it per replica
        # (pure host arithmetic: block count x per-block bytes)
        self.costplane.hbm.set_component(
            "swap_staging",
            self.swap.swapped_blocks * self._block_host_bytes,
            device=f"host:{self.replica_label or '0'}",
        )
        if self.metrics is None:
            return
        rep = self.replica_label or "0"
        # {role=} on EVERY kv_blocks_* gauge (ISSUE 13): a
        # disaggregated fleet's autoscaler scales the prefill and
        # decode replica classes independently off
        # kv_blocks_pressure{role=}; unified pools export
        # role="unified" so the label key is always present (the lint
        # collectors pin it at these literal sites)
        self.metrics.set(
            "kv_blocks_free", free, model=self.model_label, replica=rep,
            role=self.role,
        )
        self.metrics.set(
            "kv_blocks_total", total, model=self.model_label, replica=rep,
            role=self.role,
        )
        self.metrics.set(
            "kv_blocks_in_use", total - free,
            model=self.model_label, replica=rep, role=self.role,
        )
        self.metrics.set(
            "kv_blocks_committed", total - free,
            model=self.model_label, replica=rep, role=self.role,
        )
        self.metrics.set(
            "kv_blocks_reserved", reserved,
            model=self.model_label, replica=rep, role=self.role,
        )
        self.metrics.set(
            "kv_blocks_queued_demand", queued,
            model=self.model_label, replica=rep, role=self.role,
        )
        self.metrics.set(
            "kv_blocks_pressure", (total - free + queued) / total,
            model=self.model_label, replica=rep, role=self.role,
        )

    def _update_gauges_locked(self) -> None:
        super()._update_gauges_locked()
        self._update_kv_gauges()

    def blocks_in_use(self) -> int:
        return self.alloc.in_use

    def _commit_blocks(self, p_len: int, budget: int) -> int:
        """Blocks admission COMMITS for a request (ISSUE 12): the
        prompt's blocks plus one decode block under lazy reservation
        (capped at the worst case — never over-commit a short budget);
        the full prompt+budget worst case in "worst-case" mode."""

        bs = self.block_size
        full = blocks_for(p_len + budget, bs)
        if self.reserve != "lazy":
            return full
        # the FIRST WINDOW's coverage rides along (equal to the +1
        # decode block whenever K <= block_size): admitting a seat
        # that cannot run a single window would just park it again —
        # a wasted prefill + swap round trip under pressure (the same
        # convergence gate _plan_resume_locked applies; review)
        first = blocks_for(
            min(p_len + self.steps_per_sync, max(p_len + budget - 1, 1)),
            bs,
        )
        return min(max(blocks_for(p_len, bs) + 1, first), full)

    def _queued_blocks(self) -> int:
        """Block demand of queued-but-unadmitted requests — ONE
        definition feeding both the kv_blocks_pressure gauge (the
        autoscaler/alert signal) and the router's load_score, so the
        two can never silently diverge.  A fresh request demands its
        admission COMMIT (not the worst case — lazy admission will
        only pin that much); a preempted one demands the blocks its
        resume must re-upload.  Caller holds the pool lock (both call
        sites do)."""

        total = 0
        for r in self._queue:
            if r.swapped:
                rec = self.swap.peek(r.rid)
                total += rec["n_blocks"] if rec is not None else 0
            else:
                commit = self._commit_blocks(r.prompt.size, r.budget)
                if self._spec_req(r):
                    commit *= 2  # the draft-cache twin rides admission
                total += commit
        return total

    def load_score(self) -> float:
        """Least-BLOCKS-in-use routing signal: live arena occupancy
        plus the block demand already queued, normalized by arena size
        — the router sends the next request to real memory headroom,
        not just the shortest queue."""

        components = self.load_components()
        return components["prefill"] + components["decode"]

    def load_components(self) -> Dict[str, float]:
        """Phase split of the block-pressure score (ISSUE 13):
        ``prefill`` = queued block demand (admission work still to
        prefill) / usable, ``decode`` = blocks live in the arena
        (resident decode state) / usable.  Sum == the legacy
        ``load_score``; the disaggregated router picks the prefill
        replica by the former and the decode replica by the latter."""

        with self._lock:
            queued = self._queued_blocks()
        usable = max(1, self.alloc.usable)
        return {
            "prefill": queued / usable,
            "decode": self.alloc.in_use / usable,
        }

    # -- admission ---------------------------------------------------------

    def _paged_width(self, r: int) -> int:
        """Compiled admission width class for ``r`` remainder tokens:
        the next power of two, capped at max_len (prompts always fit —
        submit validated prompt+budget <= max_len).  Class count stays
        logarithmic (+1 for the exact-max_len cap)."""

        w = 1 << max(0, r - 1).bit_length()
        return w if w <= self.max_len else self.max_len

    def _fused_width(self, p: int) -> Optional[int]:
        # every paged admission is fused — base submit() must never
        # take the legacy eager-staging branch
        return self._paged_width(p)

    def _spec_tier(self, tier: str) -> bool:
        """True when requests of ``tier`` decode speculatively — the
        SLO-tier gate of ISSUE 18 (interactive wants the latency win;
        batch throughput does not want the draft FLOPs)."""

        return self.spec_enabled and tier in self.spec_tiers

    def _spec_req(self, req: _Request) -> bool:
        return self._spec_tier(req.tier)

    def submit(self, prompt_ids, max_new_tokens, **kw) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if max_new_tokens >= 1 and prompt.size >= 1:
            need = blocks_for(prompt.size + max_new_tokens, self.block_size)
            if self._spec_tier(kw.get("tier", "batch")):
                # a speculating seat pins a draft-cache twin of every
                # target block — admission could never succeed past
                # half the arena
                need *= 2
            if need > self.alloc.usable:
                raise ValueError(
                    f"request needs {need} KV blocks but the arena has "
                    f"only {self.alloc.usable} — admission could never "
                    "succeed (raise kv_blocks or lower the budget)"
                )
        return super().submit(prompt_ids, max_new_tokens, **kw)

    def _plan_admission(self, req: _Request):
        """Reserve the request's COMMIT blocks (caller holds the pool
        lock) — prompt (+1 decode block) under lazy reservation, the
        full budget in worst-case mode.  Longest cached prefix is
        retained FIRST (pinning it against eviction), fresh blocks are
        allocated for the rest, and on shortfall unmapped prefix-cache
        entries are evicted LRU-first — then, for an INTERACTIVE
        request, batch seats are preempted (the tier policy) — before
        giving up.  Returns a plan dict or None (arena exhausted —
        admission stays gated on blocks free)."""

        bs = self.block_size
        p_len = req.prompt.size
        keys = chain_keys(req.prompt, bs)
        shared: List[int] = []
        # usable prefix caps at the last FULL block strictly before the
        # prompt's final token: its logits seed the first sample, so
        # the last token always re-runs through admission prefill
        for key in keys[: (p_len - 1) // bs]:
            bid = self.prefix.peek(key)
            if bid is None:
                break
            shared.append(int(bid))
        # the padded remainder must still fit the cache view: drop
        # trailing shared blocks until prefix + width class <= max_len
        while shared and \
                len(shared) * bs + self._paged_width(p_len - len(shared) * bs) \
                > self.max_len:
            shared.pop()
        if shared:
            self.alloc.retain(shared)
        # ISSUE 13: pull the missing chain tail through the fabric —
        # blocks a prefill replica published arrive as ONE migrate_in
        # upload into fresh local blocks (and join the LOCAL cache, so
        # the next request maps them copy-free).  Runs after the local
        # retain so an allocation-pressure eviction inside the pull can
        # never reclaim a locally-hit block out from under this plan.
        if self.fabric is not None:
            self._migrate_in_locked(req, keys, shared, p_len)
        total_blocks = max(self._commit_blocks(p_len, req.budget),
                           len(shared))
        need = total_blocks - len(shared)
        new_ids = self._alloc_blocks_locked(
            need, max_victim_rank=_TIER_RANK[req.tier] - 1,
        )
        if new_ids is None:
            if shared:
                self.alloc.release(shared)
            return None
        row = np.full((self.max_blocks,), SCRATCH_BLOCK, np.int32)
        row[: len(shared)] = shared
        row[len(shared) : total_blocks] = new_ids
        draft_new: List[int] = []
        drow = None
        if self._spec_req(req):
            # the draft-cache twin: same commit formula, all fresh —
            # the draft never prefix-shares (its KV depends on the
            # draft weights, not the prompt alone being cached)
            dneed = self._commit_blocks(p_len, req.budget)
            draft_new = self._alloc_blocks_locked(
                dneed, max_victim_rank=_TIER_RANK[req.tier] - 1,
            )
            if draft_new is None:
                rollback = shared + list(new_ids)
                if rollback:
                    self.alloc.release(rollback)
                return None
            drow = np.full((self.max_blocks,), SCRATCH_BLOCK, np.int32)
            drow[:dneed] = draft_new
        return {
            "shared": shared, "new": new_ids, "keys": keys, "row": row,
            "L": len(shared) * bs, "draft_new": draft_new, "drow": drow,
        }

    def _release_plan(self, plan) -> None:
        refs = (plan.get("shared", []) + plan.get("new", [])
                + plan.get("extra", []) + plan.get("draft_new", [])
                + plan.get("draft_extra", []))
        if refs:
            self.alloc.release(refs)

    # -- SLO-tier scheduling + preemption (ISSUE 12) -----------------------

    def _effective_rank(self, req: _Request, now: float) -> int:
        """Admission-order rank: the request's tier, with the bounded
        anti-starvation boost — a batch request queued longer than
        ``age_boost_seconds`` ORDERS like an interactive one (the
        boost never grants preemption rights; victims are judged by
        their real tier)."""

        rank = _TIER_RANK[req.tier]
        if rank == 0 and now - req.t_submit_mono >= self.age_boost_seconds:
            rank = _TIER_RANK["interactive"]
        return rank

    def _queue_sort_key(self, req: _Request, now: float):
        """Priority admission replacing blind FIFO: highest effective
        rank first, FIFO (submit order) within a rank."""

        return (-self._effective_rank(req, now), req.t_submit_mono, req.rid)

    def _pick_queued_locked(self) -> int:
        now = time.monotonic()
        return min(
            range(len(self._queue)),
            key=lambda i: self._queue_sort_key(self._queue[i], now),
        )

    def _pick_victim_locked(self, max_rank: int,
                            exclude_slot: Optional[int] = None):
        """The preemption policy: among active seats of tier rank <=
        ``max_rank`` that (a) have produced at least one window since
        seating (the anti-livelock progress guard — a freshly resumed
        seat cannot be re-victimized before it decodes anything) and
        (b) whose preemption would actually FREE blocks the swap arena
        can absorb, pick lowest tier, then most blocks, then least
        progress.  Returns a slot or None."""

        cands = []
        for slot, r in self._active.items():
            if slot == exclude_slot or _TIER_RANK[r.tier] > max_rank \
                    or r.tokens_since_seat <= 0:
                continue
            refs = self._seat_refs.get(slot, [])
            private = sum(1 for b in refs if self.alloc.refcount(b) == 1)
            dprivate = len(self._draft_refs.get(slot, []))
            if private == 0 or not self.swap.admit(private + dprivate):
                continue
            cands.append((slot, r, len(refs) + dprivate))
        if not cands:
            return None
        return min(
            cands,
            key=lambda c: (_TIER_RANK[c[1].tier], -c[2],
                           len(c[1].tokens), c[0]),
        )[0]

    def _alloc_blocks_locked(self, n: int, *, max_victim_rank: int,
                             exclude_slot: Optional[int] = None,
                             exclude_rid: Optional[int] = None):
        """``n`` fresh blocks under pressure: plain allocation, then
        LRU eviction of cold prefix-cache entries, then preemption of
        eligible victims (tier rank <= ``max_victim_rank``), then
        demotion of queued swap-record holders (below) — each round
        moves real block claims, so the loop terminates.  None when
        the arena is exhausted with nothing evictable, preemptable, or
        demotable for this caller's tier."""

        ids = self.alloc.alloc(n)
        while ids is None:
            if self.prefix.evict_lru(need=n - self.alloc.free_count) == 0:
                victim = self._pick_victim_locked(
                    max_victim_rank, exclude_slot
                )
                if victim is not None:
                    self._preempt_seat_locked(victim, reason="pressure")
                elif not self._demote_queued_locked(
                    max_victim_rank, exclude_rid
                ):
                    return None
            ids = self.alloc.alloc(n)
        return ids

    def _demote_queued_locked(self, max_rank: int,
                              exclude_rid: Optional[int]) -> bool:
        """Deadlock breaker for the swap-exempt pin (review finding):
        a preempted QUEUED request keeps device refs on its
        prefix-shared blocks (swap-exempt at eviction time), and the
        prefix cache cannot evict a block whose refcount is above 1 —
        so a pool with no active seats could wedge with every free
        block claimed by queued holders.  When neither eviction nor
        seat preemption can free anything, demote the lowest-priority
        queued holder: copy its live blocks into its swap record and
        release the refs — cache-only blocks drop to refcount 1 and
        become LRU-evictable on the caller's next round.  Returns
        True when a demotion happened (the alloc loop retries)."""

        now = time.monotonic()
        cands = []
        for q in self._queue:
            if not q.swapped or q.rid == exclude_rid \
                    or _TIER_RANK[q.tier] > max_rank:
                continue
            rec = self.swap.peek(q.rid)
            if rec is None or not (rec["live"]
                                   or rec.get("draft_live")):
                continue
            if not self.swap.admit(
                len(rec["live"]) + len(rec.get("draft_live", []))
            ):
                continue
            cands.append(q)
        if not cands:
            return False
        q = max(cands, key=lambda r: self._queue_sort_key(r, now))
        rec = self.swap.peek(q.rid)
        live = rec["live"]
        dlive = rec.get("draft_live", [])
        host2 = None
        dhost2 = None
        nbytes = 0
        with self._request_span(q, "swap_out",
                                blocks=len(live) + len(dlive),
                                reason="demote"):
            with self.dispatch("swap_out", rid=q.rid,
                               blocks=len(live) + len(dlive)):
                if live:
                    nc = _pow2_class(len(live))
                    ids_pad = np.full((nc,), SCRATCH_BLOCK, np.int32)
                    ids_pad[: len(live)] = [b for _, b in live]
                    fetched = jax.device_get(
                        self._swap_gather(nc)(self._arena, ids_pad)
                    )
                    host2 = jax.tree_util.tree_map(
                        lambda l: l[: len(live)]
                        if getattr(l, "ndim", 0) == 4 else l,
                        fetched,
                    )
                if dlive:
                    # speculating seats park their draft blocks live
                    # too — demotion must copy them out or the queued
                    # holder's draft refs wedge the arena just like
                    # its target refs would (the same deadlock breaker)
                    ncd = _pow2_class(len(dlive))
                    idsd = np.full((ncd,), SCRATCH_BLOCK, np.int32)
                    idsd[: len(dlive)] = dlive
                    fetched_d = jax.device_get(
                        self._swap_gather(ncd)(self._draft_arena, idsd)
                    )
                    dhost2 = jax.tree_util.tree_map(
                        lambda l: l[: len(dlive)]
                        if getattr(l, "ndim", 0) == 4 else l,
                        fetched_d,
                    )

        def _merge(old, new):
            if new is None:
                return old
            if old is None:
                return new
            return jax.tree_util.tree_map(
                lambda a, b: np.concatenate([a, b])
                if getattr(a, "ndim", 0) == 4 else a,
                old, new,
            )

        for tree in (host2, dhost2):
            if tree is not None:
                nbytes += sum(
                    l.nbytes for l in jax.tree_util.tree_leaves(tree)
                    if getattr(l, "ndim", 0) == 4
                )
        merged = {
            "live": [],
            "blocks": rec["blocks"] + [i for i, _ in live],
            "host": _merge(rec["host"], host2),
            "rng": rec["rng"],
            "draft_live": [],
            "draft_n": rec.get("draft_n", 0) + len(dlive),
            "draft_host": _merge(rec.get("draft_host"), dhost2),
            "draft_rng": rec.get("draft_rng"),
        }
        old_n = rec["n_blocks"]
        self.swap.pop(q.rid)
        self.swap.put(q.rid, merged,
                      n_blocks=old_n + len(live) + len(dlive),
                      nbytes=nbytes)
        if live:
            self.alloc.release([b for _, b in live])
        if dlive:
            self.alloc.release(list(dlive))
        self._count_swap_bytes("out", nbytes)
        if q.entry is not None:
            self.request_log.add_swap(q.entry, len(live) + len(dlive))
            self.request_log.count_dispatch(q.entry, "swap_out")
        return True

    def _count_swap_bytes(self, direction: str, nbytes: int) -> None:
        """kv_swap_bytes_total{direction} — split out of the linted
        swap paths: ``nbytes`` is host arithmetic (np buffer sizes),
        and keeping the float() cast here keeps the no-hot-sync AST
        gate's forbidden-call scan honest over the callers."""

        if self.metrics is not None and nbytes:
            self.metrics.inc(
                "kv_swap_bytes_total", float(nbytes), direction=direction
            )

    def _swap_gather(self, nc: int):
        """The jitted arena row gather — one shape-polymorphic jit;
        ``nc`` (the pow2 id-count class) only feeds compile_count,
        since each new class is one real retrace (compile count stays
        logarithmic in the largest swap)."""

        with self._compile_lock:
            if self._swap_gather_fn is None:
                self._swap_gather_fn = jax.jit(gather_blocks_by_id)
            if nc not in self._swap_gather_classes:
                self._swap_gather_classes.add(nc)
                self.compile_count += 1
                # shape-polymorphic fn, so the wrap()-on-cache-miss
                # pattern can't see retraces — each new pow2 class IS
                # one retrace; register it directly (wall unmeasured)
                self.costplane.compiles.note(
                    "paged.swap_gather", trigger=f"ids={nc}"
                )
            return self._swap_gather_fn

    def _swap_in(self, u: int):
        """The resume program: write the swapped block rows back into
        the arena and restore the seat's ENTIRE device row — table,
        length, sampling params, rng chain value, last token — in ONE
        dispatch, so a resumed request continues byte-identically to
        an undisturbed run.  One shape-polymorphic jit; ``u`` (the
        pow2 upload class) only feeds compile_count."""

        with self._compile_lock:
            if self._swap_in_fn is None:

                def swap_in(arena, tables, lengths, temps, topks, rngs,
                            toks, bufs, ids, row, L, slot, temp, top_k,
                            rng, last_tok):
                    arena = scatter_blocks_by_id(arena, bufs, ids)
                    tables = tables.at[slot].set(row)
                    lengths = lengths.at[slot].set(L)
                    temps = temps.at[slot].set(temp)
                    topks = topks.at[slot].set(top_k)
                    rngs = rngs.at[slot].set(rng)
                    toks = toks.at[slot].set(last_tok)
                    return arena, tables, lengths, temps, topks, rngs, toks

                self._swap_in_fn = jax.jit(swap_in)
            if u not in self._swap_in_classes:
                self._swap_in_classes.add(u)
                self.compile_count += 1
                self.costplane.compiles.note(
                    "paged.swap_in", trigger=f"upload={u}"
                )
            return self._swap_in_fn

    def _upload_bufs(self, host_tree, n: int, u: int, arena=None):
        """Pad the ``n`` gathered host rows to the ``u`` width class
        (np zeros; padded rows scatter into scratch).  ``arena`` picks
        the template tree (the draft arena for draft uploads)."""

        template = self._arena if arena is None else arena

        def pad(al, hl):
            if al.ndim != 4:
                return np.zeros((), al.dtype)
            out = np.zeros((u,) + tuple(al.shape[1:]), al.dtype)
            if hl is not None and n:
                out[:n] = hl[:n]
            return out

        if host_tree is None:
            return jax.tree_util.tree_map(
                lambda al: pad(al, None), template
            )
        return jax.tree_util.tree_map(pad, template, host_tree)

    # -- KV-block migration over the prefix-cache fabric (ISSUE 13) --------

    def _count_migrate_bytes(self, direction: str, nbytes: int,
                             transport: str = "local") -> None:
        """kv_migrate_bytes_total{direction,transport} — the fabric
        transport's byte meter, split out of the linted migration paths
        like its swap twin (``nbytes`` is host arithmetic over np
        buffers).  ``transport="http"`` marks bytes that crossed the
        cross-pod fleet fabric wire (ISSUE 17) rather than the
        in-process store."""

        if self.metrics is not None and nbytes:
            self.metrics.inc(
                "kv_migrate_bytes_total", float(nbytes),
                direction=direction, transport=transport,
            )

    def _migrate_scatter(self, u: int):
        """The jitted fabric upload (scatter_blocks_by_id over the
        arena) — one shape-polymorphic jit; ``u`` (the pow2 block-count
        class) only feeds compile_count, mirroring _swap_gather."""

        with self._compile_lock:
            if self._migrate_scatter_fn is None:
                self._migrate_scatter_fn = jax.jit(scatter_blocks_by_id)
            if u not in self._migrate_scatter_classes:
                self._migrate_scatter_classes.add(u)
                self.compile_count += 1
                self.costplane.compiles.note(
                    "paged.migrate_scatter", trigger=f"upload={u}"
                )
            return self._migrate_scatter_fn

    def _migrate_in_locked(self, req: _Request, keys, shared: List[int],
                           p_len: int) -> None:
        """Pull the chain's missing tail from the fabric (caller holds
        the pool lock; ``shared`` already holds the retained LOCAL
        hits and is extended in place).  Fabric records stay PINNED
        from lookup to upload (never reclaimed while a migration holds
        a ref); each pulled block is uploaded in ONE ``migrate_in``
        dispatch, published into the LOCAL prefix cache (the alloc ref
        becomes the cache's own), and retained once more for the seat
        — from here on it is indistinguishable from a local hit.
        Allocation shortfall quietly skips the pull: the remainder
        prefill recomputes those positions, which is also the failure
        semantics when a prefill replica died mid-publish."""

        bs = self.block_size
        limit = (p_len - 1) // bs
        fetch = []  # (key, fabric record), chain-consecutive
        for i in range(len(shared), limit):
            if self.prefix.peek(keys[i]) is not None:
                # the LOCAL cache still holds this link (an evicted
                # HEAD with a retained tail — chain walks refresh LRU
                # head-first, so heads age out first).  Pulling it
                # would prefix.put over the live entry and leak the
                # old block's cache reference; stop the pull here and
                # let the remainder prefill recompute from this point.
                break
            rec = self.fabric.get(keys[i], pin=True)
            if rec is None:
                break
            fetch.append((keys[i], rec))
        # the combined prefix must leave a representable padded
        # remainder — drop trailing pulls first (local hits are free,
        # pulled blocks cost an upload)
        while fetch and \
                (len(shared) + len(fetch)) * bs + self._paged_width(
                    p_len - (len(shared) + len(fetch)) * bs
                ) > self.max_len:
            key, _ = fetch.pop()
            self.fabric.unpin(key)
        if limit > len(shared):
            # request-level accounting: only consultations that could
            # have pulled something count
            self.fabric.record(bool(fetch))
        if not fetch:
            return
        ids = self._alloc_blocks_locked(
            len(fetch), max_victim_rank=_TIER_RANK[req.tier] - 1,
        )
        if ids is None:
            for key, _ in fetch:
                self.fabric.unpin(key)
            return
        n = len(fetch)
        u = _pow2_class(n)
        host = jax.tree_util.tree_map(
            lambda *leaves: np.concatenate(leaves)
            if getattr(leaves[0], "ndim", 0) == 4 else leaves[0],
            *[rec["kv"] for _, rec in fetch],
        )
        bufs = self._upload_bufs(host, n, u)
        ids_pad = np.full((u,), SCRATCH_BLOCK, np.int32)
        ids_pad[:n] = ids
        nbytes = sum(rec["nbytes"] for _, rec in fetch)
        with self._request_span(req, "migrate", blocks=n, bytes=nbytes):
            with self.dispatch("migrate_in", rid=req.rid, blocks=n):
                self._arena = self._migrate_scatter(u)(
                    self._arena, bufs, ids_pad
                )
        for (key, _), bid in zip(fetch, ids):
            self.prefix.put(key, int(bid))     # cache owns the alloc ref
            self.alloc.retain([int(bid)])      # +1 for this seat
            shared.append(int(bid))
            self.fabric.unpin(key)
        # bytes metered per transport: blocks a FleetFabric pulled over
        # the wire carry transport="http" on their records (ISSUE 17)
        pulled = [rec for _, rec in fetch if rec.get("transport") == "http"]
        nbytes_http = sum(rec["nbytes"] for rec in pulled)
        self._count_migrate_bytes("in", nbytes - nbytes_http)
        if pulled:
            self._count_migrate_bytes("in", nbytes_http, transport="http")
        if req.entry is not None:
            self.request_log.add_migrate(req.entry, n)
            self.request_log.count_dispatch(req.entry, "migrate_in")
            if pulled:
                self.request_log.update(
                    req.entry,
                    fabric_peer=pulled[0].get("peer", ""),
                    pulled_blocks=len(pulled),
                )

    def publish_to_fabric(self, prompt_ids, *, tier: str = "batch",
                          trace_id: Optional[str] = None,
                          timeout: Optional[float] = None) -> Dict[str, int]:
        """The prefill-replica half of the migration transport (ISSUE
        13): make every FULL block of ``prompt_ids`` available in the
        shared fabric.  Blocks the fabric already holds cost nothing;
        for the rest, an INTERNAL budget-1 request chunk-prefills the
        prompt through the normal fused admission (publishing the full
        blocks into the LOCAL prefix cache and retiring the seat
        immediately), and one ``migrate_out`` dispatch gathers the
        published blocks device→host into the fabric.  The throwaway
        admission sample is greedy (no rng is consumed), so the decode
        replica's own admission sample — the one the user sees — runs
        the exact split chain of an undisturbed pool: disaggregated
        serving stays token-identical.

        BLOCKS until the internal prefill completes (a driver thread
        must be stepping this pool); raises TimeoutError past
        ``timeout``.  Returns {"publishable", "published", "computed"}.
        """

        if self.fabric is None:
            raise ValueError(
                "this replica has no prefix-cache fabric to publish into"
            )
        prompt = np.array(prompt_ids, np.int32).reshape(-1)
        bs = self.block_size
        n_pub = int(prompt.size) // bs
        out = {"publishable": n_pub, "published": 0, "computed": 0}
        if n_pub == 0:
            return out
        keys = chain_keys(prompt, bs)[:n_pub]
        missing = [k for k in keys if k not in self.fabric]
        self.fabric.record(hit=not missing)
        if not missing:
            return out
        with self._lock:
            have_local = all(
                self.prefix.peek(k) is not None for k in missing
            )
        if not have_local:
            # chunk-prefill through the pool's own admission path —
            # one fused dispatch per pow2 remainder class, prefix hits
            # (local or fabric) shrinking the computed remainder
            rid = self.submit(
                prompt, 1, tier=tier, trace_id=trace_id, internal=True,
            )
            if self.result_wait(rid, timeout=timeout) is None:
                raise TimeoutError(
                    f"fabric publish prefill timed out after {timeout}s "
                    "(is this replica's driver thread running?)"
                )
            out["computed"] = 1
        with self._lock:
            publish = []
            for k in missing:
                if k in self.fabric:
                    continue  # a concurrent publisher won the race
                bid = self.prefix.peek(k)
                if bid is None:
                    # evicted between the admission and now (extreme
                    # arena pressure): publish what survives — the
                    # decode side recomputes the rest, never blocks
                    continue
                publish.append((k, int(bid)))
            if not publish:
                return out
            bids = [b for _, b in publish]
            # pinned against reclaim for the duration of the gather
            self.alloc.retain(bids)
            try:
                nc = _pow2_class(len(bids))
                ids_pad = np.full((nc,), SCRATCH_BLOCK, np.int32)
                ids_pad[: len(bids)] = bids
                with self.dispatch("migrate_out", blocks=len(bids)):
                    fetched = jax.device_get(
                        self._swap_gather(nc)(self._arena, ids_pad)
                    )
            finally:
                self.alloc.release(bids)
            nbytes_total = 0
            for j, (k, _) in enumerate(publish):
                rec_kv = jax.tree_util.tree_map(
                    lambda l, j=j: l[j : j + 1]
                    if getattr(l, "ndim", 0) == 4 else l,
                    fetched,
                )
                nb = sum(
                    l.nbytes
                    for l in jax.tree_util.tree_leaves(rec_kv)
                    if getattr(l, "ndim", 0) == 4
                )
                self.fabric.put(k, rec_kv, nb)
                nbytes_total += nb
            out["published"] = len(publish)
            self._count_migrate_bytes("out", nbytes_total)
        return out

    def _preempt_seat_locked(self, slot: int, reason: str) -> int:
        """Evict seat ``slot`` mid-decode (caller holds the pool
        lock): private blocks (allocator refcount 1 — nothing else
        holds them) are snapshotted to the host SwapArena and freed;
        prefix-cache-shared blocks are swap-EXEMPT (their refcounts
        keep them device-resident; they re-map copy-free at resume);
        the seat's device row resets before any freed block can
        re-allocate; the request re-queues carrying its swap record.
        When the swap arena cannot absorb the private blocks the
        preemption degrades to a ZERO-COPY park — nothing is freed,
        the request just leaves its seat (the grow path's last
        resort).  Returns the number of blocks actually freed."""

        req = self._active.pop(slot)
        refs = self._seat_refs.pop(slot)
        drefs = self._draft_refs.pop(slot, [])
        req.slot = None
        exempt = [(i, b) for i, b in enumerate(refs)
                  if self.alloc.refcount(b) > 1]
        private = [(i, b) for i, b in enumerate(refs)
                   if self.alloc.refcount(b) == 1]
        sampled = req.temperature > 0.0
        # draft blocks are ALL private (never prefix-shared); they swap
        # with their seat so a resumed speculating seat continues
        # token-identically without a draft re-prefill
        if (private or drefs) and \
                not self.swap.admit(len(private) + len(drefs)):
            live, copied = exempt + private, []
            dlive, dcopied = list(drefs), []
        else:
            live, copied = exempt, private
            dlive, dcopied = [], list(drefs)
        host_tree = None
        dhost_tree = None
        rng_host = None
        drng_host = None
        if copied or dcopied or sampled:
            with self._request_span(req, "swap_out", slot=slot,
                                    blocks=len(copied) + len(dcopied),
                                    reason=reason):
                with self.dispatch("swap_out", rid=req.rid,
                                   blocks=len(copied) + len(dcopied)):
                    if copied:
                        nc = _pow2_class(len(copied))
                        ids_pad = np.full((nc,), SCRATCH_BLOCK, np.int32)
                        ids_pad[: len(copied)] = [b for _, b in copied]
                        fetched = jax.device_get(
                            self._swap_gather(nc)(self._arena, ids_pad)
                        )
                        host_tree = jax.tree_util.tree_map(
                            lambda l: l[: len(copied)]
                            if getattr(l, "ndim", 0) == 4 else l,
                            fetched,
                        )
                    if dcopied:
                        ncd = _pow2_class(len(dcopied))
                        idsd = np.full((ncd,), SCRATCH_BLOCK, np.int32)
                        idsd[: len(dcopied)] = dcopied
                        fetched_d = jax.device_get(
                            self._swap_gather(ncd)(self._draft_arena, idsd)
                        )
                        dhost_tree = jax.tree_util.tree_map(
                            lambda l: l[: len(dcopied)]
                            if getattr(l, "ndim", 0) == 4 else l,
                            fetched_d,
                        )
                    if sampled:
                        rng_host = jax.device_get(self._rngs_dev[slot])
                        if drefs:
                            drng_host = jax.device_get(
                                self._draft_rngs_dev[slot]
                            )
            if req.entry is not None:
                self.request_log.count_dispatch(req.entry, "swap_out")
        nbytes = 0
        if host_tree is not None:
            nbytes = sum(
                l.nbytes for l in jax.tree_util.tree_leaves(host_tree)
                if getattr(l, "ndim", 0) == 4
            )
        if dhost_tree is not None:
            nbytes += sum(
                l.nbytes for l in jax.tree_util.tree_leaves(dhost_tree)
                if getattr(l, "ndim", 0) == 4
            )
        # the dead seat's device row resets BEFORE its freed blocks can
        # re-allocate (the retire-program rule)
        self._retire_device_locked([slot], reqs=[req])
        freed = self.alloc.release([b for _, b in copied]) if copied else 0
        if dcopied:
            freed += self.alloc.release(dcopied)
        self.swap.put(
            req.rid,
            {"live": live, "blocks": [i for i, _ in copied],
             "host": host_tree, "rng": rng_host,
             "draft_live": dlive, "draft_n": len(dcopied),
             "draft_host": dhost_tree, "draft_rng": drng_host},
            n_blocks=len(copied) + len(dcopied), nbytes=nbytes,
        )
        req.swapped = True
        req.tokens_since_seat = 0
        now = time.monotonic()
        self._emit_span(
            req, "preempt", now, now, reason=reason, tier=req.tier,
            blocks_swapped=len(copied) + len(dcopied),
            blocks_live=len(live) + len(dlive),
        )
        if req.entry is not None:
            self.request_log.count_preempt(
                req.entry, swapped_blocks=len(copied) + len(dcopied)
            )
        self.preemptions += 1
        if self.metrics is not None:
            # literal label keys (not the _labels splat): the alert/
            # autoscaling lint collectors pin {model, tier} off this
            # call site
            self.metrics.inc(
                "serve_preemptions_total", tier=req.tier,
                model=self.model_label, replica=self.replica_label or "0",
            )
        self._count_swap_bytes("out", nbytes)
        self._queue.append(req)
        return freed

    def _plan_resume_locked(self, req: _Request):
        """Block plan for re-admitting a preempted request: its
        swapped blocks' replacements PLUS first-window growth coverage
        (resuming a seat that could not run a single window would just
        park it again — the resume gate is what makes the
        swap-exhausted degraded mode converge instead of spinning).
        Interactive resumes may preempt batch seats, like fresh
        interactive admissions."""

        rec = self.swap.peek(req.rid)
        if rec is None:
            # a swapped marker without a record is an invariant
            # violation (the KV content is unrecoverable) — fail
            # LOUDLY like every allocator-contract break; silently
            # gating the whole queue on an unresumable request is the
            # worse failure mode (review finding)
            from tf_operator_tpu.models.kv_blocks import BlockError

            raise BlockError(
                f"request {req.rid} is marked swapped but has no "
                "SwapArena record — its KV cannot be restored"
            )
        n_up = len(rec["blocks"])
        n_up_d = rec.get("draft_n", 0)
        committed = len(rec["live"]) + n_up
        length = req.prompt.size + len(req.tokens) - 1
        cap = max(req.prompt.size + req.budget - 1, 1)
        spec = self._spec_req(req)
        adv = (self.spec_k + 1) if spec else self.steps_per_sync
        target = blocks_for(min(length + adv, cap), self.block_size)
        extra = max(0, target - committed)
        dextra = 0
        if spec:
            dcommitted = len(rec.get("draft_live", [])) + n_up_d
            dextra = max(0, target - dcommitted)
        ids = self._alloc_blocks_locked(
            n_up + extra + n_up_d + dextra,
            max_victim_rank=_TIER_RANK[req.tier] - 1,
            exclude_rid=req.rid,
        )
        if ids is None:
            return None
        a, b = n_up, n_up + extra
        c = b + n_up_d
        return {"rec": rec, "new": ids[:a], "extra": ids[a:b],
                "draft_new": ids[b:c], "draft_extra": ids[c:]}

    def _admit_swapped(self, req: _Request, slot: int, plan) -> None:
        """Resume a preempted request: ONE ``swap_in`` dispatch
        uploads the host-swapped blocks into the freshly allocated
        ones, re-maps the swap-exempt blocks copy-free, and restores
        the seat's full device row (length, sampling params, rng
        chain, last token) — the re-admission half of the
        token-identity contract.  Caller holds the pool lock."""

        rec, new, extra = plan["rec"], plan["new"], plan["extra"]
        committed = len(rec["live"]) + len(new)
        row = np.full((self.max_blocks,), SCRATCH_BLOCK, np.int32)
        refs: List[int] = [SCRATCH_BLOCK] * committed
        for i, bid in rec["live"]:
            row[i] = bid
            refs[i] = bid
        for j, i in enumerate(rec["blocks"]):
            row[i] = new[j]
            refs[i] = new[j]
        row[committed : committed + len(extra)] = extra
        refs.extend(extra)
        u = _pow2_class(len(new))
        ids_pad = np.full((u,), SCRATCH_BLOCK, np.int32)
        ids_pad[: len(new)] = new
        bufs = self._upload_bufs(rec["host"], len(new), u)
        length = req.prompt.size + len(req.tokens) - 1
        sampled = req.temperature > 0.0
        rng = (
            rec["rng"] if sampled and rec["rng"] is not None
            else np.zeros((2,), np.uint32)
        )
        # draft twin (speculating seats): live draft blocks re-map
        # copy-free, swapped ones upload into fresh allocations — the
        # draft cache resumes at the same shared length as the target,
        # so the next draft window continues byte-identically
        spec = self._spec_req(req)
        dnew = plan.get("draft_new", [])
        dlive = rec.get("draft_live", [])
        drefs: List[int] = []
        drow = None
        dbufs = None
        dids_pad = None
        ud = 0
        if spec:
            drow = np.full((self.max_blocks,), SCRATCH_BLOCK, np.int32)
            drefs = list(dlive) + list(dnew) + list(
                plan.get("draft_extra", [])
            )
            drow[: len(drefs)] = drefs
            ud = _pow2_class(len(dnew))
            dids_pad = np.full((ud,), SCRATCH_BLOCK, np.int32)
            dids_pad[: len(dnew)] = dnew
            dbufs = self._upload_bufs(
                rec.get("draft_host"), len(dnew), ud,
                arena=self._draft_arena,
            )
        nbytes = 0
        for tree in (rec["host"], rec.get("draft_host")):
            if tree is not None:
                nbytes += sum(
                    l.nbytes for l in jax.tree_util.tree_leaves(tree)
                    if getattr(l, "ndim", 0) == 4
                )
        with self._request_span(
            req, "swap_in", slot=slot,
            blocks_uploaded=len(new) + len(dnew),
            blocks_live=len(rec["live"]) + len(dlive),
        ):
            with self.dispatch("swap_in", rid=req.rid,
                               blocks=len(new) + len(dnew)):
                (self._arena, self._tables_dev, self._lengths_dev,
                 self._temps_dev, self._topks_dev, self._rngs_dev,
                 self._last_tok) = self._swap_in(u)(
                    self._arena, self._tables_dev, self._lengths_dev,
                    self._temps_dev, self._topks_dev, self._rngs_dev,
                    self._last_tok, bufs, ids_pad, row,
                    jnp.int32(length), jnp.int32(slot),
                    jnp.float32(req.temperature),
                    jnp.int32(req.top_k or 0), rng,
                    jnp.int32(req.tokens[-1]),
                )
                if spec:
                    self._draft_arena = self._migrate_scatter(ud)(
                        self._draft_arena, dbufs, dids_pad
                    )
                    self._draft_tables_dev = \
                        self._draft_tables_dev.at[slot].set(drow)
                    drng = rec.get("draft_rng")
                    if sampled and drng is not None:
                        self._draft_rngs_dev = \
                            self._draft_rngs_dev.at[slot].set(drng)
        self.swap.pop(req.rid, nbytes)
        req.swapped = False
        req.slot = slot
        req.tokens_since_seat = 0
        self._active[slot] = req
        self._seat_refs[slot] = refs
        if spec:
            self._draft_refs[slot] = drefs
        self._count_swap_bytes("in", nbytes)
        if req.entry is not None:
            self.request_log.count_dispatch(req.entry, "swap_in")
            self.request_log.update(req.entry, state="active", slot=slot)

    def _admit(self) -> None:
        """Seat queued requests while both a seat AND their block plan
        are satisfiable, in PRIORITY order (interactive first, aged
        batch boosted, FIFO within a rank) — ISSUE 12 replaces the
        blind FIFO.  The top-priority request gates the queue when its
        plan fails (fairness over packing — a lower tier never skips
        ahead); interactive plans may preempt batch seats to fit."""

        while True:
            with self._lock:
                if not self._queue:
                    return
                if all(s in self._active for s in range(self.slots)):
                    return
                idx = self._pick_queued_locked()
                req = self._queue[idx]
                if req.swapped:
                    plan = self._plan_resume_locked(req)
                else:
                    plan = self._plan_admission(req)
                if plan is None:
                    self._update_gauges_locked()
                    return
                self._queue.pop(idx)
                # planning may itself have preempted seats: recompute
                free = [
                    s for s in range(self.slots) if s not in self._active
                ]
                slot = free[0]
                try:
                    if req.swapped:
                        self._admit_swapped(req, slot, plan)
                    else:
                        self._admit_paged(req, slot, plan)
                    self._update_gauges_locked()
                except BaseException:
                    # transient device failure: the request must
                    # survive — reservation rolled back, head-of-queue
                    # reinsertion, waiters never hang (the base pool's
                    # survival rule)
                    self._release_plan(plan)
                    self._queue.insert(0, req)
                    raise

    def _admit_paged(self, req: _Request, slot: int, plan) -> None:
        """One fused dispatch: gather the shared prefix view, prefill
        the padded remainder at offset L, rollback pad rows, sample
        the first token, scatter the new blocks into the arena — and
        write the seat's DEVICE-RESIDENT table row, length, sampling
        params and rng key in the same program (the once-per-request
        table delta; steady-state steps then reuse the on-device
        state, ISSUE 10 satellite).  Caller holds the pool lock (the
        program rewrites the shared arena, so it serializes with
        step() like the contiguous fused admission)."""

        bs = self.block_size
        p_len = req.prompt.size
        prefix_len = plan["L"]
        remainder = p_len - prefix_len
        width = self._paged_width(remainder)
        # CEIL division: when block_size does not divide the pow2
        # width class, the prefill writes straddle a partial block —
        # floor would silently drop it from the scatter (and publish
        # the never-written block), corrupting decode
        nbw = blocks_for(width, bs)
        ids = np.zeros((1, width), np.int32)
        ids[0, :remainder] = req.prompt[prefix_len:]
        row_pad = np.concatenate(
            [plan["row"], np.full((nbw,), SCRATCH_BLOCK, np.int32)]
        )
        sampled = req.temperature > 0.0
        rng = req.rng if sampled else jnp.zeros((2,), jnp.uint32)
        blocks_reserved = len(plan["shared"]) + len(plan["new"])
        work_start = time.perf_counter()
        self._emit_queue_wait(req)
        with self._request_span(
            req, "admission", width=width, slot=slot,
            blocks_reserved=blocks_reserved,
            prefix_hit_tokens=prefix_len,
            prefix_hit_blocks=len(plan["shared"]),
        ):
            with self.dispatch(
                "admission", rid=req.rid, width=width,
                prefix_tokens=prefix_len,
            ):
                (arena, toks, tables_dev, lengths_dev, temps_dev,
                 topks_dev, rngs_dev, tok, rng_next) = self._admission(
                    width
                )(
                    self.params, self._arena, self._last_tok,
                    self._tables_dev, self._lengths_dev, self._temps_dev,
                    self._topks_dev, self._rngs_dev,
                    jnp.asarray(row_pad), jnp.asarray(ids),
                    jnp.int32(prefix_len), jnp.int32(remainder),
                    jnp.int32(slot), jnp.float32(req.temperature),
                    jnp.int32(req.top_k or 0), rng,
                )
                tok_h = int(tok)  # host fetch: the ledger RTT includes it
        self._arena, self._last_tok = arena, toks
        self._tables_dev, self._lengths_dev = tables_dev, lengths_dev
        self._temps_dev, self._topks_dev = temps_dev, topks_dev
        self._rngs_dev = rngs_dev
        if sampled:
            req.rng = rng_next
        req.tokens.append(tok_h)
        self.prefix.record(prefix_len > 0)
        # publish every FULL prompt block (final content — decode
        # writes start at p_len, never inside them) under its chain
        # key; the cache takes its own reference per entry
        for i in range(p_len // bs):
            key = plan["keys"][i]
            if key not in self.prefix:
                bid = int(plan["row"][i])
                self.alloc.retain([bid])
                self.prefix.put(key, bid)
        if req.entry is not None:
            self.request_log.count_dispatch(req.entry, "admission")
            self.request_log.update(
                req.entry, state="active", slot=slot,
                admission={
                    "width": int(width),
                    "blocks_reserved": blocks_reserved,
                    "prefix_hit_tokens": int(prefix_len),
                    "prefix_hit_blocks": len(plan["shared"]),
                    "prefill_dispatches": 0,
                    "seconds": round(time.perf_counter() - work_start, 6),
                },
            )
        self._observe_first_token(req, work_start)
        refs = plan["shared"] + plan["new"]
        if len(req.tokens) >= req.budget:
            # budget-1: the admission token completed it — blocks go
            # straight back (published ones live on via the cache ref)
            # and the seat's freshly written device row must be
            # retired NOW: the freed blocks can re-allocate to another
            # seat, and a stale table row would let this never-seated
            # slot's step writes corrupt the new owner.  The draft
            # prefill never ran (nothing left to speculate on), so its
            # planned blocks go straight back too.
            req.done = True
            freed = self.alloc.release(refs)
            if plan.get("draft_new"):
                freed += self.alloc.release(plan["draft_new"])
            self._retire_device_locked([slot], reqs=[req])
            self._finish_request(req, blocks_freed=freed)
            self._done_cond.notify_all()
        else:
            if self._spec_req(req):
                # the draft-cache twin prefills the FULL prompt (no
                # prefix reuse — draft KV depends on the draft
                # weights) in its own ``draft``-phase dispatch; on
                # failure _admit rolls the whole plan back
                self._draft_prefill_seat(req, slot, plan)
            req.slot = slot
            req.tokens_since_seat = 0
            self._active[slot] = req
            self._seat_refs[slot] = refs
            if self._spec_req(req):
                self._draft_refs[slot] = list(plan["draft_new"])

    def _admission(self, width: int):
        with self._compile_lock:
            if width not in self._admit_fns:
                dmodel = self.dmodel
                materialize = self._materialize
                bs = self.block_size
                mb = self.max_blocks
                nbw = blocks_for(width, bs)  # ceil: cover straddle

                def admit(params, arena, toks, tables_dev, lengths_dev,
                          temps_dev, topks_dev, rngs_dev, row_pad, ids,
                          L, n, slot, temp, top_k, rng):
                    view = gather_block_view(arena, row_pad[:mb], L, bs)
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": view},
                        ids,
                        mutable=["cache"],
                    )
                    # pad rows rolled back exactly like the contiguous
                    # fused admission; garbage at gathered positions
                    # >= cache_index was masked throughout
                    cache2 = set_cache_index(vars_["cache"], L + n)
                    last = lax.dynamic_index_in_dim(
                        logits[0], n - 1, axis=0, keepdims=False
                    )
                    tok, rng_next = _admission_sample(last, temp, top_k, rng)
                    arena = scatter_block_view(
                        arena, cache2, row_pad, L // bs, nbw, bs
                    )
                    # the once-per-request device-state delta: table
                    # row + length + sampling params + rng key — the
                    # step program reuses these without any upload
                    tables_dev = tables_dev.at[slot].set(row_pad[:mb])
                    lengths_dev = lengths_dev.at[slot].set(L + n)
                    temps_dev = temps_dev.at[slot].set(temp)
                    topks_dev = topks_dev.at[slot].set(top_k)
                    rngs_dev = rngs_dev.at[slot].set(rng_next)
                    return (arena, toks.at[slot].set(tok), tables_dev,
                            lengths_dev, temps_dev, topks_dev, rngs_dev,
                            tok, rng_next)

                self._admit_fns[width] = self.costplane.compiles.wrap(
                    jax.jit(admit), "paged.admit",
                    trigger=f"width={width}",
                )
                self.compile_count += 1
            return self._admit_fns[width]

    def _draft_prefill_seat(self, req: _Request, slot: int, plan) -> None:
        """Prefill the draft-cache twin for a speculating seat: ONE
        ``draft``-phase dispatch runs the FULL prompt through the
        draft model at offset 0 into the plan's fresh draft blocks and
        writes the seat's draft table row + draft rng in the same
        program.  The draft never prefix-shares (its KV depends on
        the draft weights), so even a full-prefix-hit admission pays
        one draft prefill — charged to the ``draft`` ledger phase
        where dispatches-per-token accounting can see it.  The draft
        rng chain is fold_in(request rng, 1): deterministic, and
        independent of the target chain the token-identity contract
        pins.  Caller holds the pool lock."""

        p_len = req.prompt.size
        width = self._paged_width(p_len)
        nbw = blocks_for(width, self.block_size)
        ids = np.zeros((1, width), np.int32)
        ids[0, :p_len] = req.prompt
        drow_pad = np.concatenate(
            [plan["drow"], np.full((nbw,), SCRATCH_BLOCK, np.int32)]
        )
        sampled = req.temperature > 0.0
        rng = req.rng if sampled else jnp.zeros((2,), jnp.uint32)
        with self._request_span(req, "draft", width=width, slot=slot,
                                blocks=len(plan["draft_new"])):
            with self.dispatch("draft", rid=req.rid, width=width):
                (self._draft_arena, self._draft_tables_dev,
                 self._draft_rngs_dev) = self._draft_admission(width)(
                    self._draft_params, self._draft_arena,
                    self._draft_tables_dev, self._draft_rngs_dev,
                    jnp.asarray(drow_pad), jnp.asarray(ids),
                    jnp.int32(p_len), jnp.int32(slot), rng,
                )
        if req.entry is not None:
            self.request_log.count_dispatch(req.entry, "draft")

    def _draft_admission(self, width: int):
        with self._compile_lock:
            if width not in self._draft_admit_fns:
                dmodel = self._draft_dmodel
                materialize = self._draft_materialize
                bs = self.block_size
                mb = self.max_blocks
                nbw = blocks_for(width, bs)  # ceil: cover straddle

                def dadmit(params, darena, dtables, drngs, row_pad, ids,
                           n, slot, rng):
                    view = gather_block_view(
                        darena, row_pad[:mb], jnp.int32(0), bs
                    )
                    _, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": view},
                        ids,
                        mutable=["cache"],
                    )
                    cache2 = set_cache_index(vars_["cache"], n)
                    darena = scatter_block_view(
                        darena, cache2, row_pad, jnp.int32(0), nbw, bs
                    )
                    dtables = dtables.at[slot].set(row_pad[:mb])
                    drngs = drngs.at[slot].set(
                        jax.random.fold_in(rng, 1)
                    )
                    return darena, dtables, drngs

                self._draft_admit_fns[width] = self.costplane.compiles.wrap(
                    jax.jit(dadmit), "paged.draft_admit",
                    trigger=f"width={width}",
                )
                self.compile_count += 1
            return self._draft_admit_fns[width]

    def _retire(self):
        """One compiled reset of retired seats' device state: table
        rows back to scratch, lengths/temps/top_ks to zero.  Required
        for correctness, not hygiene — a retired seat's freed blocks
        can re-allocate immediately, and the (still computing) dead
        seat's in-place step appends would corrupt the new owner if
        its device table row survived retirement."""

        with self._compile_lock:
            if self._retire_fn is None:

                def retire(tables, lengths, temps, topks, mask):
                    tables = jnp.where(
                        mask[:, None], jnp.int32(SCRATCH_BLOCK), tables
                    )
                    lengths = jnp.where(mask, 0, lengths)
                    temps = jnp.where(mask, 0.0, temps)
                    topks = jnp.where(mask, 0, topks)
                    return tables, lengths, temps, topks

                self._retire_fn = self.costplane.compiles.wrap(
                    jax.jit(retire), "paged.retire", trigger="singleton"
                )
                self.compile_count += 1
            return self._retire_fn

    def _retire_device_locked(self, slots, reqs=()) -> None:
        """Reset the device-resident rows of ``slots`` (one dispatch
        for the whole batch, ledger phase ``retire`` — admission-class
        work, never on the steady-state step path).  ``reqs`` are the
        retiring requests: each counts its share of the batched
        dispatch in its autopsy entry."""

        mask = np.zeros((self.slots,), bool)
        mask[list(slots)] = True
        with self.dispatch("retire", slots=len(slots)):
            (self._tables_dev, self._lengths_dev, self._temps_dev,
             self._topks_dev) = self._retire()(
                self._tables_dev, self._lengths_dev, self._temps_dev,
                self._topks_dev, mask,
            )
            if self.spec_enabled:
                # the draft table row resets with its seat for the
                # same reason the target row does: freed draft blocks
                # can re-allocate immediately
                self._draft_tables_dev = jnp.where(
                    jnp.asarray(mask)[:, None],
                    jnp.int32(SCRATCH_BLOCK), self._draft_tables_dev,
                )
        for req in reqs:
            if req.entry is not None:
                self.request_log.count_dispatch(req.entry, "retire")

    # -- decode step -------------------------------------------------------

    def _step(self):
        """The steady-state decode window as ONE compiled program over
        device-resident state ONLY (params, arena, tables, lengths,
        sampling params, rng keys, last tokens) — zero per-step
        uploads on either path.  The per-window rng split the
        contiguous pool performs host-side happens in-graph here (same
        split chain, token-identical).

        Fused path (``paged_kernel`` resolved a kernel impl): the
        K-step scan runs the PAGED decode branch (transformer.py) —
        each step appends the new K/V in place to its seat's block and
        attends straight off the arena through
        ops/paged_attention; no contiguous view, no scatter-back.

        Emulation path: PR 8's gather → the shared
        ``_make_step_body`` scan → window scatter-back, with the
        table pad built and the lengths advanced in-graph.

        ISSUE 12 budget-on-demand: both paths take the window's
        lazily allocated table DELTA (``grow_logical``/``grow_phys``,
        [slots, G]) and write it into the device-resident tables
        in-graph BEFORE decoding — growth rides the one step dispatch
        instead of adding an upload dispatch (no-op rows index past
        the table and drop).  The updated tables return so the host's
        device handle stays authoritative."""

        if self._step_fn is None:
            n_inner = self.steps_per_sync
            bs = self.block_size
            nbw = self._step_nbw
            n_slots = self.slots
            if self._kernel_impl is not None:
                pmodel = self._pmodel
                materialize = self._materialize

                def step(params, arena, tables, lengths, temps, top_ks,
                         rngs, toks, enabled, grow_logical, grow_phys):
                    rows = jnp.arange(n_slots)[:, None]
                    tables = tables.at[rows, grow_logical].set(
                        grow_phys, mode="drop"
                    )
                    split = jax.vmap(jax.random.split)(rngs)
                    # disabled seats (speculating — their window runs
                    # in the draft/verify programs instead) keep their
                    # whole row: rng chain frozen, appends routed to
                    # scratch, length/last-token passed through.  An
                    # all-True mask reproduces the plain step exactly.
                    rngs_next = jnp.where(
                        enabled[:, None], split[:, 0], rngs
                    )
                    keys = split[:, 1]
                    tables_eff = jnp.where(
                        enabled[:, None], tables,
                        jnp.int32(SCRATCH_BLOCK),
                    )
                    cache0 = paged_cache_tree(arena, tables_eff, lengths)

                    def body(carry, _):
                        cache, tok, ks = carry
                        logits, vars_ = pmodel.apply(
                            {"params": materialize(params), "cache": cache},
                            tok[:, None],
                            mutable=["cache"],
                        )
                        nxt, ks2 = _step_sample(
                            logits[:, 0], temps, top_ks, ks
                        )
                        return (vars_["cache"], nxt, ks2), nxt

                    (cache, toks2, _), toks_k = lax.scan(
                        body, (cache0, toks, keys), None, length=n_inner
                    )
                    arena2, lengths_adv = split_paged_cache(cache)
                    lengths2 = jnp.where(enabled, lengths_adv, lengths)
                    toks_out = jnp.where(enabled, toks2, toks)
                    return (arena2, tables, lengths2, rngs_next,
                            toks_out, toks_k)
            else:
                make_body = self._make_step_body

                def step(params, arena, tables, lengths, temps, top_ks,
                         rngs, toks, enabled, grow_logical, grow_phys):
                    rows = jnp.arange(n_slots)[:, None]
                    tables = tables.at[rows, grow_logical].set(
                        grow_phys, mode="drop"
                    )
                    split = jax.vmap(jax.random.split)(rngs)
                    rngs_next = jnp.where(
                        enabled[:, None], split[:, 0], rngs
                    )
                    keys = split[:, 1]
                    tables_eff = jnp.where(
                        enabled[:, None], tables,
                        jnp.int32(SCRATCH_BLOCK),
                    )
                    tables_pad = jnp.concatenate(
                        [
                            tables_eff,
                            jnp.full((n_slots, nbw), SCRATCH_BLOCK,
                                     jnp.int32),
                        ],
                        axis=1,
                    )
                    stack = gather_block_stack(
                        arena, tables_eff, lengths, bs
                    )
                    body = make_body(params, temps, top_ks)
                    (stack, toks2, _), toks_k = lax.scan(
                        body, (stack, toks, keys), None, length=n_inner
                    )
                    arena2 = scatter_block_stack(
                        arena, stack, tables_pad, lengths // bs, nbw, bs
                    )
                    lengths2 = jnp.where(
                        enabled, lengths + n_inner, lengths
                    )
                    toks_out = jnp.where(enabled, toks2, toks)
                    return (arena2, tables, lengths2, rngs_next,
                            toks_out, toks_k)

            self._step_fn = self.costplane.compiles.wrap(
                jax.jit(step), "paged.step",
                trigger=f"K={self.steps_per_sync}",
            )
            self.compile_count += 1
        return self._step_fn

    def _spec_draft(self):
        """The speculative window's DRAFT half as ONE compiled program
        (ledger phase ``draft``): a (spec_k + 1)-step scan of the
        draft model over the shared device lengths — iteration 0 feeds
        the seat's last accepted token, iteration t feeds draft t, so
        the draft cache appends KV for exactly the K + 1 positions the
        verify program appends to the target cache (the shared-length
        invariant: one ``_lengths_dev`` serves both arenas).  Each
        iteration samples through the SAME temperature/top-k transform
        as the plain sampler and keeps the post-transform distribution
        q — the denominator of verify's rejection test.  The last
        iteration's token is discarded (its KV append is what matters).
        Proposed tokens and q stay ON DEVICE: they flow straight into
        the verify dispatch with no host round trip.  Non-speculating
        seats are masked: appends scratch-route, their draft rng rows
        freeze."""

        with self._compile_lock:
            if self._spec_draft_fn is None:
                k1 = self.spec_k + 1
                bs = self.block_size
                nbw = self._step_nbw
                n_slots = self.slots
                materialize = self._draft_materialize
                if self._kernel_impl is not None:
                    pmodel = self._draft_pmodel

                    def draft(params, darena, dtables, lengths, temps,
                              top_ks, drngs, toks, spec, grow_logical,
                              grow_phys):
                        rows = jnp.arange(n_slots)[:, None]
                        dtables = dtables.at[rows, grow_logical].set(
                            grow_phys, mode="drop"
                        )
                        split = jax.vmap(jax.random.split)(drngs)
                        drngs_next = jnp.where(
                            spec[:, None], split[:, 0], drngs
                        )
                        keys = split[:, 1]
                        tables_eff = jnp.where(
                            spec[:, None], dtables,
                            jnp.int32(SCRATCH_BLOCK),
                        )
                        cache0 = paged_cache_tree(
                            darena, tables_eff, lengths
                        )

                        def body(carry, _):
                            cache, tok, ks = carry
                            logits, vars_ = pmodel.apply(
                                {"params": materialize(params),
                                 "cache": cache},
                                tok[:, None],
                                mutable=["cache"],
                            )
                            nxt, ks2, dist = _spec_sample_with_dist(
                                logits[:, 0], temps, top_ks, ks
                            )
                            return (vars_["cache"], nxt, ks2), (nxt, dist)

                        (cache, _, _), (d_toks, d_dists) = lax.scan(
                            body, (cache0, toks, keys), None, length=k1
                        )
                        darena2, _ = split_paged_cache(cache)
                        return (darena2, dtables, drngs_next, d_toks,
                                d_dists)
                else:
                    dmodel = self._draft_dmodel

                    def one_slot(p, cache, tok):
                        logits, vars_ = dmodel.apply(
                            {"params": p, "cache": cache},
                            tok[None, None],
                            mutable=["cache"],
                        )
                        return vars_["cache"], logits[0, 0]

                    def draft(params, darena, dtables, lengths, temps,
                              top_ks, drngs, toks, spec, grow_logical,
                              grow_phys):
                        rows = jnp.arange(n_slots)[:, None]
                        dtables = dtables.at[rows, grow_logical].set(
                            grow_phys, mode="drop"
                        )
                        split = jax.vmap(jax.random.split)(drngs)
                        drngs_next = jnp.where(
                            spec[:, None], split[:, 0], drngs
                        )
                        keys = split[:, 1]
                        tables_eff = jnp.where(
                            spec[:, None], dtables,
                            jnp.int32(SCRATCH_BLOCK),
                        )
                        tables_pad = jnp.concatenate(
                            [
                                tables_eff,
                                jnp.full((n_slots, nbw), SCRATCH_BLOCK,
                                         jnp.int32),
                            ],
                            axis=1,
                        )
                        stack0 = gather_block_stack(
                            darena, tables_eff, lengths, bs
                        )
                        p = materialize(params)

                        def body(carry, _):
                            stack, tok, ks = carry
                            stk, logits = jax.vmap(
                                one_slot, in_axes=(None, 0, 0)
                            )(p, stack, tok)
                            nxt, ks2, dist = _spec_sample_with_dist(
                                logits, temps, top_ks, ks
                            )
                            return (stk, nxt, ks2), (nxt, dist)

                        (stack, _, _), (d_toks, d_dists) = lax.scan(
                            body, (stack0, toks, keys), None, length=k1
                        )
                        darena2 = scatter_block_stack(
                            darena, stack, tables_pad, lengths // bs,
                            nbw, bs,
                        )
                        return (darena2, dtables, drngs_next, d_toks,
                                d_dists)

                self._spec_draft_fn = self.costplane.compiles.wrap(
                    jax.jit(draft), "paged.spec_draft",
                    trigger=f"k={self.spec_k}",
                )
                self.compile_count += 1
            return self._spec_draft_fn

    def _spec_verify(self):
        """The speculative window's VERIFY half as ONE compiled program
        (ledger phase ``verify``): all K + 1 tokens — the seat's last
        accepted token plus the K proposals — run through the target
        model in a single multi-query dispatch (s_new = K + 1; the
        paged branch appends all K + 1 KV entries and attends through
        ops/paged_attention.paged_attention_multi's causal band on the
        kernel path).  Acceptance AND rollback happen in-graph:

        * greedy seats accept draft t+1 while it matches the target
          argmax at row t;
        * sampled seats run rejection sampling — accept while
          u * q(tok) <= p(tok) with p/q the EXACT post-temperature/
          top-k distributions of the plain sampler — and draw the
          boundary correction from the normalized residual
          clip(p - q, 0) (plain p on full acceptance), the classic
          unbiased speculative-sampling estimator;
        * lengths rewind to L + accepted + 1 via the same in-graph
          length write the step program uses — the rejected appends
          past the rewound length are dead by the length-mask
          convention (and overwritten by the next window's appends;
          past-table overshoot scratch-routes through block 0).

        Steady state is therefore exactly 1 draft + 1 verify dispatch
        per window.  Returns the accepted window tokens [slots, K+1]
        and per-seat counts for host distribution."""

        with self._compile_lock:
            if self._spec_verify_fn is None:
                K = self.spec_k
                bs = self.block_size
                nbw = self._step_nbw
                n_slots = self.slots
                materialize = self._materialize
                kernel = self._kernel_impl is not None
                pmodel = self._pmodel
                dmodel = self.dmodel

                def verify(params, arena, tables, lengths, temps,
                           top_ks, rngs, toks, spec, d_toks, d_dists,
                           grow_logical, grow_phys):
                    rows = jnp.arange(n_slots)[:, None]
                    tables = tables.at[rows, grow_logical].set(
                        grow_phys, mode="drop"
                    )
                    drafts = jnp.transpose(d_toks[:K])          # [S, K]
                    q = jnp.transpose(d_dists[:K], (1, 0, 2))   # [S,K,V]
                    split = jax.vmap(jax.random.split)(rngs)
                    rngs_next = jnp.where(
                        spec[:, None], split[:, 0], rngs
                    )
                    sub = jax.vmap(
                        lambda k: jax.random.split(k, 2)
                    )(split[:, 1])
                    k_u, k_corr = sub[:, 0], sub[:, 1]
                    tables_eff = jnp.where(
                        spec[:, None], tables, jnp.int32(SCRATCH_BLOCK)
                    )
                    fed = jnp.concatenate(
                        [toks[:, None], drafts], axis=1
                    )  # [S, K+1]: x0, d1..dK
                    if kernel:
                        cache0 = paged_cache_tree(
                            arena, tables_eff, lengths
                        )
                        logits, vars_ = pmodel.apply(
                            {"params": materialize(params),
                             "cache": cache0},
                            fed,
                            mutable=["cache"],
                        )  # [S, K+1, V]
                        arena2, _ = split_paged_cache(vars_["cache"])
                    else:
                        tables_pad = jnp.concatenate(
                            [
                                tables_eff,
                                jnp.full((n_slots, nbw), SCRATCH_BLOCK,
                                         jnp.int32),
                            ],
                            axis=1,
                        )
                        stack0 = gather_block_stack(
                            arena, tables_eff, lengths, bs
                        )

                        def one_slot(p, cache, fed_row):
                            lg, vars_ = dmodel.apply(
                                {"params": p, "cache": cache},
                                fed_row[None, :],
                                mutable=["cache"],
                            )
                            return vars_["cache"], lg[0]

                        stack, logits = jax.vmap(
                            one_slot, in_axes=(None, 0, 0)
                        )(materialize(params), stack0, fed)
                        arena2 = scatter_block_stack(
                            arena, stack, tables_pad, lengths // bs,
                            nbw, bs,
                        )
                    g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    greedy_ok = drafts == g[:, :K]
                    p_dist = jax.nn.softmax(
                        _masked_scaled(
                            logits.reshape(-1, logits.shape[-1]),
                            jnp.repeat(temps, K + 1),
                            jnp.repeat(top_ks, K + 1),
                        ),
                        axis=-1,
                    ).reshape(logits.shape)  # [S, K+1, V]
                    p_tok = jnp.take_along_axis(
                        p_dist[:, :K], drafts[..., None], axis=-1
                    )[..., 0]
                    q_tok = jnp.take_along_axis(
                        q, drafts[..., None], axis=-1
                    )[..., 0]
                    u = jax.vmap(
                        lambda k: jax.random.uniform(k, (K,))
                    )(k_u)
                    samp_ok = u * q_tok <= p_tok
                    ok = jnp.where(
                        temps[:, None] > 0.0, samp_ok, greedy_ok
                    )
                    all_ok = jnp.all(ok, axis=1)
                    m = jnp.where(
                        all_ok, K,
                        jnp.argmax(~ok, axis=1).astype(jnp.int32),
                    )
                    corr_greedy = jnp.take_along_axis(
                        g, m[:, None], axis=1
                    )[:, 0]
                    # residual draw: q padded with a zeros row so full
                    # acceptance (m == K) samples plain p — the bonus
                    # token
                    q_pad = jnp.concatenate(
                        [q, jnp.zeros_like(q[:, :1])], axis=1
                    )
                    p_m = jnp.take_along_axis(
                        p_dist, m[:, None, None], axis=1
                    )[:, 0]
                    q_m = jnp.take_along_axis(
                        q_pad, m[:, None, None], axis=1
                    )[:, 0]
                    resid = jnp.clip(p_m - q_m, 0.0)
                    corr_samp = jax.vmap(
                        lambda k, r: jax.random.categorical(
                            k, jnp.log(r + 1e-20)
                        )
                    )(k_corr, resid).astype(jnp.int32)
                    corr = jnp.where(
                        temps > 0.0, corr_samp, corr_greedy
                    )
                    counts = jnp.where(spec, m + 1, 0)
                    # the in-graph rollback: rejected appends fall past
                    # the rewound length (dead by the length mask)
                    lengths2 = jnp.where(spec, lengths + m + 1, lengths)
                    drafts_pad = jnp.concatenate(
                        [drafts, jnp.zeros((n_slots, 1), jnp.int32)],
                        axis=1,
                    )
                    idxs = jnp.arange(K + 1)[None, :]
                    win_toks = jnp.where(
                        idxs == m[:, None], corr[:, None], drafts_pad
                    )
                    win_toks = jnp.where(idxs <= m[:, None], win_toks, 0)
                    toks_out = jnp.where(spec, corr, toks)
                    return (arena2, tables, lengths2, rngs_next,
                            toks_out, win_toks, counts)

                self._spec_verify_fn = self.costplane.compiles.wrap(
                    jax.jit(verify), "paged.spec_verify",
                    trigger=f"k={self.spec_k}",
                )
                self.compile_count += 1
            return self._spec_verify_fn

    def _retire_seat_locked(self, slot: int) -> int:
        """Release the seat's block references; returns how many
        blocks actually went back to the free list (shared prefix
        blocks a cache entry still holds do not)."""

        refs = self._seat_refs.pop(slot, [])
        drefs = self._draft_refs.pop(slot, [])
        freed = 0
        if refs:
            freed += self.alloc.release(refs)
        if drefs:
            # draft blocks are all private — every one goes back
            freed += self.alloc.release(drefs)
        return freed

    def _grow_seats_locked(self):
        """Budget-on-demand growth (ISSUE 12), in the once-per-window
        host window: for every active seat, allocate the blocks its
        next K-step window will cross into (capped at the budget's
        final in-cache position) and stage them as the table delta the
        single step dispatch writes in-graph — steady state stays
        exactly one dispatch per window.  Growers run in priority
        order, so arena shortfall lands on the batch tail; on
        shortfall the tier policy preempts (victims of tier <= the
        grower's, progress-guarded), and when nothing is preemptable
        the grower itself leaves the device (swap, or zero-copy park
        when the swap arena is full) rather than decoding into
        scratch.  Returns the (grow_logical, grow_phys) [slots, G]
        delta arrays; no-op rows index past the table and drop."""

        G = self._step_nbw
        gl = np.full((self.slots, G), self.max_blocks, np.int32)
        gp = np.full((self.slots, G), SCRATCH_BLOCK, np.int32)
        gld = np.full((self.slots, G), self.max_blocks, np.int32)
        gpd = np.full((self.slots, G), SCRATCH_BLOCK, np.int32)
        K = self.steps_per_sync
        bs = self.block_size
        now = time.monotonic()
        order = sorted(
            self._active.items(),
            key=lambda kv: self._queue_sort_key(kv[1], now),
        )
        for slot, req in order:
            if slot not in self._active:
                continue  # preempted as an earlier grower's victim
            spec = self._spec_req(req)
            # a speculative window appends spec_k + 1 positions
            # (transiently, before the in-graph rollback) — both the
            # target and draft tables must cover the full span
            adv = (self.spec_k + 1) if spec else K
            committed = len(self._seat_refs[slot])
            length = req.prompt.size + len(req.tokens) - 1
            cap = max(req.prompt.size + req.budget - 1, 1)
            target = blocks_for(min(length + adv, cap), bs)
            delta = target - committed
            if delta > 0:
                ids = self._alloc_blocks_locked(
                    delta, max_victim_rank=_TIER_RANK[req.tier],
                    exclude_slot=slot,
                )
                if ids is None:
                    self._preempt_seat_locked(slot, reason="park")
                    continue
                gl[slot, :delta] = np.arange(
                    committed, committed + delta, dtype=np.int32
                )
                gp[slot, :delta] = ids
                self._seat_refs[slot].extend(ids)
            if spec:
                dcommitted = len(self._draft_refs[slot])
                ddelta = target - dcommitted
                if ddelta > 0:
                    dids = self._alloc_blocks_locked(
                        ddelta, max_victim_rank=_TIER_RANK[req.tier],
                        exclude_slot=slot,
                    )
                    if dids is None:
                        self._preempt_seat_locked(slot, reason="park")
                        continue
                    gld[slot, :ddelta] = np.arange(
                        dcommitted, dcommitted + ddelta, dtype=np.int32
                    )
                    gpd[slot, :ddelta] = dids
                    self._draft_refs[slot].extend(dids)
        # a seat preempted AFTER its growth was staged must not write
        # freed (possibly re-owned) block ids into its dead table row
        for s in range(self.slots):
            if s not in self._active:
                gl[s, :] = self.max_blocks
                gp[s, :] = SCRATCH_BLOCK
                gld[s, :] = self.max_blocks
                gpd[s, :] = SCRATCH_BLOCK
        return gl, gp, gld, gpd

    def step(self) -> int:
        """Admit (block-gated, priority-ordered), grow active seats'
        block tables lazily (preempting/parking under pressure), run
        `steps_per_sync` decode steps over the arena through the
        DEVICE-RESIDENT block tables (one XLA program, one host round
        trip — the only device→host traffic is the sanctioned token
        fetch inside the ledger's dispatch window; the growth delta
        rides the same dispatch), retire finished requests and free
        their blocks (one batched ``retire`` dispatch when any seat
        finished)."""

        self._admit()
        with self._lock:
            if self._active:
                (grow_logical, grow_phys, grow_logical_d,
                 grow_phys_d) = self._grow_seats_locked()
            if not self._active:
                # per-window gauge refresh even while only queueing:
                # a burst the arena cannot admit must still ramp
                # kv_blocks_pressure (host arithmetic, no device work)
                self._update_gauges_locked()
                return 0
            seats_active = len(self._active)
            # partition the window: speculating seats decode through
            # the draft + verify pair, the rest through the plain step
            # — each program masks the other partition's seats, so a
            # homogeneous pool stays at its old dispatch count (1 for
            # all-normal, 2 for all-speculating; 3 only when mixed)
            spec_mask = np.zeros((self.slots,), bool)
            for slot, r in self._active.items():
                if self._spec_req(r):
                    spec_mask[slot] = True
            norm_mask = ~spec_mask
            norm_mask[[s for s in range(self.slots)
                       if s not in self._active]] = False
            n_norm = int(norm_mask.sum())
            n_spec = int(spec_mask.sum())
            # growth deltas split by partition: each program must only
            # write its OWN seats' rows (the other program sees no-ops)
            gl_n = grow_logical.copy()
            gp_n = grow_phys.copy()
            gl_n[spec_mask] = self.max_blocks
            gp_n[spec_mask] = SCRATCH_BLOCK
            gl_s = grow_logical.copy()
            gp_s = grow_phys.copy()
            gl_s[~spec_mask] = self.max_blocks
            gp_s[~spec_mask] = SCRATCH_BLOCK
            t_window0 = time.monotonic()
            host_toks = None
            if n_norm:
                with self.dispatch("step", active=n_norm):
                    (arena, tables_dev, lengths_dev, rngs_dev, toks,
                     toks_k) = self._step()(
                        self.params, self._arena, self._tables_dev,
                        self._lengths_dev, self._temps_dev,
                        self._topks_dev, self._rngs_dev, self._last_tok,
                        jnp.asarray(norm_mask), gl_n, gp_n,
                    )
                    host_toks = np.asarray(toks_k)  # [K, slots]
                self._arena, self._last_tok = arena, toks
                self._tables_dev = tables_dev
                self._lengths_dev, self._rngs_dev = lengths_dev, rngs_dev
            host_win = None
            host_counts = None
            if n_spec:
                with self.dispatch("draft", active=n_spec):
                    smask = jnp.asarray(spec_mask)
                    (darena, dtables, drngs, d_toks,
                     d_dists) = self._spec_draft()(
                        self._draft_params, self._draft_arena,
                        self._draft_tables_dev, self._lengths_dev,
                        self._temps_dev, self._topks_dev,
                        self._draft_rngs_dev, self._last_tok, smask,
                        grow_logical_d, grow_phys_d,
                    )
                self._draft_arena = darena
                self._draft_tables_dev = dtables
                self._draft_rngs_dev = drngs
                with self.dispatch("verify", active=n_spec):
                    (arena, tables_dev, lengths_dev, rngs_dev, toks,
                     win_toks, counts) = self._spec_verify()(
                        self.params, self._arena, self._tables_dev,
                        self._lengths_dev, self._temps_dev,
                        self._topks_dev, self._rngs_dev, self._last_tok,
                        smask, d_toks, d_dists, gl_s, gp_s,
                    )
                    host_win = np.asarray(win_toks)      # [slots, K+1]
                    host_counts = np.asarray(counts)     # [slots]
                self._arena, self._last_tok = arena, toks
                self._tables_dev = tables_dev
                self._lengths_dev, self._rngs_dev = lengths_dev, rngs_dev
                self.spec_windows += 1
            t_window1 = time.monotonic()
            # ISSUE 20 step-time sentinel: same host wall the
            # decode.window spans carry — regression shows up here as
            # a drift ratio long before an offline bench window runs
            self.costplane.sentinel.observe(
                "decode.window", t_window1 - t_window0
            )
            finished = []
            finished_reqs = []
            for slot in list(self._active):
                req = self._active[slot]
                if spec_mask[slot]:
                    # the verify program already rewound the length to
                    # L + accepted + 1; the host only distributes the
                    # accepted tokens + correction
                    n_tok = int(host_counts[slot])
                    take = min(n_tok, req.budget - len(req.tokens))
                    req.tokens.extend(
                        int(t) for t in host_win[slot, :take]
                    )
                    accepted = n_tok - 1
                    self.spec_proposed += self.spec_k
                    self.spec_accepted += accepted
                    self.spec_emitted += take
                    if accepted < self.spec_k:
                        self.spec_rollbacks += 1
                    if self.metrics is not None:
                        # literal label keys: the alert/autoscaling
                        # lint collectors pin {model, tier} off these
                        # call sites
                        self.metrics.inc(
                            "serve_spec_proposed_total",
                            self.spec_k * 1.0,
                            model=self.model_label, tier=req.tier,
                        )
                        self.metrics.inc(
                            "serve_spec_accepted_total",
                            accepted * 1.0,
                            model=self.model_label, tier=req.tier,
                        )
                        if accepted < self.spec_k:
                            self.metrics.inc(
                                "serve_spec_rollbacks_total",
                                model=self.model_label, tier=req.tier,
                            )
                else:
                    # the cache now holds K more positions for this
                    # seat (the step program advanced the
                    # device-resident lengths in-graph; overshoot past
                    # the budget landed in scratch via the padded
                    # table / scratch-routed append — the reserved
                    # tail blocks absorb the in-budget span)
                    take = min(
                        len(host_toks), req.budget - len(req.tokens)
                    )
                    req.tokens.extend(
                        int(t) for t in host_toks[:take, slot]
                    )
                req.tokens_since_seat += take
                self._emit_span(
                    req, "decode.window", t_window0, t_window1,
                    tokens=take, seats_active=seats_active,
                )
                if req.entry is not None:
                    self.request_log.add_window(req.entry, take)
                if len(req.tokens) >= req.budget:
                    req.done = True
                    req.slot = None
                    del self._active[slot]
                    freed = self._retire_seat_locked(slot)
                    self._finish_request(req, blocks_freed=freed)
                    finished.append(slot)
                    finished_reqs.append(req)
            if finished:
                # freed blocks may re-allocate immediately: the dead
                # seats' device table rows must go back to scratch
                # before the next step's in-place appends
                self._retire_device_locked(finished, reqs=finished_reqs)
            self._update_gauges_locked()
            if finished:
                self._done_cond.notify_all()
            return len(self._active)

    def spec_snapshot(self) -> Dict[str, float]:
        """Host-side speculative accounting: acceptance rate and the
        CPU-honest dispatches-per-emitted-token (draft + verify
        dispatches over tokens actually delivered) — the number the
        speculative-paged benchmark row and the serve_lm refusal guard
        quote."""

        windows = self.spec_windows
        emitted = self.spec_emitted
        return {
            "spec_windows": float(windows),
            "spec_proposed": float(self.spec_proposed),
            "spec_accepted": float(self.spec_accepted),
            "spec_rollbacks": float(self.spec_rollbacks),
            "spec_emitted": float(emitted),
            "acceptance_rate": (
                self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0
            ),
            "dispatches_per_token": (
                2.0 * windows / emitted if emitted else float("inf")
            ),
        }
