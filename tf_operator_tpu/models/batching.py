"""Continuous-batching decoder: concurrent requests share one decode
loop, joining and leaving at STEP granularity.

`ChunkedServingDecoder` serves one request per call: a second request
waits for the first to finish, so a server at concurrency k runs the
weight-bandwidth-bound decode loop k times sequentially.  Continuous
batching (the vLLM idea, re-shaped for XLA's static-shape world) keeps
a fixed pool of `slots` and one compiled step program:

- **Stacked slot caches.**  The KV cache of a batch-1 decode is stacked
  along a new leading slot axis; the per-layer ``cache_index`` scalar
  becomes a per-slot vector, so every slot sits at its own sequence
  position — the thing a plain batched ``generate`` cannot do.
- **One vmapped step.**  ``jax.vmap`` of the batch-1 apply over the
  slot axis: weights broadcast (the projections still execute as one
  ``[slots,1,D]x[D,F]`` dot on the MXU); the per-slot cache write
  lowers to a scatter of one row per layer.  Inactive slots compute
  too (their writes land in already-dead cache rows) — the step cost
  is constant, which is exactly the point: an arriving request rides
  a loop that was already paying for it.
- **Compile count is O(1) + O(log max_len).**  One step program per
  pool; admission compiles one fused program per power-of-2 prompt
  width class (below), the rolling-window legacy path reuses the
  binary-chunk prefill programs.
- **K tokens per host round trip** (``steps_per_sync``): the step
  program scans K decode steps, so a tunneled chip (host↔device rides
  the network here) pays one round trip per K tokens instead of per
  token.  Requests join/retire at K-step granularity — worst case
  K-1 wasted slot-steps per finished request.
- **Single-dispatch admission** (r6, VERDICT r5 next #5).  The old
  admission sequence — chunked prefill into a batch-1 cache (>=1
  dispatch per chunk), a first-token sample, then a scatter-seating
  dispatch — cost >=3 device round trips per request; on a tunneled
  chip (~66 ms RTT each, PROFILE.md "r5 serving") admissions alone
  outweighed the decode they fed.  Admission is now ONE compiled
  program per power-of-2 prompt-width class: the prompt, zero-padded
  to the next power of two, prefills a fresh batch-1 cache in-graph;
  causal masking makes the true last position's logits exact despite
  the pad, and resetting ``cache_index`` back to the true length
  (``decode.set_cache_index`` — the speculative-rollback primitive)
  makes the pad rows invisible to every later step; the first token
  samples and the row scatters into the slot stack in the same
  program.  Exactly 1 dispatch per admitted request, compile count
  still logarithmic.  Cost of the trick: up to 2x prefill compute on
  pad positions (worst case p = 2^k + 1), irrelevant here and cheap
  against a single round trip anywhere.  The fused program needs a
  seat, so it runs in ``_admit`` under the pool lock (``submit`` just
  validates and queues — it never blocks and never touches the
  device); the device serializes programs regardless, so driver-side
  seating loses no throughput, only the old eager-prefill overlap of
  per-chunk dispatch latencies — which is the thing being deleted.
  ROLLING-WINDOW caches keep the legacy staged path (pad writes would
  poison ``cached_pos``, and the wrap state is not index-rollbackable)
  with eager submitter-thread prefill bounded by staging permits at
  2x slots, exactly as before; same for prompts whose padded width
  exceeds max_len.
- **Dispatch ledger.**  Every device call is counted and timed through
  ``utils/metrics.DispatchLedger`` (phases: admission, step, and the
  legacy path's prefill/scatter), so "tunnel overhead" is an auditable
  ``count x RTT`` number — ``measure.py --section batching`` embeds
  the ledger in its JSON and tests pin admission at exactly 1.

Greedy and per-slot temperature sampling (a ``[slots]`` temperature
vector; 0 = argmax).  Requests finish by token budget (byte-level
serving has no universal EOS).  Rolling-window caches (window <
max_len) work unchanged — each slot's wrap state (cached_pos, circular
slots) is slot-local under the vmapped step; admission prefill chunks
cap at the window like ChunkedServingDecoder's.

The reference (SURVEY.md §0) has no serving story at all; this is a
beyond-reference subsystem.  On-chip evidence: aggregate decode
tokens/s at concurrency 8 vs sequential single-request serving —
``benchmarks/measure.py --section batching``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tf_operator_tpu.models.decode import (
    _decode_variant,
    _init_cache_for,
    max_window_chunk,
    set_cache_index,
    top_k_mask,
    window_chunks,
)
from tf_operator_tpu.ops.quant import materialize_fn
from tf_operator_tpu.utils.metrics import DispatchLedger


#: static top-k width: per-slot k thresholds within the top TOP_K_MAX
#: candidates, so one compiled step serves every requested k
TOP_K_MAX = 64


class _Request:
    __slots__ = ("rid", "prompt", "budget", "temperature", "top_k", "rng",
                 "tokens", "done", "slot", "staged_cache", "staged_tok",
                 "has_permit", "t_submit", "t_first")

    def __init__(self, rid, prompt, budget, temperature, top_k, rng):
        self.rid = rid
        self.prompt = prompt  # np.ndarray [P] int32
        self.budget = budget
        self.temperature = temperature
        self.top_k = top_k  # None = no truncation
        self.rng = rng
        self.tokens: List[int] = []
        self.done = False
        self.slot: Optional[int] = None
        # primed batch-1 cache + first token: staged by the submitter's
        # thread when a staging permit was free (has_permit=True), else
        # primed lazily at admission; consumed by the seating scatter
        self.staged_cache = None
        self.staged_tok = None
        self.has_permit = False
        # SLO clocks (host monotonic): submit time, first-token time —
        # queue-wait/TTFT/time-per-output-token derive from these
        self.t_submit = time.perf_counter()
        self.t_first = None


class ContinuousBatchingDecoder:
    """Fixed-slot continuous batching over one compiled decode step.

    Thread-safe: `submit` may be called from request threads while a
    driver thread calls `step`; all pool state is lock-protected.
    """

    def __init__(self, model, params, slots: int = 8, steps_per_sync: int = 8,
                 ledger: Optional[DispatchLedger] = None,
                 metrics=None, model_label: str = ""):
        #: device-dispatch accounting (phases: admission, step, and the
        #: legacy rolling-window path's prefill/scatter)
        self.ledger = ledger if ledger is not None else DispatchLedger()
        #: SLO sink (utils/metrics.Metrics or None): every request
        #: observes queue-wait / TTFT / time-per-output-token
        #: histograms labeled {model, mode="pool"}, plus the
        #: serve_admission_queue_depth and serve_tokens_in_flight
        #: gauges — the user-facing latency layer over the ledger's
        #: per-dispatch accounting
        self.metrics = metrics if metrics is not None else self.ledger.metrics
        self.model_label = model_label or "unknown"
        self.dmodel = _decode_variant(model)
        self._materialize = materialize_fn(model)
        cfg = self.dmodel.cfg
        # rolling-window caches (window < max_len) work unchanged: each
        # slot's cache — including its wrap state (cached_pos, circular
        # slots) — is independent under the vmapped batch-1 step.  Only
        # PREFILL needs care: the rolling cache accepts at most
        # `window` tokens per apply, so admission chunks cap at the
        # window (ONE rule, shared with ChunkedServingDecoder —
        # decode.window_chunks / max_window_chunk).
        self._max_chunk = max_window_chunk(cfg)
        self.params = params
        self.slots = int(slots)
        #: tokens generated per host round trip.  One device sync per
        #: TOKEN would put a host↔device round trip (a NETWORK round
        #: trip on a tunneled chip) on every step's critical path —
        #: the sequential decoder runs its whole budget in one XLA
        #: program and would win on latency alone.  K steps per sync
        #: amortize that; requests join/retire at K-step granularity
        #: (worst-case waste K-1 steps per finished request).
        self.steps_per_sync = max(1, int(steps_per_sync))
        self.max_len = cfg.max_len
        self._lock = threading.Lock()
        self._done_cond = threading.Condition(self._lock)
        # guards the jitted-fn caches: _prefill now runs on submitter
        # threads with NO pool lock held, so fn creation needs its own
        # (tiny) critical section
        self._compile_lock = threading.Lock()
        # staging backpressure: every submitted-but-unseated request
        # that prefilled EAGERLY holds a primed batch-1 KV cache in
        # DEVICE memory, and serve_lm's ThreadingHTTPServer puts no
        # bound on concurrent submitters — without a cap, a burst of
        # N >> slots requests would pin N full-max_len caches and OOM
        # the chip.  Permits bound eager staging at 2x slots; overflow
        # requests queue host-side (prompt only) and prefill lazily at
        # admission instead (also off the pool lock, in _admit), so
        # submit NEVER blocks and device memory stays bounded at
        # slots + 2*slots caches.
        self._staging = threading.BoundedSemaphore(max(1, 2 * self.slots))
        #: slots picked by an in-flight lazy admission (lock dropped
        #: during its prefill) — excluded from the free list meanwhile
        self._reserved = set()
        self._rid = 0
        self._queue: List[_Request] = []  # submitted, no slot yet
        self._active: Dict[int, _Request] = {}  # slot -> request
        self._results: Dict[int, _Request] = {}
        # device state: stacked batch-1 caches + per-slot last token.
        # Only the SHAPES of the batch-1 row survive on self (the
        # fused admission program builds its fresh cache in-graph from
        # them); keeping the materialized template would pin an extra
        # 1/slots of the pool's cache memory in device HBM for nothing.
        row0 = _init_cache_for(self.dmodel, 1)
        self._row_shapes = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), row0
        )
        self._cache = jax.tree_util.tree_map(
            lambda l: jnp.stack([l] * self.slots), row0
        )
        self._last_tok = jnp.zeros((self.slots,), jnp.int32)
        self._prefill_fns = {}  # chunk width -> jitted batch-1 prefill
        self._admit_fns = {}  # pow2 prompt width -> fused admission
        self._step_fn = None
        self._scatter_fn = None
        self.compile_count = 0

    # -- SLO observations ------------------------------------------------

    def _observe_first_token(self, req: _Request, work_start: float) -> None:
        """First output token just landed on the host: observe
        queue-wait (submit → first device work) and TTFT (submit →
        first token), once per request."""

        if req.t_first is not None:
            return
        req.t_first = time.perf_counter()
        if self.metrics is None:
            return
        self.metrics.observe_histogram(
            "serve_queue_wait_seconds",
            max(0.0, work_start - req.t_submit),
            model=self.model_label, mode="pool",
        )
        self.metrics.observe_histogram(
            "serve_ttft_seconds",
            req.t_first - req.t_submit,
            model=self.model_label, mode="pool",
        )

    def _observe_done(self, req: _Request) -> None:
        """Request retired: observe time-per-output-token (first token
        → done, over the tokens after the first)."""

        if self.metrics is None:
            return
        t_done = time.perf_counter()
        t_first = req.t_first if req.t_first is not None else t_done
        self.metrics.observe_histogram(
            "serve_time_per_output_token_seconds",
            (t_done - t_first) / max(1, len(req.tokens) - 1),
            model=self.model_label, mode="pool",
        )

    def _update_gauges_locked(self) -> None:
        """Admission-queue depth + tokens-in-flight gauges (caller
        holds the pool lock)."""

        if self.metrics is None:
            return
        self.metrics.set(
            "serve_admission_queue_depth",
            float(len(self._queue)),
            model=self.model_label,
        )
        inflight = sum(
            r.budget - len(r.tokens) for r in self._active.values()
        ) + sum(r.budget - len(r.tokens) for r in self._queue)
        self.metrics.set(
            "serve_tokens_in_flight",
            float(max(0, inflight)),
            model=self.model_label,
        )

    # -- compiled pieces -------------------------------------------------

    def _prefill(self, width: int):
        with self._compile_lock:
            if width not in self._prefill_fns:
                dmodel = self.dmodel
                materialize = self._materialize

                def prefill(params, cache, ids):  # ids [1, width]
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": cache},
                        ids,
                        mutable=["cache"],
                    )
                    return vars_["cache"], logits[0, -1]

                self._prefill_fns[width] = jax.jit(prefill)
                self.compile_count += 1
            return self._prefill_fns[width]

    def _scatter(self):
        """Write one batch-1 cache + token into slot `i` of the stack."""

        with self._compile_lock:
            if self._scatter_fn is None:

                def scatter(stack, row_cache, last_tok, toks, i):
                    stack = jax.tree_util.tree_map(
                        lambda s, r: lax.dynamic_update_index_in_dim(
                            s, r, i, axis=0
                        ),
                        stack,
                        row_cache,
                    )
                    return stack, toks.at[i].set(last_tok)

                self._scatter_fn = jax.jit(scatter)
                self.compile_count += 1
            return self._scatter_fn

    def _fused_width(self, p: int) -> Optional[int]:
        """Padded width class for single-dispatch admission, or None
        when the request must take the legacy staged path (rolling-
        window cache, or a pad-to-pow2 width the cache can't hold)."""

        if self._max_chunk is not None:
            return None  # rolling cache: pad writes poison cached_pos
        w = 1 << max(0, p - 1).bit_length()
        return w if w <= self.max_len else None

    def _admission(self, width: int):
        """The whole admission as ONE compiled program per power-of-2
        prompt-width class: padded prefill into a fresh in-graph
        batch-1 cache, cache_index rollback to the true length (pad
        rows become invisible — set_cache_index, the speculative
        rollback primitive), first-token sample at the true last
        position, and the scatter-seating into slot `slot`.  Returns
        (stack, last_toks, first_token, advanced_rng) — the rng split
        happens in-graph so a sampled admission is still exactly one
        dispatch."""

        with self._compile_lock:
            if width not in self._admit_fns:
                dmodel = self.dmodel
                materialize = self._materialize
                template = self._row_shapes  # ShapeDtypeStructs

                def admit(params, stack, toks, ids, n, slot, temp,
                          top_k, rng):
                    cache = jax.tree_util.tree_map(
                        lambda l: jnp.zeros(l.shape, l.dtype), template
                    )
                    logits, vars_ = dmodel.apply(
                        {"params": materialize(params), "cache": cache},
                        ids,
                        mutable=["cache"],
                    )
                    # causal masking: rows < n never see the pad rows,
                    # so the true last position's logits are exact;
                    # the index reset makes the pad K/V rows invisible
                    # to every later decode step
                    row_cache = set_cache_index(vars_["cache"], n)
                    last = lax.dynamic_index_in_dim(
                        logits[0], n - 1, axis=0, keepdims=False
                    )  # [V]
                    greedy = jnp.argmax(last, -1).astype(jnp.int32)
                    split = jax.random.split(rng)
                    rng_next, r = split[0], split[1]
                    safe_t = jnp.where(temp > 0.0, temp, 1.0)
                    scaled = last / safe_t
                    # same static top-k trick as the step body: the
                    # runtime k thresholds within the top TOP_K_MAX
                    k_max = min(TOP_K_MAX, scaled.shape[-1])
                    top_vals = lax.top_k(scaled, k_max)[0]
                    kth = top_vals[jnp.clip(top_k - 1, 0, k_max - 1)]
                    scaled = jnp.where(
                        (top_k > 0) & (scaled < kth), -jnp.inf, scaled
                    )
                    samp = jax.random.categorical(r, scaled).astype(
                        jnp.int32
                    )
                    tok = jnp.where(temp > 0.0, samp, greedy)
                    stack = jax.tree_util.tree_map(
                        lambda s, row: lax.dynamic_update_index_in_dim(
                            s, row, slot, axis=0
                        ),
                        stack,
                        row_cache,
                    )
                    return stack, toks.at[slot].set(tok), tok, rng_next

                self._admit_fns[width] = jax.jit(admit)
                self.compile_count += 1
            return self._admit_fns[width]

    def _step(self):
        if self._step_fn is None:
            dmodel = self.dmodel
            n_inner = self.steps_per_sync
            materialize = self._materialize

            def one_slot(params, cache, tok):
                # batch-1 apply; under vmap the weights broadcast and
                # the per-slot cache_index stays a scalar per slot
                logits, vars_ = dmodel.apply(
                    {"params": params, "cache": cache},
                    tok[None, None],
                    mutable=["cache"],
                )
                return vars_["cache"], logits[0, 0]

            def step(params, stack, toks, temps, top_ks, rngs):
                # K decode steps per host round trip: the whole inner
                # loop is ONE XLA program, so a tunneled chip pays one
                # network round trip per K tokens, not per token.
                # Quantized trees: QDense families keep int8 all the
                # way to quant_matmul; others dequantize per step here.
                def body(carry, _):
                    stack, toks, rngs = carry
                    stk, logits = jax.vmap(
                        one_slot, in_axes=(None, 0, 0)
                    )(materialize(params), stack, toks)
                    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    split = jax.vmap(jax.random.split)(rngs)
                    safe_t = jnp.where(temps > 0.0, temps, 1.0)
                    scaled = logits / safe_t[:, None]
                    # per-slot top_k with one STATIC top-k (compile
                    # stays shape-stable): threshold at each slot's own
                    # k within the top TOP_K_MAX candidates; 0 = off
                    k_max = min(TOP_K_MAX, scaled.shape[-1])
                    top_vals = lax.top_k(scaled, k_max)[0]  # [slots,k_max]
                    idx = jnp.clip(top_ks - 1, 0, k_max - 1)[:, None]
                    kth = jnp.take_along_axis(top_vals, idx, axis=1)
                    scaled = jnp.where(
                        (top_ks[:, None] > 0) & (scaled < kth),
                        -jnp.inf,
                        scaled,
                    )
                    sampled = jax.vmap(
                        lambda r, l: jax.random.categorical(r, l)
                    )(split[:, 0], scaled).astype(jnp.int32)
                    nxt = jnp.where(temps > 0.0, sampled, greedy)
                    return (stk, nxt, split[:, 1]), nxt

                (stack, toks, _), toks_k = lax.scan(
                    body, (stack, toks, rngs), None, length=n_inner
                )
                return stack, toks, toks_k  # toks_k: [K, slots]

            self._step_fn = jax.jit(step)
            self.compile_count += 1
        return self._step_fn

    # -- public API ------------------------------------------------------

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        rng: Optional[jax.Array] = None,
    ) -> int:
        """Queue a single request ([P] int32).  Returns a request id;
        collect the output with `result` after `step`s (or `run`)."""

        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len={self.max_len}"
            )
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature sampling needs an explicit rng key")
        if temperature == 0.0:
            top_k = None  # greedy ignores top_k (same as generate())
        if top_k is not None:
            top_k = int(top_k)
            if not (1 <= top_k <= TOP_K_MAX):
                raise ValueError(
                    f"top_k must be in [1, {TOP_K_MAX}] (the pool's "
                    f"static top-k width), got {top_k}"
                )
        with self._lock:
            rid = self._rid
            self._rid += 1
        req = _Request(
            rid, prompt, max_new_tokens, float(temperature), top_k, rng,
        )
        # fused-eligible requests (non-rolling cache, pad width fits)
        # queue host-side untouched: their ENTIRE admission — prefill,
        # first token, seating — is one compiled dispatch in _admit,
        # so submit never touches the device.  Only the legacy path
        # (rolling-window caches, oversize pad widths) still prefills
        # eagerly on the submitter's thread under a staging permit;
        # past the permit bound it queues and primes lazily at
        # admission — submit never blocks on either path.
        if self._fused_width(prompt.size) is None and \
                self._staging.acquire(blocking=False):
            req.has_permit = True
            try:
                self._prefill_request(req)
            except BaseException:
                self._staging.release()
                raise
        with self._lock:
            self._results[rid] = req
            if req.staged_cache is not None and len(req.tokens) >= req.budget:
                # budget-1, eagerly prefilled: already complete —
                # never needs a slot
                req.done = True
                self._release_staged_locked(req)
                self._observe_done(req)
                self._done_cond.notify_all()
            else:
                self._queue.append(req)
            self._update_gauges_locked()
        return rid

    def _release_staged_locked(self, req: _Request) -> None:
        req.staged_cache = req.staged_tok = None
        if req.has_permit:
            req.has_permit = False
            self._staging.release()

    def _prefill_request(self, req: _Request) -> None:
        """Device-side admission work for one request — chunked prompt
        prefill into a fresh batch-1 cache plus the first sampled
        token — run with NO pool lock held (VERDICT r4 next #7: the
        old under-lock prefill serialized every concurrent submit()
        and the driver's step() behind a multi-device-call prefill;
        at seq-1k prompts on a tunneled chip that stalled the whole
        pool per admission).  Trade-off: a request waiting for a free
        slot holds its primed batch-1 cache in device memory — bounded
        by the staging semaphore (2x slots permits; see __init__),
        which blocks further submits instead of letting a request
        burst OOM the chip."""

        work_start = time.perf_counter()
        cache = _init_cache_for(self.dmodel, 1)
        last = None
        off = 0
        for width in window_chunks(req.prompt.size, self._max_chunk):
            ids = jnp.asarray(
                req.prompt[off : off + width][None, :], jnp.int32
            )
            with self.ledger.dispatch("prefill", rid=req.rid):
                cache, last = self._prefill(width)(self.params, cache, ids)
            off += width
        # the prompt's first sampled token comes from prefill logits.
        # Recorded as one "sample" ledger entry — the un-jitted op
        # group below is 1 (greedy) to ~3 (split+mask+categorical)
        # tiny device calls; the fused admission folds all of this
        # into its single program
        with self.ledger.dispatch("sample", rid=req.rid):
            if req.temperature > 0.0:
                req.rng, r = jax.random.split(req.rng)
                scaled = last / req.temperature
                if req.top_k is not None:
                    scaled = top_k_mask(scaled, req.top_k)
                tok = jax.random.categorical(r, scaled).astype(jnp.int32)
            else:
                tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        req.staged_cache = cache
        req.staged_tok = tok
        req.tokens.append(int(tok))
        self._observe_first_token(req, work_start)

    def _admit_fused(self, req: _Request, slot: int, width: int) -> None:
        """Seat one request with exactly ONE device dispatch (the fused
        per-width admission program).  Caller holds the pool lock: the
        program rewrites the shared slot stack, so it must serialize
        with step() — the device would serialize the programs anyway;
        the lock only mirrors that ordering on the host."""

        ids = np.zeros((1, width), np.int32)
        ids[0, : req.prompt.size] = req.prompt
        sampled = req.temperature > 0.0
        rng = req.rng if sampled else jnp.zeros((2,), jnp.uint32)
        work_start = time.perf_counter()
        with self.ledger.dispatch("admission", rid=req.rid, width=width):
            stack, toks, tok, rng_next = self._admission(width)(
                self.params, self._cache, self._last_tok,
                jnp.asarray(ids), jnp.int32(req.prompt.size),
                jnp.int32(slot), jnp.float32(req.temperature),
                jnp.int32(req.top_k or 0), rng,
            )
            tok_h = int(tok)  # host fetch: the ledger RTT includes it
        self._cache, self._last_tok = stack, toks
        if sampled:
            req.rng = rng_next
        req.tokens.append(tok_h)
        self._observe_first_token(req, work_start)
        if len(req.tokens) >= req.budget:
            # budget-1: the admission token completed it; the scattered
            # cache rows are dead and the slot stays free
            req.done = True
            self._observe_done(req)
            self._done_cond.notify_all()
        else:
            req.slot = slot
            self._active[slot] = req

    def _admit(self) -> None:
        """Seat queued requests into free slots.

        Fused path (non-rolling caches): the whole admission is ONE
        compiled dispatch under the lock (_admit_fused).  Legacy path
        (rolling-window caches / oversize pad widths): reserve a seat
        under the lock; prefill with the lock DROPPED if the request
        arrived un-staged (permit-exhausted burst took the lazy path);
        then scatter + bookkeeping under the lock — lock-held legacy
        device work is always exactly ONE scatter call."""

        while True:
            with self._lock:
                if not self._queue:
                    return
                free = [
                    s for s in range(self.slots)
                    if s not in self._active and s not in self._reserved
                ]
                if not free:
                    return
                req = self._queue.pop(0)
                slot = free[0]
                width = self._fused_width(req.prompt.size)
                if width is not None and req.staged_cache is None:
                    try:
                        self._admit_fused(req, slot, width)
                        self._update_gauges_locked()
                    except BaseException:
                        # same survival rule as the legacy prefill: a
                        # transient device failure must re-queue the
                        # request, not strand its rid in _results with
                        # waiters blocked forever (_admit_fused mutates
                        # pool state only after a successful dispatch,
                        # so head-of-queue reinsertion is safe)
                        self._queue.insert(0, req)
                        raise
                    continue
                self._reserved.add(slot)
            try:
                if req.staged_cache is None:
                    self._prefill_request(req)  # lazy path, off-lock
            except BaseException:
                # the request must survive a transient prefill failure
                # (device OOM is the exact pressure this path exists
                # for): back to the queue head so a retried step() can
                # admit it; without this the rid would leak in
                # _results and its waiters would hang forever
                with self._lock:
                    self._reserved.discard(slot)
                    self._queue.insert(0, req)
                raise
            with self._lock:
                self._reserved.discard(slot)
                if len(req.tokens) >= req.budget:
                    # budget-1 on the lazy path: the prefill token
                    # completed it — never needs the seat after all
                    req.done = True
                    self._release_staged_locked(req)
                    self._observe_done(req)
                    self._update_gauges_locked()
                    self._done_cond.notify_all()
                    continue
                with self.ledger.dispatch("scatter", rid=req.rid):
                    self._cache, self._last_tok = self._scatter()(
                        self._cache, req.staged_cache, req.staged_tok,
                        self._last_tok, jnp.int32(slot),
                    )
                self._release_staged_locked(req)
                req.slot = slot
                self._active[slot] = req
                self._update_gauges_locked()

    def step(self) -> int:
        """Admit waiting requests, run `steps_per_sync` decode steps
        for every active slot (one XLA program, one host round trip),
        append sampled tokens, retire finished requests.  Returns the
        number of still-active slots."""

        self._admit()
        with self._lock:
            if not self._active:
                return 0
            temps = np.zeros((self.slots,), np.float32)
            top_ks = np.zeros((self.slots,), np.int32)  # 0 = no top_k
            # legacy uint32[2] keys vmap as plain rows; dead slots get
            # key 0 but their temps=0 routes them to the greedy branch
            rngs = np.zeros((self.slots, 2), np.uint32)
            for slot, req in self._active.items():
                temps[slot] = req.temperature
                top_ks[slot] = req.top_k or 0
                if req.temperature > 0.0:
                    req.rng, r = jax.random.split(req.rng)
                    rngs[slot] = np.asarray(r)
            with self.ledger.dispatch("step", active=len(self._active)):
                self._cache, self._last_tok, toks_k = self._step()(
                    self.params,
                    self._cache,
                    self._last_tok,
                    jnp.asarray(temps),
                    jnp.asarray(top_ks),
                    jnp.asarray(rngs),
                )
                host_toks = np.asarray(toks_k)  # [K, slots]
            finished = False
            for slot in list(self._active):
                req = self._active[slot]
                take = min(len(host_toks), req.budget - len(req.tokens))
                req.tokens.extend(int(t) for t in host_toks[:take, slot])
                if len(req.tokens) >= req.budget:
                    # overshoot steps (< K) wrote only this slot's own
                    # dead cache rows; admission scatters a fresh cache
                    req.done = True
                    req.slot = None
                    del self._active[slot]
                    self._observe_done(req)
                    finished = True
            self._update_gauges_locked()
            if finished:
                self._done_cond.notify_all()
            return len(self._active)

    def run(self) -> None:
        """Step until every submitted request has finished."""

        while True:
            with self._lock:
                idle = not self._queue and not self._active
            if idle:
                return
            self.step()

    def result(self, rid: int):
        """[P + n] int32 (prompt + generated), or None if not done.

        A finished request is EVICTED on first read — a long-running
        server submits without bound, so retaining every finished
        request would be a memory leak.  Read once, keep the array."""

        with self._lock:
            req = self._results.get(rid)
            if req is None:
                raise KeyError(
                    f"request {rid} unknown or already collected "
                    "(results evict on first read)"
                )
            if not req.done:
                return None
            del self._results[rid]
        return np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)])

    def result_wait(self, rid: int, timeout: Optional[float] = None):
        """Block (condition wait, no polling) until request `rid`
        finishes; returns the [P + n] int32 row, or None on timeout.
        Evicts on success like `result`; a second wait on a collected
        rid raises KeyError rather than blocking forever."""

        with self._done_cond:
            ok = self._done_cond.wait_for(
                lambda: rid not in self._results or self._results[rid].done,
                timeout=timeout,
            )
            if not ok:
                return None
            req = self._results.pop(rid, None)
            if req is None:
                raise KeyError(
                    f"request {rid} unknown or already collected "
                    "(results evict on first read)"
                )
        return np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)])
