"""BERT — masked-LM pretraining model.

Parity target: BASELINE.md config 3, "BERT-base pretrain,
ParameterServerStrategy, 2 PS + 4 workers".  The TPU-native translation
(SURVEY.md §2b): PS-style sharded parameters become fsdp-sharded params
+ tp-sharded attention/MLP over the mesh; no parameter servers exist —
XLA collectives move the shards.

`bert_base()` matches the BERT-base shape (110M params).  The MLM loss
helper masks tokens the standard way (15% positions, loss on masked
positions only) but takes pre-masked batches — data pipelines own
masking.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.transformer import (
    ACT_HIDDEN,
    Embed,
    EncoderLayer,
    LayerNorm,
    TransformerConfig,
    dense,
    logical_constraint,
    param_with_axes,
)


class Bert(nn.Module):
    cfg: TransformerConfig
    n_segments: int = 2

    @nn.compact
    def __call__(
        self,
        input_ids,  # [B, S]
        *,
        segment_ids=None,
        attention_mask=None,  # [B, S] 1 = real token
        train: bool = False,
    ):
        cfg = self.cfg
        b, s = input_ids.shape
        x = Embed(cfg, name="tok_embed")(input_ids)
        pos = self.param(
            "pos_embed",
            param_with_axes(nn.initializers.normal(0.02), ("seq", "embed")),
            (cfg.max_len, cfg.hidden),
            jnp.float32,
        )
        x = x + pos[None, :s].astype(cfg.dtype)
        if segment_ids is not None:
            seg = self.param(
                "seg_embed",
                param_with_axes(nn.initializers.normal(0.02), ("stack", "embed")),
                (self.n_segments, cfg.hidden),
                jnp.float32,
            )
            x = x + jnp.take(seg, segment_ids, axis=0).astype(cfg.dtype)
        x = LayerNorm(cfg, name="ln_embed")(x)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = logical_constraint(x, ACT_HIDDEN)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(cfg.n_layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(x, mask=mask, train=train)
        x = LayerNorm(cfg, name="ln_final")(x)
        return x  # [B, S, hidden]

class MlmHead(nn.Module):
    """MLM head: transform + decode to vocab."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.cfg
        y = dense(cfg.hidden, cfg, ("embed", "embed2"), name="mlm_transform")(hidden)
        y = nn.gelu(y)
        y = LayerNorm(cfg, name="mlm_ln")(y)
        logits = dense(cfg.vocab_size, cfg, ("embed", "vocab"), name="mlm_decoder")(y)
        return logits.astype(jnp.float32)


class BertForPretraining(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, *, attention_mask=None, train: bool = False):
        hidden = Bert(self.cfg, name="bert")(
            input_ids, attention_mask=attention_mask, train=train
        )
        return MlmHead(self.cfg, name="mlm")(hidden)


def bert_base(vocab_size: int = 30522, max_len: int = 512, mesh=None) -> BertForPretraining:
    return BertForPretraining(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=768,
            n_heads=12,
            head_dim=64,
            n_layers=12,
            mlp_dim=3072,
            max_len=max_len,
            mesh=mesh,
        )
    )


def bert_tiny(vocab_size: int = 1024, max_len: int = 128, mesh=None, **kw) -> BertForPretraining:
    return BertForPretraining(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=128,
            n_heads=4,
            head_dim=32,
            n_layers=2,
            mlp_dim=512,
            max_len=max_len,
            mesh=mesh,
            **kw,
        )
    )


def mlm_loss(
    params, state, batch: Dict, rng, train: bool = True
) -> Tuple[jax.Array, Dict]:
    """batch: input_ids (pre-masked), labels (-100 = unmasked position),
    optional attention_mask."""

    logits = state.apply_fn(
        {"params": params},
        batch["input_ids"],
        attention_mask=batch.get("attention_mask"),
        train=train,
        rngs={"dropout": rng},
    )
    labels = batch["labels"]
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    denom = jnp.maximum(valid.sum(), 1)
    loss = jnp.where(valid, per_tok, 0.0).sum() / denom
    acc = jnp.where(valid, logits.argmax(-1) == safe, False).sum() / denom
    return loss, {"metrics": {"mlm_accuracy": acc}}
