"""ViT — vision transformer image classifier.

Beyond-reference model family (the reference's image workloads are
ResNet CNNs — SURVEY.md §6 configs 2/4); added so the framework's
image path has a transformer member that exercises the same encoder
stack, sharding rules, and attention kernels as the text families.

TPU-first choices:
- patch embedding is a RESHAPE + DENSE, not a conv: [B, H, W, C] →
  [B, N, p·p·C] → matmul to hidden.  Identical math to the standard
  stride-p conv, but it lands on the MXU as one large [B·N, p²C]×
  [p²C, hidden] matmul with no im2col/window machinery for XLA to
  pattern-match — the fastest possible lowering for non-overlapping
  patches.
- mean-pool head (no CLS token): keeps the sequence length a clean
  power of two (196→... stays whatever the grid gives, but no +1
  ragged token), which keeps flash-attention tiling applicable at
  larger image/patch combinations.
- everything reuses transformer.py's EncoderLayer, so ViT inherits
  fsdp/tp logical sharding rules, bf16 compute, and the attention
  dispatcher (flash when shapes tile, XLA otherwise) for free.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import (
    ACT_HIDDEN,
    EncoderLayer,
    LayerNorm,
    TransformerConfig,
    dense,
    logical_constraint,
    param_with_axes,
)


class PatchEmbed(nn.Module):
    """Non-overlapping patches → hidden, as one MXU matmul."""

    cfg: TransformerConfig
    patch: int

    @nn.compact
    def __call__(self, images):  # [B, H, W, C]
        p = self.patch
        b, h, w, c = images.shape
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch {p}")
        gh, gw = h // p, w // p
        x = images.reshape(b, gh, p, gw, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, gh * gw, p * p * c)
        x = x.astype(self.cfg.dtype)
        return dense(self.cfg.hidden, self.cfg, ("stack", "embed"),
                     name="proj")(x)


class ViT(nn.Module):
    cfg: TransformerConfig
    patch: int = 16
    n_classes: int = 1000

    @nn.compact
    def __call__(self, images, *, train: bool = False):
        cfg = self.cfg
        x = PatchEmbed(cfg, self.patch, name="patch_embed")(images)
        n = x.shape[1]
        pos = self.param(
            "pos_embed",
            param_with_axes(nn.initializers.normal(0.02), ("seq", "embed")),
            (cfg.max_len, cfg.hidden),
            jnp.float32,
        )
        if n > cfg.max_len:
            raise ValueError(
                f"{n} patches > max_len {cfg.max_len}; raise cfg.max_len"
            )
        x = x + pos[None, :n].astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = logical_constraint(x, ACT_HIDDEN)
        for i in range(cfg.n_layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(x, train=train)
        x = LayerNorm(cfg, name="ln_final")(x)
        x = x.mean(axis=1)  # mean-pool over patches
        logits = dense(self.n_classes, cfg, ("embed", "vocab"),
                       name="head")(x)
        return logits.astype(jnp.float32)


def vit_b16(image_size: int = 224, n_classes: int = 1000, mesh=None) -> ViT:
    """ViT-Base/16 (~86M params at 224²/1000)."""
    n = (image_size // 16) ** 2
    return ViT(
        TransformerConfig(
            vocab_size=1,  # unused; classification head sizes itself
            hidden=768,
            n_heads=12,
            head_dim=64,
            n_layers=12,
            mlp_dim=3072,
            max_len=n,
            mesh=mesh,
        ),
        patch=16,
        n_classes=n_classes,
    )


def vit_tiny(image_size: int = 32, n_classes: int = 10, mesh=None, **kw) -> ViT:
    """Test-scale ViT (patch 8, 2 layers)."""
    n = (image_size // 8) ** 2
    return ViT(
        TransformerConfig(
            vocab_size=1,
            hidden=64,
            n_heads=4,
            head_dim=16,
            n_layers=2,
            mlp_dim=128,
            max_len=n,
            mesh=mesh,
            **kw,
        ),
        patch=8,
        n_classes=n_classes,
    )


def vit_loss(params, state, batch, rng, train: bool = True) -> Tuple[jax.Array, dict]:
    """Supervised classification loss (same contract as
    parallel.trainer.cross_entropy_loss; stateless model)."""
    import optax

    logits = state.apply_fn(
        {"params": params}, batch["image"], train=train, rngs={"dropout": rng}
    )
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["label"]
    ).mean()
    acc = (logits.argmax(-1) == batch["label"]).mean()
    return loss, {"metrics": {"accuracy": acc}}
