"""Multi-replica serving pool behind ONE admission queue (ISSUE 8).

``serve_lm --replicas N`` runs N continuous-batching pool replicas —
each with its own compiled programs, KV arena, and driver thread — and
this router fronts them with the single submit/result surface the
handler already speaks.  Routing is least-blocks-in-use
(``load_score()``: paged pools report live arena occupancy + queued
block demand over arena size; contiguous pools fall back to
active+queued counts), so the next request lands on real memory
headroom, not just the shortest queue.

Each replica carries a ``replica_label``: its SLO observations and
gauges export per-replica on ``/metrics``
(``serve_admission_queue_depth{replica=}`` /
``kv_blocks_free{replica=}`` — the per-replica visibility half of the
acceptance contract), while ``/slo`` merges the quantile summaries
across the replica label (utils/metrics.histogram_family_merged) so
multi-replica serving reports ONE user-facing p99 TTFT.

On this single-host box N replicas are N model copies sharing the
process (the scale-out topology without the network); under the
operator each replica is a serving-TPUJob worker pod and the router's
role is played by the shared admission queue in front of them —
the routing policy and the metrics contract are what this module
pins.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class PoolRouter:
    """N pool replicas, one admission queue, one rid namespace.

    ``pools`` are ContinuousBatchingDecoder / PagedContinuousBatching-
    Decoder instances (mixed is allowed but pointless).  Thread-safe:
    submit/result_wait may race driver threads exactly like a single
    pool's surface.

    ISSUE 11: routing is part of a request's lifecycle — with a
    ``tracer`` every submit emits a ``route`` span on the request's
    trace, tagged the chosen replica and its ``load_score`` (plus the
    full score vector), so the waterfall answers "why did THIS replica
    serve it".  The router also merges the per-replica request logs /
    arena timelines for the /requests and /debug/arena endpoints.
    """

    def __init__(self, pools: List, tracer=None):
        if not pools:
            raise ValueError("router needs at least one pool replica")
        self.pools = list(pools)
        self.tracer = tracer
        self._lock = threading.Lock()
        self._rid = 0
        #: router rid -> (pool index, pool-local rid)
        self._route: Dict[int, Tuple[int, int]] = {}

    def __len__(self) -> int:
        return len(self.pools)

    @property
    def compile_count(self) -> int:
        return sum(p.compile_count for p in self.pools)

    def load_scores(self) -> List[float]:
        return [p.load_score() for p in self.pools]

    def submit(self, prompt_ids, max_new_tokens: int, **kw) -> int:
        """Route to the least-loaded replica; returns a ROUTER rid
        (collect with this router's result/result_wait, not the
        pool's).  Validation failures raise before any routing state
        is recorded."""

        scores = self.load_scores()
        idx = min(range(len(self.pools)), key=lambda i: (scores[i], i))
        # the request's identity is settled HERE (adopted from the
        # caller or minted) so the route span and the replica's
        # lifecycle spans share one trace id
        tid = kw.get("trace_id")
        if tid is None and self.tracer is not None:
            tid = self.tracer.mint_trace_id()
            kw["trace_id"] = tid
        if self.tracer is not None:
            span = self.tracer.start_span(
                "route", trace_id=tid, attributes={
                    "replica": str(idx),
                    "load_score": round(scores[idx], 4),
                    "scores": [round(s, 4) for s in scores],
                    # ISSUE 12: the SLO tier is routing-relevant
                    # context — a preempted batch request's waterfall
                    # should show what class it competed in
                    "tier": str(kw.get("tier", "batch")),
                },
            )
            with span:
                prid = self.pools[idx].submit(
                    prompt_ids, max_new_tokens, **kw
                )
                span.set_attribute("rid", prid)
        else:
            prid = self.pools[idx].submit(prompt_ids, max_new_tokens, **kw)
        with self._lock:
            rid = self._rid
            self._rid += 1
            self._route[rid] = (idx, prid)
        return rid

    # -- merged observability reads (ISSUE 11) ---------------------------

    def request_autopsy(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The request's lifecycle record, or None.  Ids are trace
        ids, normally unique — but a client reusing an ``x-trace-id``
        can land the same id on TWO replicas (per-replica ``~rid``
        demotion never fires across logs), so matches are resolved
        newest-submit-first to honor RequestLog's latest-wins
        contract."""

        matches = [
            entry
            for p in self.pools
            if (entry := p.request_log.get(request_id)) is not None
        ]
        if not matches:
            return None
        return max(matches, key=lambda e: e.get("submit_unix", 0.0))

    def recent_requests(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first autopsies merged across every replica's log
        (the /slo merged-family pattern applied to request records)."""

        merged: List[Dict[str, Any]] = []
        for p in self.pools:
            merged.extend(p.request_log.recent(limit))
        merged.sort(key=lambda e: e.get("submit_unix", 0.0), reverse=True)
        return merged[:limit]

    def arena_snapshots(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Per-replica arena-timeline snapshots (paged replicas only —
        contiguous pools have no arena)."""

        return [
            p.timeline.snapshot(limit)
            for p in self.pools
            if getattr(p, "timeline", None) is not None
        ]

    def _lookup(self, rid: int) -> Tuple[int, int]:
        with self._lock:
            entry = self._route.get(rid)
        if entry is None:
            raise KeyError(
                f"request {rid} unknown or already collected "
                "(results evict on first read)"
            )
        return entry

    def result(self, rid: int):
        idx, prid = self._lookup(rid)
        row = self.pools[idx].result(prid)
        if row is not None:
            with self._lock:
                self._route.pop(rid, None)
        return row

    def result_wait(self, rid: int, timeout: Optional[float] = None):
        idx, prid = self._lookup(rid)
        row = self.pools[idx].result_wait(prid, timeout=timeout)
        if row is not None:
            with self._lock:
                self._route.pop(rid, None)
        return row

    def step_all(self) -> int:
        """Drive every replica one step (tests / single-threaded
        drivers); serve_lm runs one driver thread per replica
        instead.  Returns total still-active seats."""

        return sum(p.step() for p in self.pools)

    def run(self) -> None:
        """Step every replica until all queues drain (test helper)."""

        while True:
            idle = True
            for p in self.pools:
                with p._lock:
                    if p._queue or p._active:
                        idle = False
                        break
            if idle:
                return
            self.step_all()
