"""Multi-replica serving pool behind ONE admission queue (ISSUE 8).

``serve_lm --replicas N`` runs N continuous-batching pool replicas —
each with its own compiled programs, KV arena, and driver thread — and
this router fronts them with the single submit/result surface the
handler already speaks.  Routing is least-blocks-in-use
(``load_score()``: paged pools report live arena occupancy + queued
block demand over arena size; contiguous pools fall back to
active+queued counts), so the next request lands on real memory
headroom, not just the shortest queue.

Each replica carries a ``replica_label``: its SLO observations and
gauges export per-replica on ``/metrics``
(``serve_admission_queue_depth{replica=}`` /
``kv_blocks_free{replica=}`` — the per-replica visibility half of the
acceptance contract), while ``/slo`` merges the quantile summaries
across the replica label (utils/metrics.histogram_family_merged) so
multi-replica serving reports ONE user-facing p99 TTFT.

On this single-host box N replicas are N model copies sharing the
process (the scale-out topology without the network); under the
operator each replica is a serving-TPUJob worker pod and the router's
role is played by the shared admission queue in front of them —
the routing policy and the metrics contract are what this module
pins.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class _NullSpan:
    """No-op stand-in for a route span when the router is untraced —
    the call sites keep one shape (enter, set_attribute, set_error)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attribute(self, *a, **k):
        pass

    def set_error(self, *a, **k):
        pass


_NULL_SPAN = _NullSpan()


class PoolRouter:
    """N pool replicas, one admission queue, one rid namespace.

    ``pools`` are ContinuousBatchingDecoder / PagedContinuousBatching-
    Decoder instances (mixed is allowed but pointless).  Thread-safe:
    submit/result_wait may race driver threads exactly like a single
    pool's surface.

    ISSUE 11: routing is part of a request's lifecycle — with a
    ``tracer`` every submit emits a ``route`` span on the request's
    trace, tagged the chosen replica and its ``load_score`` (plus the
    full score vector), so the waterfall answers "why did THIS replica
    serve it".  The router also merges the per-replica request logs /
    arena timelines for the /requests and /debug/arena endpoints.
    """

    #: ceiling on one prefill-publish handshake: a dead prefill driver
    #: thread must degrade to the decode replica RECOMPUTING (the
    #: documented failure semantics), not hang every multi-block
    #: request's submit thread forever on result_wait(None)
    PUBLISH_TIMEOUT_S = 120.0

    def __init__(self, pools: List, tracer=None,
                 publish_timeout: Optional[float] = None):
        if not pools:
            raise ValueError("router needs at least one pool replica")
        self.pools = list(pools)
        self.tracer = tracer
        self.publish_timeout = (
            self.PUBLISH_TIMEOUT_S if publish_timeout is None
            else float(publish_timeout)
        )
        self._lock = threading.Lock()
        self._rid = 0
        #: router rid -> (pool index, pool-local rid)
        self._route: Dict[int, Tuple[int, int]] = {}
        # -- phase roles (ISSUE 13): a fleet with any "prefill" replica
        # is DISAGGREGATED — prompts chunk-prefill on a prefill replica
        # (publishing blocks into the shared fabric) and decode on a
        # decode/unified replica that maps the published chain,
        # pulling only the missing tail.  Roles are read off the pools
        # themselves; the fleet must be able to serve both phases.
        self.prefill_idx = [
            i for i, p in enumerate(self.pools)
            if getattr(p, "role", "unified") == "prefill"
        ]
        self.decode_idx = [
            i for i, p in enumerate(self.pools)
            if getattr(p, "role", "unified") != "prefill"
        ]
        self.disaggregated = bool(self.prefill_idx)
        if self.disaggregated:
            if not self.decode_idx:
                raise ValueError(
                    "a disaggregated fleet needs at least one decode/"
                    "unified replica — prefill replicas never decode"
                )
            fabrics = {
                id(getattr(self.pools[i], "fabric", None))
                for i in self.prefill_idx + self.decode_idx
            }
            if None in {
                getattr(self.pools[i], "fabric", None)
                for i in self.prefill_idx
            } or len(fabrics) != 1:
                raise ValueError(
                    "disaggregated replicas must share ONE prefix-cache "
                    "fabric (the migration transport) — construct every "
                    "replica with the same fabric="
                )

    def __len__(self) -> int:
        return len(self.pools)

    @property
    def compile_count(self) -> int:
        return sum(p.compile_count for p in self.pools)

    def load_scores(self) -> List[float]:
        return [p.load_score() for p in self.pools]

    def _route_span(self, tid, **attrs):
        """A ``route`` span on the request's trace (a no-op span when
        untraced).  ISSUE 13: every route span carries ``phase`` and
        ``role`` attributes — the waterfall answers not just "which
        replica" but "which replica FOR WHICH PHASE"."""

        if self.tracer is None:
            return _NULL_SPAN
        return self.tracer.start_span("route", trace_id=tid,
                                      attributes=attrs)

    def _replica_name(self, idx: int) -> str:
        return self.pools[idx].replica_label or str(idx)

    def submit(self, prompt_ids, max_new_tokens: int, **kw) -> int:
        """Route to the least-loaded replica; returns a ROUTER rid
        (collect with this router's result/result_wait, not the
        pool's).  Validation failures raise before any routing state
        is recorded.

        Disaggregated fleets (ISSUE 13) run the two-phase handshake:
        the prompt chunk-prefills on the least-prefill-loaded PREFILL
        replica, which publishes its finished blocks into the fabric
        (this call BLOCKS until that prefill completes — the prefill
        replica's driver thread must be running); the request then
        submits to the least-decode-loaded DECODE replica, whose
        admission maps the published chain copy-free on local hits and
        pulls only the missing tail (``migrate_in``).  The decode
        pool's SLO clocks are backdated to THIS call's entry, so TTFT
        spans the whole handshake."""

        # the request's identity is settled HERE (adopted from the
        # caller or minted) so the route span and the replicas'
        # lifecycle spans share one trace id
        tid = kw.get("trace_id")
        if tid is None and self.tracer is not None:
            tid = self.tracer.mint_trace_id()
            kw["trace_id"] = tid
        if self.disaggregated:
            idx, prid = self._submit_disaggregated(
                prompt_ids, max_new_tokens, kw
            )
        else:
            scores = self.load_scores()
            idx = min(range(len(self.pools)), key=lambda i: (scores[i], i))
            span = self._route_span(
                tid,
                replica=str(idx),
                load_score=round(scores[idx], 4),
                scores=[round(s, 4) for s in scores],
                # ISSUE 12: the SLO tier is routing-relevant context —
                # a preempted batch request's waterfall should show
                # what class it competed in
                tier=str(kw.get("tier", "batch")),
                phase="unified",
                role=getattr(self.pools[idx], "role", "unified"),
            )
            with span:
                prid = self.pools[idx].submit(
                    prompt_ids, max_new_tokens, **kw
                )
                span.set_attribute("rid", prid)
            if tid is not None:
                # both phases ran on the one replica — attribute both
                self.pools[idx].request_log.annotate(
                    tid,
                    prefill_replica=self._replica_name(idx),
                    decode_replica=self._replica_name(idx),
                )
        with self._lock:
            rid = self._rid
            self._rid += 1
            self._route[rid] = (idx, prid)
        return rid

    def _submit_disaggregated(self, prompt_ids, max_new_tokens: int,
                              kw) -> Tuple[int, int]:
        """(decode pool index, pool-local rid) for one request through
        the prefill→fabric→decode handshake."""

        t0, t0m = time.perf_counter(), time.monotonic()
        tid = kw.get("trace_id")
        tier = str(kw.get("tier", "batch"))
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        pi = min(
            self.prefill_idx,
            key=lambda i: (self.pools[i].load_components()["prefill"], i),
        )
        di = min(
            self.decode_idx,
            key=lambda i: (self.pools[i].load_components()["decode"], i),
        )
        ppool, dpool = self.pools[pi], self.pools[di]
        # prompts with no full block strictly before their final token
        # have nothing publishable the decode side could map — they
        # skip the prefill phase entirely (short prompts never pay the
        # handshake)
        usable = (int(prompt.size) - 1) // ppool.block_size
        if usable > 0:
            span = self._route_span(
                tid, phase="prefill", role="prefill",
                replica=self._replica_name(pi), tier=tier,
                load_score=round(
                    ppool.load_components()["prefill"], 4
                ),
            )
            with span:
                try:
                    res = ppool.publish_to_fabric(
                        prompt, tier=tier, trace_id=tid,
                        timeout=self.publish_timeout,
                    )
                    span.set_attribute("published", res["published"])
                except Exception as exc:
                    # failure semantics (docs/ARCHITECTURE.md): a
                    # prefill replica dying mid-publish must not fail
                    # the request — the decode replica recomputes
                    # whatever never reached the fabric.  Counted so a
                    # sick prefill class is visible before it becomes
                    # a latency regression.
                    if ppool.metrics is not None:
                        ppool.metrics.inc(
                            "serve_fabric_publish_failures_total",
                            model=ppool.model_label,
                        )
                    span.set_error(repr(exc))
        span = self._route_span(
            tid, phase="decode", role=getattr(dpool, "role", "unified"),
            replica=self._replica_name(di), tier=tier,
            load_score=round(dpool.load_components()["decode"], 4),
        )
        with span:
            prid = dpool.submit(
                prompt, max_new_tokens,
                t_submit=t0, t_submit_mono=t0m, **kw,
            )
            span.set_attribute("rid", prid)
        if tid is not None:
            dpool.request_log.annotate(
                tid,
                prefill_replica=self._replica_name(pi) if usable > 0
                else self._replica_name(di),
                decode_replica=self._replica_name(di),
            )
        return di, prid

    # -- merged observability reads (ISSUE 11) ---------------------------

    def request_autopsy(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The request's lifecycle record, or None.  Ids are trace
        ids, normally unique — but a client reusing an ``x-trace-id``
        can land the same id on TWO replicas (per-replica ``~rid``
        demotion never fires across logs), so matches are resolved
        newest-submit-first to honor RequestLog's latest-wins
        contract."""

        matches = [
            entry
            for p in self.pools
            if (entry := p.request_log.get(request_id)) is not None
        ]
        if not matches:
            return None
        return max(matches, key=lambda e: e.get("submit_unix", 0.0))

    def recent_requests(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first autopsies merged across every replica's log
        (the /slo merged-family pattern applied to request records)."""

        merged: List[Dict[str, Any]] = []
        for p in self.pools:
            merged.extend(p.request_log.recent(limit))
        merged.sort(key=lambda e: e.get("submit_unix", 0.0), reverse=True)
        return merged[:limit]

    def arena_snapshots(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Per-replica arena-timeline snapshots (paged replicas only —
        contiguous pools have no arena)."""

        return [
            p.timeline.snapshot(limit)
            for p in self.pools
            if getattr(p, "timeline", None) is not None
        ]

    def _lookup(self, rid: int) -> Tuple[int, int]:
        with self._lock:
            entry = self._route.get(rid)
        if entry is None:
            raise KeyError(
                f"request {rid} unknown or already collected "
                "(results evict on first read)"
            )
        return entry

    def result(self, rid: int):
        idx, prid = self._lookup(rid)
        row = self.pools[idx].result(prid)
        if row is not None:
            with self._lock:
                self._route.pop(rid, None)
        return row

    def result_wait(self, rid: int, timeout: Optional[float] = None):
        idx, prid = self._lookup(rid)
        row = self.pools[idx].result_wait(prid, timeout=timeout)
        if row is not None:
            with self._lock:
                self._route.pop(rid, None)
        return row

    def step_all(self) -> int:
        """Drive every replica one step (tests / single-threaded
        drivers); serve_lm runs one driver thread per replica
        instead.  Returns total still-active seats."""

        return sum(p.step() for p in self.pools)

    def run(self) -> None:
        """Step every replica until all queues drain (test helper)."""

        while True:
            idle = True
            for p in self.pools:
                with p._lock:
                    if p._queue or p._active:
                        idle = False
                        break
            if idle:
                return
            self.step_all()
