"""LoRA fine-tuning: low-rank adapters over frozen base weights.

TPU-first shape: adapters merge into the base kernels INSIDE the
jitted step — ``W_eff = W + (alpha/r)·(A@B)`` — so the model's hot
matmuls stay exactly the dense MXU ops they were (no extra per-token
matmul chain, no dynamic control flow).  The merge costs I·r·O flops
per kernel per step: at rank 8 that is ~r/tokens of the main matmul's
cost — noise.  Gradients flow only to A/B because only they are
trainable arguments; the base tree rides the jaxpr as constants.

The integration is a WRAPPER, not Trainer surgery:

    lora = LoraModel(model, base_params, rank=8)
    trainer = Trainer(lora, cfg, mesh, loss, batch, init_args=...,
                      shardings="fsdp")

`LoraModel.init` returns ONLY the adapter tree as "params", so the
Trainer's optimizer state, checkpoints, and donation all scope to the
adapters — an adapter checkpoint is a few hundred KB for a model whose
base is GBs (the classic LoRA deployment story).  `merge_lora` bakes
trained adapters back into a full tree for export/serving (the merged
tree serves through every existing path: generate, the batching pool,
int8 quantization, speculative decode).

Selection mirrors ops/quant.py: leaves named ``kernel`` with >= 2 dims
and >= ``min_size`` elements (all-but-last axes are the input side).
The reference (SURVEY.md §0) has no fine-tuning story — this is a
beyond-reference capability.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

DEFAULT_MIN_SIZE = 4096


def _leaf_name(path) -> str:
    for entry in reversed(path):
        k = getattr(entry, "key", None)
        if isinstance(k, str):
            return k
    return ""


def _path_key(path) -> str:
    """Stable string key for a param path ('.value' boxes skipped)."""

    parts = []
    for entry in path:
        k = getattr(entry, "key", None)
        if isinstance(k, str):
            parts.append(k)
    return "/".join(parts)


def lora_init(
    base_params,
    rng,
    rank: int = 8,
    *,
    min_size: int = DEFAULT_MIN_SIZE,
) -> Dict[str, Dict[str, Any]]:
    """Adapter tree {path_key: {"a": [I,r], "b": [r,O]}} for every
    selected kernel.  A ~ N(0, 0.02), B = 0 — the delta starts at
    exactly zero, so step 0 reproduces the base model bit-for-bit."""

    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    adapters: Dict[str, Dict[str, Any]] = {}
    leaves = jax.tree_util.tree_leaves_with_path(base_params)
    keys = jax.random.split(rng, max(1, len(leaves)))
    for (path, leaf), key in zip(leaves, keys):
        if (
            _leaf_name(path) == "kernel"
            and getattr(leaf, "ndim", 0) >= 2
            and leaf.size >= min_size
        ):
            shape = leaf.shape
            i = 1
            for d in shape[:-1]:
                i *= d
            o = shape[-1]
            adapters[_path_key(path)] = {
                "a": (jax.random.normal(key, (i, rank), jnp.float32) * 0.02),
                "b": jnp.zeros((rank, o), jnp.float32),
            }
    if not adapters:
        raise ValueError(
            "no kernels selected for LoRA — check min_size vs the "
            "model's layer sizes"
        )
    return adapters


def merge_lora(base_params, adapters, *, alpha: float = 16.0):
    """Base tree with ``W + (alpha/r)·(A@B)`` at adapted kernels.
    Call INSIDE jit (LoraModel.apply does) — XLA schedules the tiny
    rank-r matmuls alongside everything else."""

    def f(path, leaf):
        ab = adapters.get(_path_key(path))
        if ab is None:
            return leaf
        rank = ab["a"].shape[-1]
        delta = (ab["a"] @ ab["b"]).reshape(leaf.shape) * (alpha / rank)
        return (leaf.astype(jnp.float32) + delta).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(f, base_params)


class LoraModel:
    """Duck-typed flax-module stand-in whose "params" are the adapters.

    Works anywhere the Trainer expects a model: ``init`` returns the
    adapter tree, ``apply`` merges and delegates.  The base tree is
    captured — under jit it becomes constants, never traced arguments,
    so the optimizer/donation/checkpoint surface is adapters-only.
    """

    def __init__(
        self,
        model,
        base_params,
        rank: int = 8,
        alpha: float = 16.0,
        min_size: int = DEFAULT_MIN_SIZE,
    ):
        self.model = model
        self.base_params = base_params
        self.rank = rank
        self.alpha = alpha
        self.min_size = min_size
        # the wrapped family's config rides along (decode/export paths
        # read model.cfg)
        self.cfg = getattr(model, "cfg", None)

    def init(self, rng, *args, **kwargs):
        return {
            "params": lora_init(
                self.base_params, rng, self.rank, min_size=self.min_size
            )
        }

    def apply(self, variables, *args, **kwargs):
        merged = merge_lora(
            self.base_params, variables["params"], alpha=self.alpha
        )
        rest = {k: v for k, v in variables.items() if k != "params"}
        return self.model.apply({"params": merged, **rest}, *args, **kwargs)

    def merged_params(self, adapters):
        """Full params with the trained adapters baked in — feed to
        export_params / generate / quantize_tree / serving."""

        return merge_lora(self.base_params, adapters, alpha=self.alpha)
