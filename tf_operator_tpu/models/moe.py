"""Mixture-of-Experts causal LM — expert parallelism over the ep axis.

The reference has no MoE story (SURVEY.md §2b: EP "absent"); this
framework makes the ep mesh axis real.  The design is the TPU-idiomatic
dense-dispatch MoE (Switch/Mesh-TF style): expert FFN weights are
stacked on a leading logical ``expert`` axis (→ ep via
parallel/sharding.py LOGICAL_RULES), tokens are routed top-2 into fixed
per-expert capacity buckets with einsum dispatch/combine tensors, and
XLA turns the resharding between token layout ([batch, seq, ...]) and
expert layout ([expert, ...]) into all-to-alls over ICI.  Everything is
static-shaped — no gather/scatter with data-dependent sizes — so the
whole block jits and tiles onto the MXU.

Load-balance + router-z auxiliary losses are sowed into the ``losses``
collection; use `moe_lm_loss` (exported) instead of plain lm_loss so
they reach the optimizer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.transformer import (
    ACT_HIDDEN,
    Embed,
    LayerNorm,
    MultiHeadAttention,
    TransformerConfig,
    logical_constraint,
    param_with_axes,
)


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    base: TransformerConfig
    num_experts: int = 8
    capacity_factor: float = 2.0
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3


class MoeMlp(nn.Module):
    """Top-2 routed expert FFNs with fixed capacity buckets.

    Token layout [B, S, H] → dispatch einsum → expert layout
    [E, B, C, H] (E sharded over ep) → stacked FFN → combine einsum
    back.  Dropped tokens (over capacity) pass through the residual
    only, as in Switch Transformer.
    """

    moe: MoeConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.moe.base
        n_exp = self.moe.num_experts
        b, s, h = x.shape
        if cfg.decode:
            # cached decode (models/decode.py): DROPLESS routing.  Each
            # token takes at most one slot per expert (top-1 and top-2
            # are distinct experts), so capacity = s admits the worst
            # case at both prefill (s = prompt) and step (s = 1) —
            # serving must not silently drop tokens the way training's
            # fixed-capacity buckets may (VERDICT r3 weak #6).
            capacity = s
        else:
            capacity = max(int(2 * s * self.moe.capacity_factor / n_exp), 4)

        # router runs in float32 — routing decisions are precision-sensitive
        router_logits = nn.DenseGeneral(
            n_exp,
            dtype=jnp.float32,
            use_bias=False,
            kernel_init=param_with_axes(nn.initializers.lecun_normal(), ("embed", "expert")),
            name="router",
        )(x.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)  # [B,S,E]

        gate1 = jnp.max(probs, axis=-1)
        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = jax.nn.one_hot(idx1, n_exp, dtype=probs.dtype)  # [B,S,E]
        probs_wo1 = probs * (1.0 - mask1)
        gate2 = jnp.max(probs_wo1, axis=-1)
        idx2 = jnp.argmax(probs_wo1, axis=-1)
        mask2 = jax.nn.one_hot(idx2, n_exp, dtype=probs.dtype)

        # auxiliary losses: load balance (Switch eq. 4) over the top-1
        # route, router z-loss for logit stability
        frac_tokens = jnp.mean(mask1, axis=(0, 1))  # [E]
        frac_probs = jnp.mean(probs, axis=(0, 1))  # [E]
        aux = n_exp * jnp.sum(frac_tokens * frac_probs)
        z = jnp.mean(jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2)
        self.sow(
            "losses",
            "moe_aux",
            self.moe.aux_loss_weight * aux + self.moe.z_loss_weight * z,
            reduce_fn=lambda a, b: a + b,
            init_fn=lambda: jnp.zeros((), jnp.float32),
        )

        # capacity bucketing: position of each token within its expert,
        # scanning the sequence; second route queues behind the first
        pos1 = jnp.cumsum(mask1, axis=1) * mask1 - mask1  # [B,S,E]
        count1 = jnp.sum(mask1, axis=1, keepdims=True)  # [B,1,E]
        pos2 = (jnp.cumsum(mask2, axis=1) + count1) * mask2 - mask2
        keep1 = mask1 * (pos1 < capacity)
        keep2 = mask2 * (pos2 < capacity)

        # renormalise surviving gates so combine weights sum to <=1
        denom = gate1 * jnp.sum(keep1, -1) + gate2 * jnp.sum(keep2, -1) + 1e-9
        gate1 = gate1 / denom
        gate2 = gate2 / denom

        # positions are float cumsums; -1 (unrouted) one-hots to all-zero
        onehot_pos1 = jax.nn.one_hot(
            pos1.astype(jnp.int32), capacity, dtype=probs.dtype
        )  # [B,S,E,C]
        onehot_pos2 = jax.nn.one_hot(pos2.astype(jnp.int32), capacity, dtype=probs.dtype)
        combine = (
            gate1[..., None, None] * keep1[..., None] * onehot_pos1
            + gate2[..., None, None] * keep2[..., None] * onehot_pos2
        )  # [B,S,E,C]
        dispatch = (combine > 0.0).astype(cfg.dtype)

        # token layout -> expert layout (all-to-all over ep under GSPMD)
        expert_in = jnp.einsum("bsec,bsh->ebch", dispatch, x.astype(cfg.dtype))
        expert_in = logical_constraint(
            expert_in, ("expert", "batch", "cap", "act_embed")
        )

        wi = self.param(
            "wi",
            param_with_axes(nn.initializers.lecun_normal(), ("expert", "embed", "mlp")),
            (n_exp, h, cfg.mlp_dim),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            param_with_axes(nn.initializers.lecun_normal(), ("expert", "mlp", "embed")),
            (n_exp, cfg.mlp_dim, h),
            jnp.float32,
        )
        hdn = jnp.einsum("ebch,ehm->ebcm", expert_in, wi.astype(cfg.dtype))
        hdn = logical_constraint(hdn, ("expert", "batch", "cap", "act_mlp"))
        hdn = nn.gelu(hdn)
        expert_out = jnp.einsum("ebcm,emh->ebch", hdn, wo.astype(cfg.dtype))
        expert_out = logical_constraint(
            expert_out, ("expert", "batch", "cap", "act_embed")
        )

        # expert layout -> token layout (second all-to-all)
        out = jnp.einsum("bsec,ebch->bsh", combine.astype(cfg.dtype), expert_out)
        out = nn.Dropout(cfg.dropout, deterministic=not train)(out)
        return logical_constraint(out, ACT_HIDDEN)


class MoeDecoderLayer(nn.Module):
    """Pre-LN decoder block with a routed-MoE FFN."""

    moe: MoeConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.moe.base
        y = LayerNorm(cfg, rms=True, name="ln_self")(x)
        x = x + MultiHeadAttention(cfg, causal=True, name="self_attn")(y, train=train)
        y = LayerNorm(cfg, rms=True, name="ln_mlp")(x)
        x = x + MoeMlp(self.moe, name="moe")(y, train=train)
        return logical_constraint(x, ACT_HIDDEN)


class MoeLM(nn.Module):
    """Decoder-only LM with MoE FFN layers (every layer routed).

    Supports cached autoregressive decode (models/decode.py): the
    attention layers keep their KV caches, the learned position table
    follows the running cache index (the CausalLM pattern), and the
    router switches to dropless per-token dispatch — routing is
    position-independent, so cached decode routes each token exactly as
    a full-context forward would.
    """

    SUPPORTS_DECODE = True

    moe: MoeConfig

    @property
    def cfg(self) -> TransformerConfig:
        return self.moe.base

    @nn.nowrap
    def decode_variant(self) -> "MoeLM":
        """The same architecture in cached-decode mode (decode.py hook
        for families whose config nests TransformerConfig)."""

        return MoeLM(
            dataclasses.replace(
                self.moe,
                base=dataclasses.replace(self.moe.base, decode=True, dropout=0.0),
            )
        )

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False):
        cfg = self.moe.base
        _, s = input_ids.shape
        embed = Embed(cfg, name="tok_embed")
        x = embed(input_ids)
        pos = self.param(
            "pos_embed",
            param_with_axes(nn.initializers.normal(0.02), ("seq", "embed")),
            (cfg.max_len, cfg.hidden),
            jnp.float32,
        )
        if cfg.decode:
            pos_idx = self.variable(
                "cache", "pos_index", lambda: jnp.array(0, jnp.int32)
            )
            i = pos_idx.value
            x = x + jax.lax.dynamic_slice(pos, (i, 0), (s, pos.shape[1]))[
                None
            ].astype(cfg.dtype)
            pos_idx.value = i + s
        else:
            x = x + pos[None, :s].astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = logical_constraint(x, ACT_HIDDEN)
        for i in range(cfg.n_layers):
            x = MoeDecoderLayer(self.moe, name=f"layer_{i}")(x, train=train)
        x = LayerNorm(cfg, rms=True, name="ln_final")(x)
        logits = embed.attend(x)
        return logits.astype(jnp.float32)


def moe_tiny(
    vocab_size: int = 1024,
    max_len: int = 256,
    num_experts: int = 4,
    mesh=None,
    **kw,
) -> MoeLM:
    return MoeLM(
        MoeConfig(
            base=TransformerConfig(
                vocab_size=vocab_size,
                hidden=128,
                n_heads=4,
                head_dim=32,
                n_layers=2,
                mlp_dim=256,
                max_len=max_len,
                mesh=mesh,
            ),
            num_experts=num_experts,
            **kw,
        )
    )


def moe_lm_loss(
    params, state, batch: Dict, rng, train: bool = True
) -> Tuple[jax.Array, Dict]:
    """Next-token loss + sowed MoE auxiliary losses."""

    logits, mutated = state.apply_fn(
        {"params": params},
        batch["input_ids"],
        train=train,
        rngs={"dropout": rng},
        mutable=["losses"],
    )
    targets = batch["input_ids"][:, 1:]
    logits = logits[:, :-1]
    xent = optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()
    aux = sum(
        jnp.sum(v) for v in jax.tree_util.tree_leaves(mutated.get("losses", {}))
    )
    acc = (logits.argmax(-1) == targets).mean()
    return xent + aux, {
        "metrics": {"token_accuracy": acc, "moe_aux_loss": aux, "xent": xent}
    }
