"""Speculative decoding: a cheap DRAFT model proposes k tokens, the
TARGET verifies them in ONE forward.  Greedy mode (temperature 0) is
provably identical to target-only greedy decode; sampling mode
(temperature > 0) uses the rejection rule (accept d w.p.
min(1, p(d)/q(d)), replace from the residual norm(max(p-q, 0))), which
samples EXACTLY the target distribution for any draft — acceptance
only changes SPEED in both modes.

Why this fits the TPU: plain decode is weight-bandwidth-bound (one
[B,1,D] matvec per weight read); verification re-reads the same
weights once per k positions as a [B,k,D] matmul — the MXU finally has
rows to chew while HBM traffic stays one weight pass.  With an
agreeable draft (a distilled/quantized sibling), tokens/step ≈ 1 + m
for m accepted proposals.

Round structure (exact-greedy; `t1` = target's known next token) —
the WHOLE round is ONE fused XLA program (`_round`), one host round
trip each, because on a tunneled chip every device call rides the
network:
  1. draft proposes d_2..d_k autoregressively from t1 (lax.scan);
  2. target applies the chunk [t1, d_2..d_k] through its KV cache
     (width-k prefill) → greedy g_1..g_k, where g_i is target's choice
     after the chunk's first i tokens;
  3. accept the longest prefix with d_{i+1} == g_i (computed ON
     DEVICE; a batch aligns on the MINIMUM acceptance — still exact
     per row, see below); emit t1, the accepted d's, and set t1 := the
     g at the first divergence (target's own correction);
  4. ROLL BACK both KV caches to the accepted length, also in-graph:
     decode attention masks strictly by `cache_index` (transformer.py's
     non-rolling cache branch: `cols <= row_pos`), so stale K/V rows
     past the index are invisible and rollback is just resetting the
     index scalars — no recompute.

Batch alignment: acceptance lengths differ per row; cache_index is one
scalar per layer, so rows align on min(m_r).  Exactness holds: rows
that agreed further simply re-derive their own next token as the
"correction" (g_m equals their d_{m+1}).

Rolling-window caches (window < max_len) are rejected — their wrap
state (cached_pos) is not index-rollbackable.  The reference
(SURVEY.md §0) has no serving story; this subsystem is
beyond-reference.  Parity: `tests/test_speculative.py` pins
speculative == plain greedy for BOTH a perfect draft (the target
itself) and an adversarial draft (random weights — worst case, still
exact, just slow).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tf_operator_tpu.models.decode import (
    _decode_variant,
    _init_cache_for,
    binary_chunks,
)
from tf_operator_tpu.ops.quant import materialize_tree


def _set_cache_index(cache, n):
    """Reset every layer's cache_index scalar to n (rollback)."""

    def f(path, leaf):
        name = ""
        for entry in reversed(path):
            k = getattr(entry, "key", None)
            if isinstance(k, str):
                name = k
                break
        if name == "cache_index":
            return jnp.asarray(n, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(f, cache)


class SpeculativeDecoder:
    """Greedy speculative decode; output == `generate(target, ...)`."""

    def __init__(
        self, target, tparams, draft, dparams, k: int = 4,
        rounds_per_call: int = 8,
    ):
        self.dtar = _decode_variant(target)
        self.ddraft = _decode_variant(draft)
        for m, who in ((self.dtar, "target"), (self.ddraft, "draft")):
            w = getattr(m.cfg, "window", None)
            if w is not None and w < m.cfg.max_len:
                raise NotImplementedError(
                    f"speculative decode does not support the rolling-"
                    f"window cache ({who}); wrap state is not "
                    "index-rollbackable"
                )
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError("target and draft must share a vocabulary")
        self.tparams = tparams
        self.dparams = dparams
        self.k = max(2, int(k))
        self.rounds_per_call = max(1, int(rounds_per_call))
        self.max_len = self.dtar.cfg.max_len
        self._fns = {}
        self.compile_count = 0
        #: acceptance telemetry: proposals accepted / proposals made
        self.proposed = 0
        self.accepted = 0

    # -- jitted pieces ---------------------------------------------------

    def _jit(self, name, fn):
        if name not in self._fns:
            self._fns[name] = jax.jit(fn)
            self.compile_count += 1
        return self._fns[name]

    def _prefill(self, model_tag, width):
        dmodel = self.dtar if model_tag == "t" else self.ddraft

        def prefill(params, cache, ids):
            logits, vars_ = dmodel.apply(
                {"params": materialize_tree(params), "cache": cache},
                ids,
                mutable=["cache"],
            )
            return vars_["cache"], logits[:, -1]  # caller samples/argmaxes

        return self._jit(("prefill", model_tag, width), prefill)

    # shared round mechanics (both acceptance modes): the final
    # proposal's K/V write — under full acceptance the committed
    # sequence includes it, and rollback must never mark an unwritten
    # cache row valid — and the width-k target verify
    def _finalize_draft(self, dparams_m, dcache, last):
        _, dvars = self.ddraft.apply(
            {"params": dparams_m, "cache": dcache},
            last[:, None],
            mutable=["cache"],
        )
        return dvars["cache"]

    def _verify_chunk(self, tparams, tcache, chunk):
        logits, tvars = self.dtar.apply(
            {"params": materialize_tree(tparams), "cache": tcache},
            chunk,
            mutable=["cache"],
        )
        return tvars["cache"], logits

    def _round(self, k: int):
        """ONE XLA program per speculation round: draft-propose scan,
        width-k target verify, device-side acceptance + cache-index
        rollback.  A host-driven round would be ~4 device calls; on a
        tunneled chip every call is a network round trip, so the fused
        round keeps speculation profitable."""

        ddraft = self.ddraft
        n_prop = k - 1

        def rnd(tparams, dparams, tcache, dcache, t1, n):
            dparams_m = materialize_tree(dparams)

            def body(carry, _):
                cache, tok = carry
                logits, vars_ = ddraft.apply(
                    {"params": dparams_m, "cache": cache},
                    tok[:, None],
                    mutable=["cache"],
                )
                nxt = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
                return (vars_["cache"], nxt), nxt

            (dcache, last), ds = lax.scan(
                body, (dcache, t1), None, length=n_prop
            )
            dcache = self._finalize_draft(dparams_m, dcache, last)
            ds = jnp.swapaxes(ds, 0, 1)  # [B, k-1]
            chunk = jnp.concatenate([t1[:, None], ds], axis=1)  # [B, k]
            tcache, logits = self._verify_chunk(tparams, tcache, chunk)
            g = jnp.argmax(logits, -1).astype(jnp.int32)  # [B, k]
            # batch-aligned acceptance length m (min over rows)
            col_ok = jnp.all(ds == g[:, : k - 1], axis=0)  # [k-1]
            m = jnp.where(
                jnp.all(col_ok), k - 1, jnp.argmin(col_ok)
            ).astype(jnp.int32)
            n_next = n + 1 + m
            tcache = _set_cache_index(tcache, n_next)
            dcache = _set_cache_index(dcache, n_next)
            t1_next = lax.dynamic_index_in_dim(g, m, axis=1, keepdims=False)
            return tcache, dcache, t1_next, m, chunk

        return rnd

    def _round_sampled(self, k: int):
        """Speculative SAMPLING round (Leviathan/Chen rejection rule):
        draft samples d_i ~ q_i, target accepts with prob
        min(1, p_i(d_i)/q_i(d_i)); at the first rejection the
        replacement draws from the RESIDUAL distribution
        norm(max(p - q, 0)).  Every committed token is therefore an
        exact sample from the target distribution at `temperature`,
        for ANY draft.  Batch rows align on the minimum acceptance:
        a row that accepted further keeps its own d at the alignment
        position (already a valid p-sample); its discarded tail is
        simply re-drawn with fresh randomness next round — still
        exact."""

        ddraft = self.ddraft
        n_prop = k - 1

        def rnd(tparams, dparams, tcache, dcache, t1, n, rng, temp):
            dparams_m = materialize_tree(dparams)

            def body(carry, _):
                cache, tok, rng = carry
                logits, vars_ = ddraft.apply(
                    {"params": dparams_m, "cache": cache},
                    tok[:, None],
                    mutable=["cache"],
                )
                ql = logits[:, 0] / temp  # [B, V]
                rng, r = jax.random.split(rng)
                d = jax.random.categorical(r, ql).astype(jnp.int32)
                return (vars_["cache"], d, rng), (d, ql)

            (dcache, last, rng), (ds, qls) = lax.scan(
                body, (dcache, t1, rng), None, length=n_prop
            )
            dcache = self._finalize_draft(dparams_m, dcache, last)
            ds = jnp.swapaxes(ds, 0, 1)  # [B, k-1]
            qls = jnp.swapaxes(qls, 0, 1)  # [B, k-1, V]
            chunk = jnp.concatenate([t1[:, None], ds], axis=1)
            tcache, logits = self._verify_chunk(tparams, tcache, chunk)
            pls = logits / temp  # [B, k, V]
            logp = jax.nn.log_softmax(pls[:, : k - 1], -1)
            logq = jax.nn.log_softmax(qls, -1)
            tok_logp = jnp.take_along_axis(logp, ds[..., None], -1)[..., 0]
            tok_logq = jnp.take_along_axis(logq, ds[..., None], -1)[..., 0]
            rng, r = jax.random.split(rng)
            u = jax.random.uniform(r, ds.shape)
            accept = jnp.log(u) < jnp.minimum(0.0, tok_logp - tok_logq)
            any_rej = jnp.any(~accept, axis=1)  # [B]
            first_rej = jnp.where(
                any_rej, jnp.argmax(~accept, axis=1), n_prop
            )  # [B]; n_prop = accepted everything
            m = jnp.min(first_rej).astype(jnp.int32)
            # replacement token at the alignment position m:
            #   first_rej == m  -> residual sample norm(max(p_m - q_m, 0))
            #   first_rej >  m  -> keep own d_m (a valid p-sample)
            #   m == k-1 (all rows accepted all): q pads to 0 so the
            #   "residual" is exactly p_{k-1} — a fresh target sample
            p_m = jax.nn.softmax(
                lax.dynamic_index_in_dim(pls, m, axis=1, keepdims=False), -1
            )  # [B, V]
            q_probs = jnp.exp(logq)  # log_softmax already computed above
            q_pad = jnp.concatenate(
                [q_probs, jnp.zeros_like(q_probs[:, :1])], axis=1
            )
            q_m = lax.dynamic_index_in_dim(q_pad, m, axis=1, keepdims=False)
            resid = jnp.clip(p_m - q_m, 0.0, None)
            ok = jnp.sum(resid, -1, keepdims=True) > 1e-9
            resid = jnp.where(ok, resid, p_m)  # numeric-zero fallback
            rng, r = jax.random.split(rng)
            corr = jax.random.categorical(
                r, jnp.log(resid + 1e-20)
            ).astype(jnp.int32)
            ds_pad = jnp.concatenate([ds, jnp.zeros_like(ds[:, :1])], axis=1)
            d_at_m = lax.dynamic_index_in_dim(ds_pad, m, axis=1, keepdims=False)
            t1_next = jnp.where(first_rej <= m, corr, d_at_m)
            n_next = n + 1 + m
            tcache = _set_cache_index(tcache, n_next)
            dcache = _set_cache_index(dcache, n_next)
            return tcache, dcache, t1_next, m, chunk, rng

        return rnd

    def _rounds(self, k: int, r: int):
        """R rounds scanned into one program: on a tunneled chip the
        per-call network round trip dominates a single round's compute,
        so rounds batch until either R rounds ran or the host's room
        budget (r <= room // k, set by the caller) is spent.  The host
        slices each round's chunk by its returned m."""

        rnd = self._round(k)

        def many(tparams, dparams, tcache, dcache, t1, n):
            def body(carry, _):
                tcache, dcache, t1, n = carry
                tcache, dcache, t1, m, chunk = rnd(
                    tparams, dparams, tcache, dcache, t1, n
                )
                return (tcache, dcache, t1, n + 1 + m), (m, chunk)

            (tcache, dcache, t1, n), (ms, chunks) = lax.scan(
                body, (tcache, dcache, t1, n), None, length=r
            )
            return tcache, dcache, t1, n, ms, chunks

        return self._jit(("rounds", k, r), many)

    def _rounds_sampled(self, k: int, r: int):
        rnd = self._round_sampled(k)

        def many(tparams, dparams, tcache, dcache, t1, n, rng, temp):
            def body(carry, _):
                tcache, dcache, t1, n, rng = carry
                tcache, dcache, t1, m, chunk, rng = rnd(
                    tparams, dparams, tcache, dcache, t1, n, rng, temp
                )
                return (tcache, dcache, t1, n + 1 + m, rng), (m, chunk)

            (tcache, dcache, t1, n, rng), (ms, chunks) = lax.scan(
                body, (tcache, dcache, t1, n, rng), None, length=r
            )
            return tcache, dcache, t1, n, rng, ms, chunks

        return self._jit(("rounds-sampled", k, r), many)

    # -- public ----------------------------------------------------------

    def generate(
        self,
        prompt_ids,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        rng=None,
    ) -> np.ndarray:
        """[B, P + N] int32.  temperature 0 = greedy, bit-identical to
        greedy `generate` on the target (same decode-variant code
        path); temperature > 0 = exact speculative SAMPLING from the
        target distribution (rejection rule — see _round_sampled)."""

        prompt = jnp.asarray(prompt_ids, jnp.int32)
        b, p = prompt.shape
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature sampling needs an explicit rng key")
        if p + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}"
            )
        sampled = temperature > 0.0
        temp = jnp.float32(temperature if sampled else 1.0)
        if rng is None:
            rng = jax.random.PRNGKey(0)  # greedy: never consumed

        def pick(logits, r):
            if not sampled:
                return jnp.argmax(logits, -1).astype(jnp.int32)
            return jax.random.categorical(r, logits / temp).astype(jnp.int32)

        tcache = _init_cache_for(self.dtar, b)
        dcache = _init_cache_for(self.ddraft, b)
        last = None
        off = 0
        for width in binary_chunks(p):
            ids = prompt[:, off : off + width]
            tcache, last = self._prefill("t", width)(self.tparams, tcache, ids)
            dcache, _ = self._prefill("d", width)(self.dparams, dcache, ids)
            off += width
        rng, r0 = jax.random.split(rng)
        t1 = pick(last, r0)
        n = p  # committed sequence length in both caches
        emitted = []  # list of [B] np arrays
        while len(emitted) < max_new_tokens:
            # cap the chunk so the verify never writes past max_len
            room = self.max_len - n
            k = min(self.k, room)
            if k < 2:  # no space to speculate: plain target steps
                tcache, last = self._prefill("t", 1)(
                    self.tparams, tcache, t1[:, None]
                )
                emitted.append(np.asarray(t1))
                n += 1
                rng, r = jax.random.split(rng)
                t1 = pick(last, r)
                continue
            # R rounds per device call; power-of-2 bucket bounds the
            # compile count.  r <= room // k guarantees no cache
            # overrun even under full acceptance (each round commits
            # at most k tokens).
            remaining = max_new_tokens - len(emitted)
            r = max(1, min(self.rounds_per_call, room // k, remaining))
            r = 1 << (r.bit_length() - 1)
            if sampled:
                rng, sub = jax.random.split(rng)
                (tcache, dcache, t1, n_dev, _, ms, chunks) = (
                    self._rounds_sampled(k, r)(
                        self.tparams, self.dparams, tcache, dcache, t1,
                        jnp.asarray(n, jnp.int32), sub, temp,
                    )
                )
            else:
                tcache, dcache, t1, n_dev, ms, chunks = self._rounds(k, r)(
                    self.tparams, self.dparams, tcache, dcache, t1,
                    jnp.asarray(n, jnp.int32),
                )
            ms_h = np.asarray(ms)
            chunks_h = np.asarray(chunks)  # [r, B, k]
            for rr in range(r):
                m = int(ms_h[rr])
                self.proposed += (k - 1) * b
                self.accepted += m * b
                for i in range(1 + m):  # t1 then the accepted proposals
                    emitted.append(chunks_h[rr][:, i])
            n = int(n_dev)
        toks = np.stack(emitted[:max_new_tokens], axis=1)
        return np.concatenate([np.asarray(prompt), toks], axis=1)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0
