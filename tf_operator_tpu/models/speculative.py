"""Speculative decoding: a cheap DRAFT model proposes k tokens, the
TARGET verifies them in ONE forward.  Greedy mode (temperature 0)
matches target-only greedy decode exactly up to floating-point
tie-breaking: the width-k verify tiles its matmuls differently from
width-1 decode, so logits that are near-exact ties can argmax-flip
between the two computation orders (the parity tests train fixtures
away from ties).  Sampling mode
(temperature > 0) uses the rejection rule (accept d w.p.
min(1, p(d)/q(d)), replace from the residual norm(max(p-q, 0))), which
samples EXACTLY the target distribution for any draft — acceptance
only changes SPEED in both modes.

Why this fits the TPU: plain decode is weight-bandwidth-bound (one
[B,1,D] matvec per weight read); verification re-reads the same
weights once per k positions as a [B,k,D] matmul — the MXU finally has
rows to chew while HBM traffic stays one weight pass.  With an
agreeable draft (a distilled/quantized sibling), tokens/step ≈ 1 + m
for m accepted proposals.

Round structure (exact-greedy; `t1` = target's known next token) —
the WHOLE round is ONE fused XLA program (`_round`), one host round
trip each, because on a tunneled chip every device call rides the
network:
  1. draft proposes d_2..d_k autoregressively from t1 (lax.scan);
  2. target applies the chunk [t1, d_2..d_k] through its KV cache
     (width-k prefill) → greedy g_1..g_k, where g_i is target's choice
     after the chunk's first i tokens;
  3. accept the longest prefix with d_{i+1} == g_i (computed ON
     DEVICE, per row); emit t1, the accepted d's, and set t1 := the
     g at the first divergence (target's own correction);
  4. ROLL BACK both KV caches to the accepted length, also in-graph:
     decode attention masks strictly by `cache_index` (transformer.py's
     non-rolling cache branch: `cols <= row_pos`), so stale K/V rows
     past the index are invisible and rollback is just resetting the
     index scalars — no recompute.

Per-row rollback (VERDICT r4 next #6): the KV caches are STACKED
batch-1 caches — a leading [B] axis on every leaf, including the
per-layer `cache_index` scalar, exactly the batching pool's per-slot
index mechanism (models/batching.py).  Every round runs as `jax.vmap`
of a batch-1 round over that axis (weights broadcast, so the verify's
projections still execute as one [B,k,D]×[D,F] dot on the MXU), which
gives each row its OWN acceptance length, committed position, and
correction token.  Rows never align on the batch minimum: a batch's
accepted-token count is Σ_r m_r, not B·min(m_r) — strictly more
whenever rows disagree.  The host loop tracks a per-row committed
length; chunk caps and round budgets key off the furthest row so no
row can overrun max_len.

Rolling-window caches (window < max_len) are rejected — their wrap
state (cached_pos) is not index-rollbackable.  The reference
(SURVEY.md §0) has no serving story; this subsystem is
beyond-reference.  Parity: `tests/test_speculative.py` pins
speculative == plain greedy for BOTH a perfect draft (the target
itself) and an adversarial draft (random weights — worst case, still
exact, just slow).

STATUS since ISSUE 18: this is the LEGACY batch-1 path, kept as the
rejection-rule reference and for `measure.py --section speculative`
history.  `serve_lm --speculative` no longer routes here — serving
speculation is a mode of the paged pool
(`models/batching.PagedContinuousBatchingDecoder(draft_model=...)`)
with draft KV in the shared block arena, a fused multi-query verify
(`ops/paged_attention.paged_attention_multi`), and in-graph
accept/rollback; see docs/ARCHITECTURE.md "Speculative paged
decoding".
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tf_operator_tpu.models.decode import (
    _decode_variant,
    _init_cache_for,
    binary_chunks,
    set_cache_index as _set_cache_index,  # rollback primitive, shared
)
from tf_operator_tpu.ops.quant import materialize_fn
from tf_operator_tpu.utils.metrics import DispatchLedger


class SpeculativeDecoder:
    """Speculative decode; output matches `generate(target, ...)` up to
    floating-point tie-breaking (see module docstring)."""

    def __init__(
        self, target, tparams, draft, dparams, k: int = 4,
        rounds_per_call: int = 8, ledger: "DispatchLedger | None" = None,
    ):
        #: device-dispatch accounting (phases: prefill, generate for
        #: the fused while driver, chunk for the scan driver,
        #: round/step for the host loop) — the "one dispatch +
        #: one packed fetch per generate()" claim, counted
        self.ledger = ledger if ledger is not None else DispatchLedger()
        self.dtar = _decode_variant(target)
        self.ddraft = _decode_variant(draft)
        for m, who in ((self.dtar, "target"), (self.ddraft, "draft")):
            w = getattr(m.cfg, "window", None)
            if w is not None and w < m.cfg.max_len:
                raise NotImplementedError(
                    f"speculative decode does not support the rolling-"
                    f"window cache ({who}); wrap state is not "
                    "index-rollbackable"
                )
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError("target and draft must share a vocabulary")
        self.tparams = tparams
        self.dparams = dparams
        # int8 DRAFT is the economic premise (half the HBM bytes per
        # draft step) — both models must consume QTensor natively
        self._materialize = materialize_fn(target, draft)
        self.k = max(2, int(k))
        self.rounds_per_call = max(1, int(rounds_per_call))
        #: whole-generation fused drivers (used when room allows; the
        #: host round loop takes over near max_len and remains the
        #: parity reference in tests).  use_fused=False forces the
        #: host loop.
        self.use_fused = True
        #: which fused driver.  "while" (default): the whole
        #: generation as one while_loop program, one dispatch + one
        #: packed fetch.  "scan": chunked fixed-length scans with a
        #: small host loop — built to test the r5 hypothesis that the
        #: while body defeats cross-iteration weight-DMA pipelining;
        #: the CLEAN probe refuted it (benchmarks/spec_scan_probe.py,
        #: no concurrent chip/CPU load: 128 tokens 0.98 s while vs
        #: 1.06 s scan; 512 tokens 1.04 vs 1.27 — the while body runs
        #: ~2.6-3 ms/round, same as the scan program, and the chunk
        #: driver's extra round trips are pure loss; the earlier
        #: "~75 ms/round inside while" reading was contention from a
        #: concurrently running test suite).  Kept selectable for
        #: parity testing and for re-evaluation on other hosts.
        self.fused_driver = "while"
        #: top-up chunk length for the scan driver (the first chunk is
        #: sized to the optimistic round count, bucket // k)
        self.scan_chunk_rounds = 8
        self.max_len = self.dtar.cfg.max_len
        self._fns = {}
        self.compile_count = 0
        #: acceptance telemetry: proposals accepted / proposals made
        self.proposed = 0
        self.accepted = 0
        #: the per-round counterfactual of the pre-r5 min-alignment
        #: rule (B·min_r m_r summed over rounds) — what the SAME rounds
        #: would have committed if rows still aligned on the batch
        #: minimum.  accepted > accepted_min_aligned whenever per-row
        #: rollback won tokens (VERDICT r4 next #6's "strictly more").
        self.accepted_min_aligned = 0

    # -- jitted pieces ---------------------------------------------------

    def _jit(self, name, fn):
        if name not in self._fns:
            self._fns[name] = jax.jit(fn)
            self.compile_count += 1
        return self._fns[name]

    def _stacked_cache(self, dmodel, b: int):
        """Stacked batch-1 caches: leading [B] axis on every leaf, so
        each row carries its own cache_index (the pool's per-slot
        mechanism, models/batching.py)."""

        row = _init_cache_for(dmodel, 1)
        return jax.tree_util.tree_map(lambda l: jnp.stack([l] * b), row)

    def _prefill(self, model_tag, width):
        """Vmapped prompt prefill: ids [B, width] through the stacked
        caches; returns per-row last-position logits [B, V]."""

        dmodel = self.dtar if model_tag == "t" else self.ddraft

        def prefill_row(params_m, cache, ids):  # ids [width]
            logits, vars_ = dmodel.apply(
                {"params": params_m, "cache": cache},
                ids[None, :],
                mutable=["cache"],
            )
            return vars_["cache"], logits[0, -1]

        materialize = self._materialize

        def prefill(params, caches, ids):
            return jax.vmap(prefill_row, in_axes=(None, 0, 0))(
                materialize(params), caches, ids
            )

        return self._jit(("prefill", model_tag, width), prefill)

    # shared row-level round mechanics (both acceptance modes): the
    # final proposal's K/V write — under full acceptance the committed
    # sequence includes it, and rollback must never mark an unwritten
    # cache row valid — and the width-k target verify
    def _finalize_draft_row(self, dparams_m, dcache, last):
        _, dvars = self.ddraft.apply(
            {"params": dparams_m, "cache": dcache},
            last[None, None],
            mutable=["cache"],
        )
        return dvars["cache"]

    def _verify_chunk_row(self, tparams_m, tcache, chunk):
        logits, tvars = self.dtar.apply(
            {"params": tparams_m, "cache": tcache},
            chunk[None, :],
            mutable=["cache"],
        )
        return tvars["cache"], logits[0]  # [k, V]

    def _round_row(self, k: int):
        """ONE speculation round for ONE row (batch-1 caches, scalar
        t1/n) — vmapped over the stacked row axis by _rounds, so each
        row accepts, rolls back, and corrects independently.  A
        host-driven round would be ~4 device calls; on a tunneled chip
        every call is a network round trip, so the fused round keeps
        speculation profitable."""

        ddraft = self.ddraft
        n_prop = k - 1

        def rnd(tparams_m, dparams_m, tcache, dcache, t1, n, limit):
            # per-row freeze: a row that already committed its token
            # budget (n >= limit) stops advancing — it neither moves
            # its cache index nor emits, so a fast row can't burn the
            # batch's max_len room while slow rows still need tokens
            # (its SPMD lane still computes; the results are masked)
            active = n < limit

            def body(carry, _):
                cache, tok = carry
                logits, vars_ = ddraft.apply(
                    {"params": dparams_m, "cache": cache},
                    tok[None, None],
                    mutable=["cache"],
                )
                nxt = jnp.argmax(logits[0, 0], -1).astype(jnp.int32)
                return (vars_["cache"], nxt), nxt

            # unroll: the k-1 sequential draft passes are tiny and
            # weight-DMA-bound; unrolling lets XLA overlap each pass's
            # weight streams instead of fencing at scan iteration
            # boundaries (measured: the fused driver's wall time is
            # async-DMA-bound, PROFILE.md "speculative")
            (dcache, last), ds = lax.scan(
                body, (dcache, t1), None, length=n_prop, unroll=True
            )  # ds [k-1]
            dcache = self._finalize_draft_row(dparams_m, dcache, last)
            chunk = jnp.concatenate([t1[None], ds])  # [k]
            tcache, logits = self._verify_chunk_row(tparams_m, tcache, chunk)
            g = jnp.argmax(logits, -1).astype(jnp.int32)  # [k]
            ok = ds == g[:n_prop]
            m = jnp.where(jnp.all(ok), n_prop, jnp.argmin(ok)).astype(
                jnp.int32
            )
            m = jnp.where(active, m, 0)
            n_next = n + jnp.where(active, 1 + m, 0)
            tcache = _set_cache_index(tcache, n_next)
            dcache = _set_cache_index(dcache, n_next)
            t1_next = jnp.where(
                active,
                lax.dynamic_index_in_dim(g, m, axis=0, keepdims=False),
                t1,
            )
            return tcache, dcache, t1_next, m, chunk, active

        return rnd

    def _round_row_sampled(self, k: int):
        """Speculative SAMPLING round for one row (Leviathan/Chen
        rejection rule): draft samples d_i ~ q_i, target accepts with
        prob min(1, p_i(d_i)/q_i(d_i)); at the row's first rejection
        the replacement draws from the RESIDUAL distribution
        norm(max(p - q, 0)); if the row accepted everything, the
        zero-padded q makes the "residual" exactly p_{k-1} — a fresh
        target sample.  Every committed token is an exact sample from
        the target distribution at `temperature`, for ANY draft.
        Per-row: the replacement position IS this row's own rejection
        point — no alignment case-split."""

        ddraft = self.ddraft
        n_prop = k - 1

        def rnd(tparams_m, dparams_m, tcache, dcache, t1, n, limit, rng, temp):
            # per-row freeze, same as the greedy round (see _round_row)
            active = n < limit

            def body(carry, _):
                cache, tok, rng = carry
                logits, vars_ = ddraft.apply(
                    {"params": dparams_m, "cache": cache},
                    tok[None, None],
                    mutable=["cache"],
                )
                ql = logits[0, 0] / temp  # [V]
                rng, r = jax.random.split(rng)
                d = jax.random.categorical(r, ql).astype(jnp.int32)
                return (vars_["cache"], d, rng), (d, ql)

            (dcache, last, rng), (ds, qls) = lax.scan(
                body, (dcache, t1, rng), None, length=n_prop
            )  # ds [k-1], qls [k-1, V]
            dcache = self._finalize_draft_row(dparams_m, dcache, last)
            chunk = jnp.concatenate([t1[None], ds])
            tcache, logits = self._verify_chunk_row(tparams_m, tcache, chunk)
            pls = logits / temp  # [k, V]
            logp = jax.nn.log_softmax(pls[:n_prop], -1)
            logq = jax.nn.log_softmax(qls, -1)
            tok_logp = jnp.take_along_axis(logp, ds[:, None], 1)[:, 0]
            tok_logq = jnp.take_along_axis(logq, ds[:, None], 1)[:, 0]
            rng, r = jax.random.split(rng)
            u = jax.random.uniform(r, ds.shape)
            accept = jnp.log(u) < jnp.minimum(0.0, tok_logp - tok_logq)
            any_rej = jnp.any(~accept)
            m = jnp.where(any_rej, jnp.argmax(~accept), n_prop).astype(
                jnp.int32
            )
            # replacement at this row's own position m: residual sample
            # norm(max(p_m - q_m, 0)); q zero-pads to k rows so full
            # acceptance (m == k-1) draws a fresh target sample
            p_m = jax.nn.softmax(
                lax.dynamic_index_in_dim(pls, m, axis=0, keepdims=False), -1
            )  # [V]
            q_probs = jnp.exp(logq)  # log_softmax already computed above
            q_pad = jnp.concatenate([q_probs, jnp.zeros_like(q_probs[:1])])
            q_m = lax.dynamic_index_in_dim(q_pad, m, axis=0, keepdims=False)
            resid = jnp.clip(p_m - q_m, 0.0, None)
            ok = jnp.sum(resid) > 1e-9
            resid = jnp.where(ok, resid, p_m)  # numeric-zero fallback
            rng, r = jax.random.split(rng)
            corr = jax.random.categorical(
                r, jnp.log(resid + 1e-20)
            ).astype(jnp.int32)
            m = jnp.where(active, m, 0)
            t1_next = jnp.where(active, corr, t1)
            n_next = n + jnp.where(active, 1 + m, 0)
            tcache = _set_cache_index(tcache, n_next)
            dcache = _set_cache_index(dcache, n_next)
            return tcache, dcache, t1_next, m, chunk, active, rng

        return rnd

    def _make_round_body(self, k: int, sampled: bool, width: int):
        """One speculation round as a state-dict transform, shared by
        the while-loop (`_fused`) and chunked-scan (`_fused_scan`)
        drivers.  A row at its limit is frozen in-graph (act False —
        no commit, no index advance), so running EXTRA rounds past
        all-done is semantically a no-op; that is what makes a
        fixed-length scan over rounds safe."""

        rnd_row = (
            self._round_row_sampled(k) if sampled else self._round_row(k)
        )

        def make(tparams_m, dparams_m, n0, limit, temp):
            def body(st):
                if sampled:
                    tc, dc, t1n, m, chunk, act, rngs_n = jax.vmap(
                        rnd_row, in_axes=(None, None, 0, 0, 0, 0, 0, 0, None)
                    )(
                        tparams_m, dparams_m, st["tc"], st["dc"], st["t1"],
                        st["n"], limit, st["rngs"], temp,
                    )
                else:
                    tc, dc, t1n, m, chunk, act = jax.vmap(
                        rnd_row, in_axes=(None, None, 0, 0, 0, 0, 0)
                    )(
                        tparams_m, dparams_m, st["tc"], st["dc"], st["t1"],
                        st["n"], limit,
                    )
                    rngs_n = st["rngs"]
                off = st["n"] - n0  # committed-new per row, pre-round

                def write_row(out_row, off_r, chunk_r, m_r, act_r):
                    idx = jnp.clip(off_r + jnp.arange(k), 0, width - 1)
                    keep = act_r & (jnp.arange(k) <= m_r)
                    return out_row.at[idx].set(
                        jnp.where(keep, chunk_r, out_row[idx])
                    )

                out = jax.vmap(write_row)(st["out"], off, chunk, m, act)
                n = st["n"] + jnp.where(act, 1 + m, 0)
                n_act = act.sum().astype(jnp.int32)
                m_masked = jnp.where(act, m, 0)
                m_min = jnp.min(
                    jnp.where(act, m, jnp.int32(2**30))
                ).astype(jnp.int32)
                telem = st["telem"] + jnp.where(
                    n_act > 0,
                    jnp.stack(
                        [(k - 1) * n_act, m_masked.sum(), m_min * n_act]
                    ).astype(jnp.int32),
                    jnp.zeros((3,), jnp.int32),
                )
                return {
                    "out": out, "tc": tc, "dc": dc, "n": n, "t1": t1n,
                    "rngs": rngs_n, "telem": telem,
                }

            return body

        return make

    def _fused(self, k: int, max_new: int, b: int, sampled: bool):
        """The WHOLE generation as one device program: a lax.while_loop
        over speculation rounds with an in-graph commit buffer, exited
        when every row has its budget.  One dispatch + one packed fetch
        per generate() call — the host-driven path pays ~4 tunnel round
        trips (~66 ms each, measured) per rounds_per_call block, which
        at small batch costs more than the compute it orchestrates
        (round-4/5 windows measured 0.05× plain decode; this driver is
        the fix).  Requires p + max_new + k <= max_len so cache room is
        never the binding constraint (generate() falls back to the
        host loop near max_len).

        r5 note: a chunked-scan alternative (`_fused_scan`) was built
        on the hypothesis that the while body defeats cross-iteration
        weight-DMA pipelining; the clean probe refuted it — this
        driver's rounds run at the same ~3 ms as the scan program and
        the chunk driver's extra round trips are pure loss on this
        host (benchmarks/spec_scan_probe.py; PROFILE.md "scan-driver
        experiment").  This stays the default.

        Packed return (int32): [B*(max_new+k) commit buffer, B final
        n's, proposed, accepted, min-aligned-counterfactual]."""

        width = max_new + k  # final round may overrun the budget by k-1
        materialize = self._materialize
        make_body = self._make_round_body(k, sampled, width)

        def fused(tparams, dparams, tcaches, dcaches, t1, n0, limit,
                  rngs, temp):
            body = make_body(
                materialize(tparams), materialize(dparams), n0, limit, temp
            )

            def cond(st):
                return jnp.any(st["n"] < limit)

            state = {
                "out": jnp.zeros((b, width), jnp.int32),
                "tc": tcaches, "dc": dcaches,
                "n": n0, "t1": t1,
                "rngs": rngs,
                "telem": jnp.zeros((3,), jnp.int32),
            }
            state = lax.while_loop(cond, body, state)
            return jnp.concatenate([
                state["out"].ravel(),
                state["n"].astype(jnp.int32),
                state["telem"],
            ])

        return self._jit(("fused", k, max_new, b, sampled), fused)

    def _fused_scan(self, k: int, max_new: int, b: int, sampled: bool,
                    r: int):
        """One CHUNK of the generation: r speculation rounds as a
        fixed-length lax.scan over the same round body `_fused` runs
        under its while_loop.  Built to test whether the while body
        defeats cross-iteration weight-DMA pipelining; the clean probe
        says NO on this host (both structures run ~3 ms/round —
        spec_scan_probe.py), so this driver is opt-in
        (`fused_driver="scan"`), kept as the parity alternative and
        for hosts with different while-loop scheduling.  The caller
        re-dispatches chunks until every row reports done, fetching
        only the B-length `n` vector between chunks (caches and the
        commit buffer stay device-resident in the state dict; the
        packed vector is fetched once, after the last chunk).  Rounds
        past a row's budget are in-graph no-ops, so over-scanning the
        tail chunk is safe — it costs compute, never correctness."""

        width = max_new + k
        materialize = self._materialize
        make_body = self._make_round_body(k, sampled, width)

        def chunk(tparams, dparams, state, n0, limit, temp):
            body = make_body(
                materialize(tparams), materialize(dparams), n0, limit, temp
            )
            state, _ = lax.scan(
                lambda st, _: (body(st), None), state, None, length=r
            )
            # packed is part of every chunk's graph (a cheap device
            # concat) but the host only FETCHES it after the last
            # chunk; between chunks it fetches state["n"] alone — B
            # int32s — for the done check
            packed = jnp.concatenate([
                state["out"].ravel(),
                state["n"].astype(jnp.int32),
                state["telem"],
            ])
            return state, packed

        return self._jit(("fused-scan", k, max_new, b, sampled, r), chunk)

    def _drive_scan(self, bucket: int, b: int, sampled: bool,
                    tcache, dcache, t1, n0, limit, rngs, temp):
        """Host side of the chunked-scan driver: dispatch an optimistic
        first chunk (bucket // k rounds — the minimum that can finish,
        every round commits at least one token per active row), then
        fixed-size top-up chunks until every row reports done.  Between
        chunks only the B-length `n` vector crosses the wire; the
        packed commit buffer is fetched once after the final chunk,
        and caches stay device-resident in the state pytree.  Two
        compiled programs per (k, bucket, b, sampled) worst case — r0
        and the top-up size are both deterministic.

        The loop is BOUNDED at the worst case (ADVICE r5): every round
        commits at least one token per active row, so `bucket` rounds
        total must finish every row.  Rows still unfinished past that
        mean the round body's act/freeze logic regressed — raise
        instead of dispatching device programs forever."""

        width = bucket + self.k
        state = {
            "out": jnp.zeros((b, width), jnp.int32),
            "tc": tcache, "dc": dcache,
            "n": n0, "t1": t1,
            "rngs": rngs,
            "telem": jnp.zeros((3,), jnp.int32),
        }
        r0 = max(1, -(-bucket // self.k))
        r0 = 1 << max(0, r0 - 1).bit_length()  # pow2: bounded compiles
        limit_h = np.asarray(limit)
        chunk_r = r0
        rounds_done = 0
        while True:
            fn = self._fused_scan(self.k, bucket, b, sampled, chunk_r)
            with self.ledger.dispatch("chunk", rounds=chunk_r):
                state, packed = fn(
                    self.tparams, self.dparams, state, n0, limit, temp
                )
                # between-chunk done check: fetch ONLY the B-length n
                # vector; the full packed buffer (B*(bucket+k) ints)
                # crosses the wire once, after the final chunk
                n_h = np.asarray(state["n"])
            rounds_done += chunk_r
            if (n_h >= limit_h).all():
                return np.asarray(packed)
            if rounds_done >= bucket:
                raise RuntimeError(
                    f"speculative scan driver dispatched {rounds_done} "
                    f"rounds (worst case {bucket}: every round commits "
                    f">=1 token per active row) with rows still "
                    f"unfinished (n={n_h.tolist()}, "
                    f"limit={limit_h.tolist()}) — the round body's "
                    "act/freeze logic has regressed"
                )
            chunk_r = max(1, min(self.scan_chunk_rounds, r0))

    def _rounds(self, k: int, r: int):
        """R rounds scanned into one program, each round a vmap of the
        row round over the stacked axis: on a tunneled chip the
        per-call network round trip dominates a single round's compute,
        so rounds batch until either R rounds ran or the host's room
        budget (r <= room // k, set by the caller) is spent.  The host
        slices each round's per-row chunk by its returned m."""

        rnd_row = self._round_row(k)
        materialize = self._materialize

        def many(tparams, dparams, tcaches, dcaches, t1, n, limit):
            tparams_m = materialize(tparams)
            dparams_m = materialize(dparams)

            def body(carry, _):
                tcaches, dcaches, t1, n = carry
                tcaches, dcaches, t1, m, chunk, act = jax.vmap(
                    rnd_row, in_axes=(None, None, 0, 0, 0, 0, 0)
                )(tparams_m, dparams_m, tcaches, dcaches, t1, n, limit)
                n = n + jnp.where(act, 1 + m, 0)
                return (tcaches, dcaches, t1, n), (m, chunk, act)

            (tcaches, dcaches, t1, n), (ms, chunks, acts) = lax.scan(
                body, (tcaches, dcaches, t1, n), None, length=r
            )
            return tcaches, dcaches, t1, n, ms, chunks, acts

        return self._jit(("rounds", k, r), many)

    def _rounds_sampled(self, k: int, r: int):
        rnd_row = self._round_row_sampled(k)
        materialize = self._materialize

        def many(tparams, dparams, tcaches, dcaches, t1, n, limit, rngs, temp):
            tparams_m = materialize(tparams)
            dparams_m = materialize(dparams)

            def body(carry, _):
                tcaches, dcaches, t1, n, rngs = carry
                tcaches, dcaches, t1, m, chunk, act, rngs = jax.vmap(
                    rnd_row, in_axes=(None, None, 0, 0, 0, 0, 0, 0, None)
                )(
                    tparams_m, dparams_m, tcaches, dcaches, t1, n, limit,
                    rngs, temp,
                )
                n = n + jnp.where(act, 1 + m, 0)
                return (tcaches, dcaches, t1, n, rngs), (m, chunk, act)

            (tcaches, dcaches, t1, n, rngs), (ms, chunks, acts) = lax.scan(
                body, (tcaches, dcaches, t1, n, rngs), None, length=r
            )
            return tcaches, dcaches, t1, n, rngs, ms, chunks, acts

        return self._jit(("rounds-sampled", k, r), many)

    # -- public ----------------------------------------------------------

    def generate(
        self,
        prompt_ids,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        rng=None,
    ) -> np.ndarray:
        """[B, P + N] int32.  temperature 0 = greedy, matching greedy
        `generate` on the target (same decode-variant code path) up to
        floating-point tie-breaking between the width-k and width-1
        computation orders; temperature > 0 = exact speculative
        SAMPLING from the target distribution (rejection rule — see
        _round_row_sampled)."""

        prompt = jnp.asarray(prompt_ids, jnp.int32)
        b, p = prompt.shape
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature sampling needs an explicit rng key")
        if p + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}"
            )
        sampled = temperature > 0.0
        temp = jnp.float32(temperature if sampled else 1.0)
        if rng is None:
            rng = jax.random.PRNGKey(0)  # greedy: never consumed

        def pick(logits, r):
            if not sampled:
                return jnp.argmax(logits, -1).astype(jnp.int32)
            return jax.random.categorical(r, logits / temp).astype(jnp.int32)

        tcache = self._stacked_cache(self.dtar, b)
        dcache = self._stacked_cache(self.ddraft, b)
        last = None
        off = 0
        for width in binary_chunks(p):
            ids = prompt[:, off : off + width]
            with self.ledger.dispatch("prefill", model="target", width=width):
                tcache, last = self._prefill("t", width)(
                    self.tparams, tcache, ids
                )
            with self.ledger.dispatch("prefill", model="draft", width=width):
                dcache, _ = self._prefill("d", width)(
                    self.dparams, dcache, ids
                )
            off += width
        rng, r0 = jax.random.split(rng)
        t1 = pick(last, r0)
        # per-row committed length (all rows start at the prompt; rows
        # then advance at their own acceptance rate) and per-row
        # commit ceiling: a row freezes in-graph once it has its
        # max_new_tokens, so a fast row can't burn max_len room while
        # slow rows still need tokens
        n = np.full((b,), p, np.int64)
        limit = jnp.full((b,), p + max_new_tokens, jnp.int32)
        rows = [[] for _ in range(b)]  # emitted tokens per row

        def shortest() -> int:
            return min(len(r) for r in rows)

        def active_rows():
            return [i for i in range(b) if len(rows[i]) < max_new_tokens]

        # per-row rngs for the sampled rounds (greedy never consumes)
        rngs = jax.random.split(rng, b + 1)
        rng, row_rngs = rngs[0], rngs[1:]

        # fused whole-generation driver (one dispatch + one fetch; see
        # _fused) whenever cache room can never bind: every verify
        # write fits even under full acceptance at the budget edge.
        # The program is keyed on a POWER-OF-2 budget bucket, not the
        # exact max_new_tokens — per-request budgets must not each
        # compile the largest program in the stack (same discipline as
        # the host path's round bucketing and ChunkedServingDecoder);
        # the exact budget rides in the runtime `limit` vector.
        bucket = 1 << max(0, max_new_tokens - 1).bit_length()
        if self.use_fused and p + max_new_tokens + self.k <= self.max_len:
            n0_dev = jnp.full((b,), p, jnp.int32)
            if self.fused_driver == "scan":
                packed = self._drive_scan(
                    bucket, b, sampled, tcache, dcache, t1, n0_dev,
                    limit, row_rngs, temp,
                )
            else:
                with self.ledger.dispatch("generate", bucket=bucket):
                    packed = np.asarray(
                        self._fused(self.k, bucket, b, sampled)(
                            self.tparams, self.dparams, tcache, dcache, t1,
                            n0_dev, limit, row_rngs, temp,
                        )
                    )
            w = bucket + self.k
            toks = packed[: b * w].reshape(b, w)[:, :max_new_tokens]
            telem = packed[b * w + b :]
            self.proposed += int(telem[0])
            self.accepted += int(telem[1])
            self.accepted_min_aligned += int(telem[2])
            return np.concatenate(
                [np.asarray(prompt), toks.astype(np.int32)], axis=1
            )
        while shortest() < max_new_tokens:
            # cap the chunk so no ACTIVE row's verify writes past
            # max_len (frozen rows neither commit nor count)
            room = self.max_len - int(n[active_rows()].max())
            k = min(self.k, room)
            if k < 2:  # no space to speculate: plain target steps.
                # The DRAFT cache must advance too: with per-row room
                # the loop can re-enter speculation after the crowding
                # row freezes (room is no longer monotone), and a
                # draft left behind here would propose from stale
                # context ever after — acceptance would collapse.
                with self.ledger.dispatch("step", model="target"):
                    tcache, last = self._prefill("t", 1)(
                        self.tparams, tcache, t1[:, None]
                    )
                with self.ledger.dispatch("step", model="draft"):
                    dcache, _ = self._prefill("d", 1)(
                        self.dparams, dcache, t1[:, None]
                    )
                for i in active_rows():
                    rows[i].append(int(t1[i]))
                n += 1  # device cache indexes advanced for every row
                rng, r = jax.random.split(rng)
                t1 = pick(last, r)
                continue
            # R rounds per device call; power-of-2 bucket bounds the
            # compile count.  r <= room // k guarantees no cache
            # overrun even under full acceptance (each round commits
            # at most k tokens per active row).
            remaining = max_new_tokens - shortest()
            r = max(1, min(self.rounds_per_call, room // k, remaining))
            r = 1 << (r.bit_length() - 1)
            with self.ledger.dispatch("round", rounds=r):
                if sampled:
                    (tcache, dcache, t1, n_dev, row_rngs, ms, chunks,
                     acts) = self._rounds_sampled(k, r)(
                        self.tparams, self.dparams, tcache, dcache, t1,
                        jnp.asarray(n, jnp.int32), limit, row_rngs, temp,
                    )
                else:
                    tcache, dcache, t1, n_dev, ms, chunks, acts = (
                        self._rounds(k, r)(
                            self.tparams, self.dparams, tcache, dcache, t1,
                            jnp.asarray(n, jnp.int32), limit,
                        )
                    )
                ms_h = np.asarray(ms)  # [r, B]
            chunks_h = np.asarray(chunks)  # [r, B, k]
            acts_h = np.asarray(acts)  # [r, B] bool
            for rr in range(r):
                n_act = int(acts_h[rr].sum())
                if n_act:
                    self.proposed += (k - 1) * n_act
                    self.accepted += int(ms_h[rr].sum())
                    # counterfactual of the pre-r5 alignment rule over
                    # the rows still decoding this round
                    self.accepted_min_aligned += (
                        int(ms_h[rr][acts_h[rr]].min()) * n_act
                    )
                for i in range(b):
                    if not acts_h[rr, i]:
                        continue
                    m = int(ms_h[rr, i])
                    rows[i].extend(int(t) for t in chunks_h[rr, i, : 1 + m])
            n = np.asarray(n_dev, np.int64)
        toks = np.stack(
            [np.asarray(row[:max_new_tokens], np.int32) for row in rows]
        )
        return np.concatenate([np.asarray(prompt), toks], axis=1)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0
