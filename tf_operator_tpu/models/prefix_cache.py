"""Content-addressed prefix cache — ONE store for both serving reuse
paths (ISSUE 8 satellite: the pool's block-level prefix sharing and
ChunkedServingDecoder's batch-1 snapshot reuse used to be two bespoke
stores with two eviction policies; now both are clients of this class,
and there is one ``serve_prefix_cache_{hits,misses,evictions}_total``
metric family, labeled ``{mode}``).

Keys are rolling token-hash CHAIN keys (``chain_keys``): the key of
block *i* hashes the previous block's key together with block *i*'s
tokens, so a key addresses the entire prefix up to and including its
block — two requests sharing a system prompt produce identical chain
prefixes, and a lookup walks the chain until the first miss (the
longest cached prefix).  The chunked decoder uses the degenerate
single-link chain over the whole prompt (``exact_key``) — exact-prompt
snapshot reuse is prefix caching with one maximal block.

Values are opaque to the cache:

- the paged pool stores PHYSICAL BLOCK IDS (models/kv_blocks.py).  A
  hit maps the block into the new seat's table copy-free; refcounts
  (``can_evict`` hook → allocator refcount == 1, i.e. only the cache
  itself holds the block) guarantee a shared block is never evicted —
  and therefore never reclaimed/rewritten — while any seat maps it;
- the chunked decoder stores (primed cache, last logits) snapshot
  tuples — immutable jax arrays, exact by construction.

Eviction is LRU, entry-capacity bounded (``capacity``) and/or
pressure-driven (``evict_lru(need=...)`` — the paged pool calls it
when the arena can't satisfy an admission).  Entries whose value is
still externally referenced (``can_evict`` False) are skipped, never
reclaimed.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Callable, List, Optional


def chain_keys(tokens, block_size: int) -> List[bytes]:
    """Rolling hash-chain keys for every FULL block of ``tokens``
    (host ints/np array): key_i = H(key_{i-1} || tokens[i*bs:(i+1)*bs]).
    Partial trailing blocks get no key — only final, never-rewritten
    blocks are publishable."""

    import numpy as np

    toks = np.asarray(tokens, np.int32).reshape(-1)
    keys: List[bytes] = []
    prev = b"kv-chain-v1"
    for off in range(0, (toks.size // block_size) * block_size, block_size):
        h = hashlib.sha256()
        h.update(prev)
        h.update(toks[off : off + block_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


def exact_key(arr) -> bytes:
    """Whole-array content key for exact-prompt snapshot reuse: shape
    and dtype are part of the key (raw bytes alone collide across
    reshapes — [1,4] vs [2,2] — and dtype aliases)."""

    import numpy as np

    a = np.asarray(arr)
    h = hashlib.sha256()
    h.update(repr((a.shape, a.dtype.str)).encode())
    h.update(a.tobytes())
    return h.digest()


class PrefixFabric:
    """Cross-replica prefix-cache FABRIC (ISSUE 13): one
    content-addressed HOST-side store of finished prompt blocks, keyed
    by the same rolling hash-chain keys as every replica's local
    :class:`PrefixCache` — the migration transport of disaggregated
    serving.

    Prefill replicas PUBLISH: after chunk-prefilling a prompt they
    gather its full blocks device→host (one ``migrate_out`` ledger
    dispatch) and ``put`` each block's KV content here under its chain
    key.  Decode replicas PULL: admission walks the chain, maps local
    cache hits copy-free, and for the missing tail ``get``s the host
    copies and uploads them into freshly allocated arena blocks (one
    ``migrate_in`` dispatch) — after which the blocks live in the
    decode replica's LOCAL cache and every later request maps them
    copy-free.  Two replicas never talk to each other directly; the
    fabric IS the wire, and the chain keys make the transport
    content-addressed: identical prompt prefixes on distinct replicas
    produce identical keys (property-tested, tests/test_kv_blocks.py).

    Values are opaque block records ``{"kv": <host tree, one block row
    per ndim-4 leaf>, "nbytes": int}``.  ``capacity_blocks`` bounds the
    host footprint (None = unbounded); eviction is LRU with a PIN
    guard: an entry a migration currently holds a reference on
    (``get(..., pin=True)`` → ``unpin``) is never reclaimed — the
    allocator's never-reclaim-while-mapped rule, fabric edition
    (property-tested).  Thread-safe: publishes and pulls race from
    every replica's submit/driver threads.
    """

    def __init__(self, capacity_blocks: Optional[int] = None,
                 metrics=None, model_label: str = "",
                 pin_ttl_seconds: float = 120.0, clock=None):
        import time

        self.capacity_blocks = (
            None if capacity_blocks is None else int(capacity_blocks)
        )
        self.metrics = metrics
        self.model_label = model_label or "unknown"
        #: ISSUE 17 small fix: pins are LEASES, not counts — a puller
        #: that crashes between get(pin=True) and unpin can only block
        #: eviction for this long, never forever
        self.pin_ttl_seconds = float(pin_ttl_seconds)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, Any]" = OrderedDict()
        self._pins: dict = {}  # key -> [lease deadline, ...] (monotonic)
        self.hits = 0
        self.misses = 0
        self.publishes = 0
        self.evictions = 0
        self.bytes_published = 0
        self.pin_expiries = 0
        #: bumped on every key-set change (fresh publish, eviction) —
        #: the /fabric/index change stamp peers cheap-poll against
        self.generation = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        with self._lock:
            return key in self._entries

    def get(self, key: bytes, pin: bool = False):
        """The block record for ``key`` (refreshing LRU), or None.
        ``pin=True`` takes a migration reference — the entry cannot be
        evicted until the matching :meth:`unpin` — so the uploader can
        read the record without racing an eviction."""

        with self._lock:
            rec = self._entries.get(key)
            if rec is None:
                return None
            self._entries.move_to_end(key)
            if pin:
                self._pins.setdefault(key, []).append(
                    self._clock() + self.pin_ttl_seconds
                )
            return rec

    def unpin(self, key: bytes) -> None:
        with self._lock:
            leases = self._pins.get(key)
            if leases:
                leases.pop(0)
            if not leases:
                self._pins.pop(key, None)

    def _expire_pins_locked(self, now: float) -> int:
        """Drop pin leases past their TTL (caller holds the lock) —
        the crashed-puller escape hatch: an entry whose every lease
        expired is evictable again."""

        expired = 0
        for k in list(self._pins):
            live = [d for d in self._pins[k] if d > now]
            expired += len(self._pins[k]) - len(live)
            if live:
                self._pins[k] = live
            else:
                del self._pins[k]
        self.pin_expiries += expired
        return expired

    def record(self, hit: bool) -> None:
        """Request-level hit/miss accounting (one increment per
        request however many chain links matched — the PrefixCache
        contract, mode="fabric")."""

        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
        if self.metrics is not None:
            if hit:
                self.metrics.inc(
                    "serve_prefix_cache_hits_total", mode="fabric"
                )
            else:
                self.metrics.inc(
                    "serve_prefix_cache_misses_total", mode="fabric"
                )

    def put(self, key: bytes, kv_tree: Any, nbytes: int) -> None:
        """Publish one block's host KV copy under its chain key
        (idempotent — a concurrent publisher of the same content just
        refreshes LRU).  Evicts LRU unpinned entries past the block
        cap; when every entry is pinned the fabric stays over capacity
        rather than reclaim a record a migration is reading."""

        with self._lock:
            fresh = key not in self._entries
            self._entries[key] = {"kv": kv_tree, "nbytes": int(nbytes)}
            self._entries.move_to_end(key)
            if fresh:
                self.publishes += 1
                self.bytes_published += int(nbytes)
                self.generation += 1
            evicted = 0
            if self.capacity_blocks is not None:
                self._expire_pins_locked(self._clock())
                for k in list(self._entries):
                    if len(self._entries) <= self.capacity_blocks:
                        break
                    if self._pins.get(k):
                        continue  # a LIVE lease holds it — never reclaim
                    del self._entries[k]
                    self.evictions += 1
                    self.generation += 1
                    evicted += 1
        if self.metrics is not None:
            if fresh:
                # idempotent re-publishes (two prefill replicas racing
                # on a shared prefix) must not drift this counter away
                # from snapshot()["publishes"]
                self.metrics.inc(
                    "kv_fabric_publishes_total", model=self.model_label
                )
            self.metrics.set(
                "kv_fabric_blocks", float(len(self)),
                model=self.model_label,
            )
            if evicted:
                self.metrics.inc(
                    "serve_prefix_cache_evictions_total", float(evicted),
                    mode="fabric",
                )

    def index_keys(self):
        """``(chain keys, generation)`` — the /fabric/index read
        (models/fabric_service.FabricServer)."""

        with self._lock:
            return list(self._entries.keys()), self.generation

    def snapshot(self) -> dict:
        """The observability read (rides /debug/arena on serve_lm)."""

        with self._lock:
            self._expire_pins_locked(self._clock())
            return {
                "blocks": len(self._entries),
                "capacity_blocks": self.capacity_blocks,
                "pinned": sum(1 for v in self._pins.values() if v),
                "pin_expiries": self.pin_expiries,
                "generation": self.generation,
                "hits": self.hits,
                "misses": self.misses,
                "publishes": self.publishes,
                "evictions": self.evictions,
                "bytes_published": self.bytes_published,
            }


class PrefixCache:
    """Refcount-aware LRU keyed by chain keys.  Thread-safe.

    ``capacity`` bounds entry count (None = unbounded, pressure-driven
    eviction only).  ``can_evict(value) -> bool`` gates eviction (the
    pool supplies "allocator refcount == 1"); ``on_evict(value)`` runs
    after removal (the pool releases the cache's block reference).
    Hit/miss accounting is REQUEST-level, not per-chain-link: callers
    walk the chain with ``peek`` and then ``record`` once.
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        metrics=None,
        mode: str = "pool",
        can_evict: Optional[Callable[[Any], bool]] = None,
        on_evict: Optional[Callable[[Any], None]] = None,
    ):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, Any]" = OrderedDict()
        self.capacity = capacity
        self.metrics = metrics
        self.mode = mode
        self._can_evict = can_evict
        self._on_evict = on_evict
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        with self._lock:
            return key in self._entries

    def peek(self, key: bytes):
        """Value for ``key`` (refreshing its LRU position) WITHOUT
        hit/miss accounting, or None.  Chain walks peek per link and
        ``record`` once per request."""

        with self._lock:
            if key not in self._entries:
                return None
            self._entries.move_to_end(key)
            return self._entries[key]

    def record(self, hit: bool) -> None:
        """Count one request-level hit or miss (one increment per
        served request, however many chain links matched)."""

        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
        if self.metrics is not None:
            if hit:
                self.metrics.inc(
                    "serve_prefix_cache_hits_total", mode=self.mode
                )
            else:
                self.metrics.inc(
                    "serve_prefix_cache_misses_total", mode=self.mode
                )

    def get(self, key: bytes):
        """peek + record in one call — the exact-prompt (single-link)
        client's read."""

        v = self.peek(key)
        self.record(v is not None)
        return v

    def put(self, key: bytes, value: Any) -> None:
        """Insert/refresh; evicts LRU entries past ``capacity`` (the
        refcount gate applies — an over-capacity cache whose every
        entry is mapped simply stays over capacity until seats
        retire)."""

        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
        if self.capacity is not None and len(self) > self.capacity:
            self.evict_lru(need=len(self) - self.capacity)

    def evict_lru(self, need: int = 1) -> int:
        """Evict up to ``need`` LRU entries whose values pass
        ``can_evict``; returns how many were evicted.  Entries still
        referenced are skipped (and keep their LRU position) — a
        mapped shared block survives any pressure."""

        evicted = 0
        with self._lock:
            for key in list(self._entries):
                if evicted >= need:
                    break
                value = self._entries[key]
                if self._can_evict is not None and not self._can_evict(value):
                    continue
                del self._entries[key]
                self.evictions += 1
                evicted += 1
                if self._on_evict is not None:
                    self._on_evict(value)
        if evicted and self.metrics is not None:
            self.metrics.inc(
                "serve_prefix_cache_evictions_total", float(evicted),
                mode=self.mode,
            )
        return evicted
