"""Content-addressed prefix cache — ONE store for both serving reuse
paths (ISSUE 8 satellite: the pool's block-level prefix sharing and
ChunkedServingDecoder's batch-1 snapshot reuse used to be two bespoke
stores with two eviction policies; now both are clients of this class,
and there is one ``serve_prefix_cache_{hits,misses,evictions}_total``
metric family, labeled ``{mode}``).

Keys are rolling token-hash CHAIN keys (``chain_keys``): the key of
block *i* hashes the previous block's key together with block *i*'s
tokens, so a key addresses the entire prefix up to and including its
block — two requests sharing a system prompt produce identical chain
prefixes, and a lookup walks the chain until the first miss (the
longest cached prefix).  The chunked decoder uses the degenerate
single-link chain over the whole prompt (``exact_key``) — exact-prompt
snapshot reuse is prefix caching with one maximal block.

Values are opaque to the cache:

- the paged pool stores PHYSICAL BLOCK IDS (models/kv_blocks.py).  A
  hit maps the block into the new seat's table copy-free; refcounts
  (``can_evict`` hook → allocator refcount == 1, i.e. only the cache
  itself holds the block) guarantee a shared block is never evicted —
  and therefore never reclaimed/rewritten — while any seat maps it;
- the chunked decoder stores (primed cache, last logits) snapshot
  tuples — immutable jax arrays, exact by construction.

Eviction is LRU, entry-capacity bounded (``capacity``) and/or
pressure-driven (``evict_lru(need=...)`` — the paged pool calls it
when the arena can't satisfy an admission).  Entries whose value is
still externally referenced (``can_evict`` False) are skipped, never
reclaimed.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Callable, List, Optional


def chain_keys(tokens, block_size: int) -> List[bytes]:
    """Rolling hash-chain keys for every FULL block of ``tokens``
    (host ints/np array): key_i = H(key_{i-1} || tokens[i*bs:(i+1)*bs]).
    Partial trailing blocks get no key — only final, never-rewritten
    blocks are publishable."""

    import numpy as np

    toks = np.asarray(tokens, np.int32).reshape(-1)
    keys: List[bytes] = []
    prev = b"kv-chain-v1"
    for off in range(0, (toks.size // block_size) * block_size, block_size):
        h = hashlib.sha256()
        h.update(prev)
        h.update(toks[off : off + block_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


def exact_key(arr) -> bytes:
    """Whole-array content key for exact-prompt snapshot reuse: shape
    and dtype are part of the key (raw bytes alone collide across
    reshapes — [1,4] vs [2,2] — and dtype aliases)."""

    import numpy as np

    a = np.asarray(arr)
    h = hashlib.sha256()
    h.update(repr((a.shape, a.dtype.str)).encode())
    h.update(a.tobytes())
    return h.digest()


class PrefixCache:
    """Refcount-aware LRU keyed by chain keys.  Thread-safe.

    ``capacity`` bounds entry count (None = unbounded, pressure-driven
    eviction only).  ``can_evict(value) -> bool`` gates eviction (the
    pool supplies "allocator refcount == 1"); ``on_evict(value)`` runs
    after removal (the pool releases the cache's block reference).
    Hit/miss accounting is REQUEST-level, not per-chain-link: callers
    walk the chain with ``peek`` and then ``record`` once.
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        metrics=None,
        mode: str = "pool",
        can_evict: Optional[Callable[[Any], bool]] = None,
        on_evict: Optional[Callable[[Any], None]] = None,
    ):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, Any]" = OrderedDict()
        self.capacity = capacity
        self.metrics = metrics
        self.mode = mode
        self._can_evict = can_evict
        self._on_evict = on_evict
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        with self._lock:
            return key in self._entries

    def peek(self, key: bytes):
        """Value for ``key`` (refreshing its LRU position) WITHOUT
        hit/miss accounting, or None.  Chain walks peek per link and
        ``record`` once per request."""

        with self._lock:
            if key not in self._entries:
                return None
            self._entries.move_to_end(key)
            return self._entries[key]

    def record(self, hit: bool) -> None:
        """Count one request-level hit or miss (one increment per
        served request, however many chain links matched)."""

        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
        if self.metrics is not None:
            if hit:
                self.metrics.inc(
                    "serve_prefix_cache_hits_total", mode=self.mode
                )
            else:
                self.metrics.inc(
                    "serve_prefix_cache_misses_total", mode=self.mode
                )

    def get(self, key: bytes):
        """peek + record in one call — the exact-prompt (single-link)
        client's read."""

        v = self.peek(key)
        self.record(v is not None)
        return v

    def put(self, key: bytes, value: Any) -> None:
        """Insert/refresh; evicts LRU entries past ``capacity`` (the
        refcount gate applies — an over-capacity cache whose every
        entry is mapped simply stays over capacity until seats
        retire)."""

        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
        if self.capacity is not None and len(self) > self.capacity:
            self.evict_lru(need=len(self) - self.capacity)

    def evict_lru(self, need: int = 1) -> int:
        """Evict up to ``need`` LRU entries whose values pass
        ``can_evict``; returns how many were evicted.  Entries still
        referenced are skipped (and keep their LRU position) — a
        mapped shared block survives any pressure."""

        evicted = 0
        with self._lock:
            for key in list(self._entries):
                if evicted >= need:
                    break
                value = self._entries[key]
                if self._can_evict is not None and not self._can_evict(value):
                    continue
                del self._entries[key]
                self.evictions += 1
                evicted += 1
                if self._on_evict is not None:
                    self._on_evict(value)
        if evicted and self.metrics is not None:
            self.metrics.inc(
                "serve_prefix_cache_evictions_total", float(evicted),
                mode=self.mode,
            )
        return evicted
