"""Llama-style decoder family: RoPE + RMSNorm + SwiGLU + GQA.

The reference ships no model code (SURVEY.md §0 — it is a control
plane); its *examples* cover the 2019-era TF families.  This module is
the framework's modern-decoder representative: the architecture every
current open-weights LM (llama/mistral/qwen-class) uses, built from the
same transformer blocks and logical sharding rules as the rest of the
zoo, so dp/fsdp/tp/sp(ring|ulysses) all apply unchanged.

Differences from `models/gpt.py` (GPT-2 class):
- rotary position embeddings inside attention (no learned pos table)
- RMSNorm everywhere (no biases anywhere in the network)
- SwiGLU gated MLP
- optional grouped-query attention (n_kv_heads < n_heads)
- untied LM head (separate output projection)
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax

from tf_operator_tpu.models.transformer import (
    ACT_HIDDEN,
    DecoderLayer,
    Embed,
    LayerNorm,
    QDenseGeneral,
    TransformerConfig,
    logical_constraint,
    param_with_axes,
)


class LlamaLM(nn.Module):
    """Decoder-only LM over a TransformerConfig with rope=True."""

    SUPPORTS_DECODE = True  # autoregressive: models/decode.py can drive it
    #: the whole stack routes QDenseGeneral/Embed, so the decode loops
    #: may pass a quantize_tree'd params tree straight to apply — the
    #: int8 weight feeds ops/quant_matmul per tile, no bf16 copy
    SUPPORTS_QTENSOR = True

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False, mode: str = "full"):
        """mode="full": ids -> f32 logits (the default contract).
        mode="hidden": ids -> post-ln_final hidden states [B, S, D]
        (the lm_head is not applied).  mode="head": input_ids is
        ALREADY a hidden-state tensor; apply only the lm_head.  The
        split modes exist for llama_loss_chunked, which streams the
        vocab projection + cross-entropy over sequence chunks so the
        [B, S, vocab] f32 logits tensor is never materialized (the
        trace of the 0.69-MFU wide step shows the fp32 vocab tier as
        the largest op cluster — benchmarks/PROFILE.md)."""

        if mode not in ("full", "hidden", "head"):
            raise ValueError(f"mode must be full|hidden|head, got {mode!r}")
        cfg = self.cfg
        head = QDenseGeneral(
            cfg.vocab_size,
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=param_with_axes(
                nn.initializers.normal(0.02), ("embed", "vocab")
            ),
            name="lm_head",
        )
        if mode == "head":
            return head(input_ids).astype(jnp.float32)
        x = Embed(cfg, name="tok_embed")(input_ids)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = logical_constraint(x, ACT_HIDDEN)
        for i in range(cfg.n_layers):
            x = DecoderLayer(cfg, cross=False, activation="swiglu", name=f"layer_{i}")(
                x, train=train
            )
        x = LayerNorm(cfg, rms=True, name="ln_final")(x)
        if mode == "hidden":
            return x
        # untied head (llama convention), vocab on the tp axis
        return head(x).astype(jnp.float32)


def llama_tiny(
    vocab_size: int = 1024,
    max_len: int = 256,
    mesh=None,
    n_kv_heads: Optional[int] = 2,
    **kw,
) -> LlamaLM:
    """Test-scale shape (GQA 4q:2kv by default)."""

    return LlamaLM(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=128,
            n_heads=4,
            head_dim=32,
            n_layers=2,
            mlp_dim=352,  # ~8/3 * hidden, llama convention
            max_len=max_len,
            dropout=0.0,
            mesh=mesh,
            rope=True,
            attn_bias=False,
            n_kv_heads=n_kv_heads,
            **kw,
        )
    )


def llama_7b_shape(vocab_size: int = 32000, max_len: int = 4096, mesh=None, **kw) -> LlamaLM:
    """The canonical 7B shape (for sharding/bench configs; init it on a
    mesh with fsdp/tp or it will not fit one chip)."""

    return LlamaLM(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=4096,
            n_heads=32,
            head_dim=128,
            n_layers=32,
            mlp_dim=11008,
            max_len=max_len,
            dropout=0.0,
            mesh=mesh,
            rope=True,
            attn_bias=False,
            **kw,
        )
    )


# next-token cross-entropy: identical contract and math to the GPT
# family's loss — one implementation, re-exported under the family name
from tf_operator_tpu.models.gpt import lm_loss as llama_loss  # noqa: E402


def llama_loss_chunked(
    params, state, batch, rng, train: bool = True, *, n_chunks: int = 8
):
    """Next-token loss with the vocab projection + cross-entropy
    streamed over sequence chunks (Trainer loss_fn contract, drop-in
    for llama_loss).

    Why: the full-logits path materializes an f32 [B, S, vocab] tensor
    (~1 GB at the wide bench shape) and its bwd reads it back — the
    trace of the 0.69-MFU step shows this fp32 vocab tier as the
    largest op cluster (PROFILE.md).  Here each chunk computes its
    logits + loss under jax.checkpoint, so only the chunk's hidden
    states are saved for the backward and the full logits tensor never
    exists; the checkpoint recomputes one chunk's head matmul in bwd —
    MXU flops traded for HBM round trips, and the freed memory is what
    lets bigger batches fit without remat.

    Exact same math as llama_loss up to summation order (parity test:
    tests/test_llama.py::test_chunked_loss_matches_full)."""

    ids = batch["input_ids"]
    h = state.apply_fn(
        {"params": params}, ids, train=train, rngs={"dropout": rng},
        mode="hidden",
    )
    h = h[:, :-1]
    tgt = ids[:, 1:]
    b, s, _ = h.shape
    c = max(1, min(n_chunks, s))
    while s % c:  # largest chunk count <= n_chunks that tiles S-1
        c -= 1
    hc = h.reshape(b, c, s // c, -1).swapaxes(0, 1)  # [C, B, s/C, D]
    tc = tgt.reshape(b, c, s // c).swapaxes(0, 1)

    @jax.checkpoint
    def one(args):
        hcc, tcc = args
        logits = state.apply_fn({"params": params}, hcc, mode="head")
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, tcc
        ).sum()
        acc = (logits.argmax(-1) == tcc).sum()
        return loss, acc

    losses, accs = lax.map(one, (hc, tc))
    denom = b * s
    return losses.sum() / denom, {
        "metrics": {"token_accuracy": accs.sum() / denom}
    }
