"""T5 encoder-decoder.

Parity target: BASELINE.md config 5, "T5-base JAX/Flax multi-host via
jax.distributed on a v5e-16 slice" — the one reference config that was
already JAX-shaped.  Standard T5 architecture: RMSNorm pre-LN blocks,
relative position bias (shared across layers, per T5), ReLU MLP, tied
embedding/LM head with 1/sqrt(hidden) logit scaling.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.transformer import (
    ACT_HIDDEN,
    DecoderLayer,
    Embed,
    EncoderLayer,
    LayerNorm,
    TransformerConfig,
    logical_constraint,
    param_with_axes,
)


def _relative_position_bucket(rel_pos, bidirectional: bool, num_buckets: int, max_distance: int):
    """T5's log-bucketed relative positions (public algorithm)."""

    ret = 0
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class RelativePositionBias(nn.Module):
    cfg: TransformerConfig
    bidirectional: bool = True
    num_buckets: int = 32
    max_distance: int = 128

    @nn.compact
    def __call__(self, sq: int, sk: int):
        table = self.param(
            "rel_embedding",
            param_with_axes(nn.initializers.normal(0.02), ("relpos_buckets", "heads")),
            (self.num_buckets, self.cfg.n_heads),
            jnp.float32,
        )
        ctx = jnp.arange(sq)[:, None]
        mem = jnp.arange(sk)[None, :]
        buckets = _relative_position_bucket(
            mem - ctx, self.bidirectional, self.num_buckets, self.max_distance
        )
        bias = jnp.take(table, buckets, axis=0)  # [Sq, Sk, H]
        return jnp.transpose(bias, (2, 0, 1))[None]  # [1, H, Sq, Sk]


class T5(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        encoder_ids,  # [B, Se]
        decoder_ids,  # [B, Sd]
        *,
        encoder_mask: Optional[jax.Array] = None,  # [B, Se] 1 = real
        train: bool = False,
    ):
        cfg = self.cfg
        embed = Embed(cfg, name="shared_embed")
        enc_bias = RelativePositionBias(cfg, bidirectional=True, name="enc_relpos")(
            encoder_ids.shape[1], encoder_ids.shape[1]
        )
        dec_bias = RelativePositionBias(cfg, bidirectional=False, name="dec_relpos")(
            decoder_ids.shape[1], decoder_ids.shape[1]
        )

        # encoder
        x = embed(encoder_ids)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = logical_constraint(x, ACT_HIDDEN)
        mask = None
        if encoder_mask is not None:
            mask = encoder_mask[:, None, None, :].astype(bool)
        for i in range(cfg.n_layers):
            x = EncoderLayer(cfg, rms=True, activation="relu", name=f"enc_{i}")(
                x, mask=mask, bias=enc_bias, train=train
            )
        enc = LayerNorm(cfg, rms=True, name="enc_ln_final")(x)

        # decoder
        y = embed(decoder_ids)
        y = nn.Dropout(cfg.dropout, deterministic=not train)(y)
        y = logical_constraint(y, ACT_HIDDEN)
        cross_mask = mask
        for i in range(cfg.n_layers):
            y = DecoderLayer(cfg, cross=True, name=f"dec_{i}")(
                y, enc=enc, self_bias=dec_bias, enc_mask=cross_mask, train=train
            )
        y = LayerNorm(cfg, rms=True, name="dec_ln_final")(y)
        logits = embed.attend(y) / jnp.sqrt(jnp.asarray(cfg.hidden, y.dtype))
        return logits.astype(jnp.float32)


def t5_base(vocab_size: int = 32128, mesh=None) -> T5:
    return T5(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=768,
            n_heads=12,
            head_dim=64,
            n_layers=12,
            mlp_dim=3072,
            max_len=512,
            mesh=mesh,
        )
    )


def t5_tiny(vocab_size: int = 1024, mesh=None, **kw) -> T5:
    return T5(
        TransformerConfig(
            vocab_size=vocab_size,
            hidden=128,
            n_heads=4,
            head_dim=32,
            n_layers=2,
            mlp_dim=512,
            max_len=128,
            mesh=mesh,
            **kw,
        )
    )


def seq2seq_loss(
    params, state, batch: Dict, rng, train: bool = True
) -> Tuple[jax.Array, Dict]:
    """batch: encoder_ids, decoder_ids (shifted right), targets,
    optional encoder_mask, target_mask (1 = count in loss)."""

    logits = state.apply_fn(
        {"params": params},
        batch["encoder_ids"],
        batch["decoder_ids"],
        encoder_mask=batch.get("encoder_mask"),
        train=train,
        rngs={"dropout": rng},
    )
    targets = batch["targets"]
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    tmask = batch.get("target_mask")
    if tmask is None:
        tmask = jnp.ones_like(targets)
    denom = jnp.maximum(tmask.sum(), 1)
    loss = (per_tok * tmask).sum() / denom
    acc = ((logits.argmax(-1) == targets) * tmask).sum() / denom
    return loss, {"metrics": {"token_accuracy": acc}}
