"""Cross-pod KV fabric service (ISSUE 17) — the networked half of the
prefix-cache fabric.

PR 13's :class:`~tf_operator_tpu.models.prefix_cache.PrefixFabric` is
the migration transport of disaggregated serving, but it is an
in-process object: across real pods it transports nothing.  This module
makes the shared prompt cache a FLEET property:

- :class:`FabricServer` — every serving pod exports its local fabric
  over HTTP (the ``runtime/telemetry.PodTelemetryServer`` pattern):

      GET  /fabric/index            chain-key catalog + generation stamp
      GET  /fabric/blocks/<hexkey>  one block record on the wire
      POST /fabric/publish          push-style key announcements
      GET  /healthz                 liveness

- :class:`FleetFabric` — the client tier, duck-type compatible with
  ``PrefixFabric`` so the paged pool, the pool router and serve_lm use
  it unchanged.  ``get`` resolves local-first, then pulls the block
  from a peer that advertises its chain key; ``__contains__`` answers
  fleet-wide (local OR any peer's announced index), which is what lets
  a prefill replica skip recomputing a prompt some other pod already
  published; ``put`` publishes locally and announces the key to peers.

Wire format (``/fabric/blocks``): one JSON header line —
``{"v", "key", "nbytes", "leaves": [{"shape", "dtype"}...], "sha256"}``
— then the payload: each block-row (ndim-4) leaf as an 8-byte
big-endian length prefix + raw bytes, in arena flatten order.  The
header's sha256 covers the whole payload END-TO-END: a corrupt or
short read is detected before anything touches the arena, counts
``kv_fabric_pull_failures_total{reason}`` and degrades to a miss (the
admission path recomputes the tail) — never a 500, never a poisoned
block.  Coherence is free: chain keys are content addresses, so a key
either names exactly the bytes it hashes or it does not exist.

Peer discovery is the PR 15 telemetry-port mechanics — the reconciler
allocates a port per pod and stamps it into the
``tpujob.dist/fabric-port`` annotation (``controller/reconciler.py``)
— or static ``serve_lm --fabric-peers host:port,...``.  Pulls ride
``backend/retry.fabric_pull_policy`` (tight budget: admission blocks
on this; recompute is always the fallback).

Host-side only: sockets + numpy; jax is imported lazily for pytree
flatten/unflatten of block records.
"""

from __future__ import annotations

import hashlib
import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: bump when the /fabric/blocks header or payload layout changes — a
#: version-mismatched peer reads as corrupt and degrades to recompute
WIRE_VERSION = 1

#: pull-failure taxonomy (the {reason} label): every way a remote pull
#: can fail maps to exactly one of these, and every one of them means
#: "recompute the tail", never an error surfaced to the request
PULL_FAILURE_REASONS = (
    "peer_dead",    # connection refused/reset, retry budget exhausted
    "not_found",    # stale index: peer evicted between index and pull
    "http_error",   # non-404 HTTP failure the retry policy gave up on
    "corrupt",      # content hash / header / template mismatch
    "short_read",   # payload shorter than its header claims
    "no_template",  # no arena template registered yet (pool still booting)
)


class PullError(Exception):
    """A classified remote-pull failure (``reason`` ∈
    :data:`PULL_FAILURE_REASONS`)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


def encode_block(key: bytes, rec: Dict[str, Any]) -> bytes:
    """Serialise one fabric block record for the wire: JSON header
    line + length-prefixed raw bytes of every block-row (ndim-4) leaf
    in flatten order.  The header's sha256 covers the payload."""

    import jax

    parts: List[bytes] = []
    metas: List[Dict[str, Any]] = []
    for leaf in jax.tree_util.tree_leaves(rec["kv"]):
        if getattr(leaf, "ndim", 0) != 4:
            continue
        arr = np.ascontiguousarray(leaf)
        raw = arr.tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
        metas.append({"shape": list(arr.shape), "dtype": str(arr.dtype)})
    payload = b"".join(parts)
    header = {
        "v": WIRE_VERSION,
        "key": key.hex(),
        "nbytes": int(rec["nbytes"]),
        "leaves": metas,
        "sha256": hashlib.sha256(payload).hexdigest(),
    }
    return json.dumps(header, sort_keys=True).encode() + b"\n" + payload


def decode_block(body: bytes, template) -> Tuple[Any, int]:
    """Parse a ``/fabric/blocks`` response against the registered
    arena ``template`` (treedef + per-leaf meta); returns
    ``(kv_tree, nbytes)``.  Raises :class:`PullError` with the right
    reason on any mismatch — the hash check runs BEFORE the tree is
    rebuilt, so a corrupt payload never reaches the caller."""

    import jax

    if template is None:
        raise PullError("no_template")
    treedef, leaf_meta = template
    try:
        nl = body.index(b"\n")
        header = json.loads(body[:nl])
    except (ValueError, UnicodeDecodeError) as exc:
        raise PullError("corrupt", f"unparseable header: {exc}")
    payload = body[nl + 1:]
    if int(header.get("v", 0)) != WIRE_VERSION:
        raise PullError("corrupt", f"wire version {header.get('v')!r}")
    if hashlib.sha256(payload).hexdigest() != header.get("sha256"):
        raise PullError("corrupt", "content hash mismatch")
    raws: List[Tuple[Dict[str, Any], bytes]] = []
    off = 0
    for meta in header.get("leaves", []):
        if off + 8 > len(payload):
            raise PullError("short_read", "truncated length prefix")
        (n,) = struct.unpack(">Q", payload[off: off + 8])
        off += 8
        if off + n > len(payload):
            raise PullError("short_read", f"leaf needs {n} bytes")
        raws.append((meta, payload[off: off + n]))
        off += n
    n_rows = sum(1 for is_row, _, _ in leaf_meta if is_row)
    if len(raws) != n_rows:
        raise PullError(
            "corrupt", f"{len(raws)} wire leaves, template has {n_rows}"
        )
    leaves: List[Any] = []
    it = iter(raws)
    for is_row, shape, dtype in leaf_meta:
        if not is_row:
            leaves.append(np.zeros((), dtype))
            continue
        meta, raw = next(it)
        want_shape = (1,) + tuple(shape[1:])
        want_dtype = np.dtype(dtype)
        try:
            got_dtype = np.dtype(meta.get("dtype", "V"))
        except TypeError:
            raise PullError("corrupt", f"bad dtype {meta.get('dtype')!r}")
        if tuple(meta.get("shape", ())) != want_shape or \
                got_dtype != want_dtype:
            raise PullError(
                "corrupt",
                f"leaf {meta} does not match template "
                f"{(want_shape, str(want_dtype))}",
            )
        if len(raw) != want_dtype.itemsize * int(np.prod(want_shape)):
            raise PullError("short_read", "leaf byte count mismatch")
        leaves.append(np.frombuffer(raw, want_dtype).reshape(want_shape))
    return (
        jax.tree_util.tree_unflatten(treedef, leaves),
        int(header.get("nbytes", 0)),
    )


# ---------------------------------------------------------------------------
# the client tier
# ---------------------------------------------------------------------------


class _Peer:
    """One peer's announced state (mutated under the fabric lock)."""

    def __init__(self, addr: str):
        self.addr = addr
        self.keys: set = set()
        self.generation = 0
        self.up: Optional[bool] = None  # None = never contacted
        self.last_index = 0.0  # monotonic stamp of the last index read


class FleetFabric:
    """Fleet-wide prefix fabric: a local ``PrefixFabric`` plus the HTTP
    client half of the cross-pod tier.  Duck-type compatible with
    ``PrefixFabric`` (``get``/``unpin``/``record``/``put``/
    ``__contains__``/``snapshot``), so the paged pool and pool router
    need no special casing — ``get`` just reaches further on a local
    miss, and the record it returns carries ``transport="http"`` +
    ``peer`` so the migration path can meter bytes by transport.

    Remote pulls need the arena pytree template to rebuild records
    (:meth:`register_template`, called by the pool once its arena
    exists); until then pulls degrade to misses (``reason=no_template``).
    """

    def __init__(
        self,
        local,
        peers=(),
        metrics=None,
        model_label: str = "",
        policy=None,
        request_timeout: float = 1.0,
        index_ttl_seconds: float = 2.0,
        announce_timeout: float = 1.0,
    ):
        from tf_operator_tpu.backend.retry import fabric_pull_policy

        self.local = local
        self.metrics = metrics if metrics is not None else local.metrics
        self.model_label = model_label or local.model_label
        self.policy = policy if policy is not None else fabric_pull_policy()
        self.request_timeout = float(request_timeout)
        self.index_ttl_seconds = float(index_ttl_seconds)
        self.announce_timeout = float(announce_timeout)
        self.advertise = ""  # host:port peers pull from (set after bind)
        self._lock = threading.Lock()
        self._peers: "Dict[str, _Peer]" = {
            str(a): _Peer(str(a)) for a in peers if str(a)
        }
        self._template = None  # (treedef, [(is_row, shape, dtype)...])
        self.pulls = {"hit": 0, "miss": 0, "failed": 0}
        self.pull_failures: Dict[str, int] = {}
        self.bytes_pulled = 0
        # -- push announcements: a daemon thread drains the queue so
        # put() (called under the pool lock) never blocks on a socket
        self._ann_cv = threading.Condition()
        self._ann_pending: List[bytes] = []
        self._ann_thread: Optional[threading.Thread] = None
        self._ann_stop = False

    # -- PrefixFabric surface ----------------------------------------------

    def __len__(self) -> int:
        return len(self.local)

    def __contains__(self, key: bytes) -> bool:
        """FLEET-wide membership: local, or advertised by any peer
        (refreshing stale peer indexes on a miss).  This is the
        zero-recompute lever — a prefill replica's publish pass sees a
        prompt some other pod already published as fully present and
        skips the local prefill entirely."""

        if key in self.local:
            return True
        return bool(self._peers_with(key))

    def unpin(self, key: bytes) -> None:
        self.local.unpin(key)

    def record(self, hit: bool) -> None:
        self.local.record(hit)

    def put(self, key: bytes, kv_tree: Any, nbytes: int) -> None:
        self.local.put(key, kv_tree, nbytes)
        self._announce([key])

    def get(self, key: bytes, pin: bool = False):
        """Local-first resolve; on a miss, pull from a peer whose index
        advertises the key.  A successful pull lands in the LOCAL
        fabric (so every later request is a local hit) and the returned
        record — a shallow copy — carries ``transport="http"`` +
        ``peer``.  Any failure returns None: the admission path
        recomputes, never errors."""

        rec = self.local.get(key, pin=pin)
        if rec is not None:
            return rec
        with self._lock:
            have_peers = bool(self._peers)
        if not have_peers:
            return None
        candidates = self._peers_with(key)
        if not candidates:
            self._count_pull("miss")
            return None
        for peer in candidates:
            try:
                tree, nbytes = self._pull_block(peer, key)
            except PullError as exc:
                self._count_failure(exc.reason, peer)
                if exc.reason == "not_found":
                    with self._lock:
                        peer.keys.discard(key)
                continue
            self._mark_up(peer)
            self.local.put(key, tree, nbytes)
            stored = self.local.get(key, pin=pin)
            if stored is None:  # pathological capacity: serve transient
                stored = {"kv": tree, "nbytes": int(nbytes)}
            self._count_pull("hit")
            with self._lock:
                self.bytes_pulled += int(nbytes)
            return dict(stored, transport="http", peer=peer.addr)
        self._count_pull("failed")
        return None

    def snapshot(self) -> dict:
        snap = self.local.snapshot()
        with self._lock:
            snap["advertise"] = self.advertise
            snap["peers"] = [
                {
                    "peer": p.addr,
                    "up": p.up,
                    "keys": len(p.keys),
                    "generation": p.generation,
                }
                for p in self._peers.values()
            ]
            snap["pulls"] = dict(self.pulls)
            snap["pull_failures"] = dict(self.pull_failures)
            snap["bytes_pulled"] = self.bytes_pulled
        return snap

    # -- fleet plumbing ------------------------------------------------------

    def register_template(self, arena) -> None:
        """Record the arena pytree template remote pulls decode
        against (treedef + per-leaf block-row flag/shape/dtype).
        Called by the paged pool right after its arena is built."""

        import jax

        leaves, treedef = jax.tree_util.tree_flatten(arena)
        meta = [
            (
                getattr(leaf, "ndim", 0) == 4,
                tuple(getattr(leaf, "shape", ())),
                str(np.dtype(leaf.dtype))
                if hasattr(leaf, "dtype") else "float32",
            )
            for leaf in leaves
        ]
        with self._lock:
            self._template = (treedef, meta)

    def set_advertise(self, addr: str) -> None:
        """The ``host:port`` this pod's :class:`FabricServer` serves
        on — stamped into announcements so peers learn where to pull
        from (announcement-based discovery for statically-configured
        fleets)."""

        self.advertise = str(addr)

    def add_peer(self, addr: str) -> None:
        addr = str(addr)
        if not addr or addr == self.advertise:
            return
        with self._lock:
            self._peers.setdefault(addr, _Peer(addr))

    def handle_publish(self, payload: dict) -> None:
        """Server-side merge of a peer's ``POST /fabric/publish``
        announcement — unknown senders are added (discovery), known
        senders' key sets grow.  Malformed keys are dropped, never
        raised: announcements are best-effort."""

        addr = str(payload.get("advertise") or "")
        if not addr or addr == self.advertise:
            return
        keys = []
        for k in payload.get("keys", []) or []:
            try:
                keys.append(bytes.fromhex(str(k)))
            except ValueError:
                continue
        with self._lock:
            peer = self._peers.get(addr)
            if peer is None:
                peer = self._peers[addr] = _Peer(addr)
            peer.keys.update(keys)
            try:
                peer.generation = int(payload.get("generation") or 0)
            except (TypeError, ValueError):
                pass
        self._mark_up(peer)

    def refresh_peers(self) -> None:
        """Force an index read of every peer (tests / CLI warmup)."""

        with self._lock:
            peers = list(self._peers.values())
        for p in peers:
            self._refresh_index(p)

    def stop(self) -> None:
        """Join the announcer thread (serve_lm shutdown)."""

        with self._ann_cv:
            self._ann_stop = True
            self._ann_cv.notify()
        t = self._ann_thread
        if t is not None:
            t.join(timeout=2.0)

    # -- internals -----------------------------------------------------------

    def _peers_with(self, key: bytes) -> List[_Peer]:
        """Peers whose advertised index holds ``key`` — consulting the
        cached indexes first, then re-reading any index older than
        ``index_ttl_seconds`` (at most one HTTP round per peer per TTL
        window, so a miss storm cannot turn into an index storm)."""

        with self._lock:
            peers = list(self._peers.values())
        found = [p for p in peers if key in p.keys]
        if found:
            return found
        now = time.monotonic()
        for p in peers:
            if now - p.last_index > self.index_ttl_seconds:
                self._refresh_index(p)
        return [p for p in peers if key in p.keys]

    def _refresh_index(self, peer: _Peer) -> None:
        url = f"http://{peer.addr}/fabric/index"
        try:
            with urllib.request.urlopen(
                url, timeout=self.request_timeout
            ) as resp:
                idx = json.loads(resp.read())
        except (OSError, ValueError) as exc:
            peer.up = False
            peer.last_index = time.monotonic()
            if self.metrics is not None:
                self.metrics.set(
                    "kv_fabric_peer_up", 0.0, peer=peer.addr
                )
            del exc
            return
        keys = set()
        for k in idx.get("keys", []) or []:
            try:
                keys.add(bytes.fromhex(str(k)))
            except ValueError:
                continue
        with self._lock:
            peer.keys = keys
            try:
                peer.generation = int(idx.get("generation") or 0)
            except (TypeError, ValueError):
                pass
            peer.last_index = time.monotonic()
        self._mark_up(peer)

    def _pull_block(self, peer: _Peer, key: bytes):
        with self._lock:
            template = self._template
        if template is None:
            raise PullError("no_template")
        url = f"http://{peer.addr}/fabric/blocks/{key.hex()}"

        def attempt():
            with urllib.request.urlopen(
                url, timeout=self.request_timeout
            ) as resp:
                return resp.read()

        try:
            body = self.policy.call(
                attempt, client="fabric", metrics=self.metrics
            )
        except urllib.error.HTTPError as exc:
            raise PullError(
                "not_found" if exc.code == 404 else "http_error",
                f"HTTP {exc.code}",
            )
        except OSError as exc:
            raise PullError("peer_dead", str(exc))
        return decode_block(body, template)

    def _count_pull(self, outcome: str) -> None:
        with self._lock:
            self.pulls[outcome] = self.pulls.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics.inc(
                "kv_fabric_pulls_total",
                model=self.model_label, outcome=outcome,
            )

    def _count_failure(self, reason: str, peer: _Peer) -> None:
        with self._lock:
            self.pull_failures[reason] = (
                self.pull_failures.get(reason, 0) + 1
            )
        if self.metrics is not None:
            self.metrics.inc(
                "kv_fabric_pull_failures_total",
                model=self.model_label, reason=reason,
            )
        if reason == "peer_dead":
            peer.up = False
            if self.metrics is not None:
                self.metrics.set(
                    "kv_fabric_peer_up", 0.0, peer=peer.addr
                )

    def _mark_up(self, peer: _Peer) -> None:
        peer.up = True
        if self.metrics is not None:
            self.metrics.set("kv_fabric_peer_up", 1.0, peer=peer.addr)

    def _announce(self, keys: List[bytes]) -> None:
        with self._lock:
            have_peers = bool(self._peers)
        if not have_peers or not self.advertise:
            return
        with self._ann_cv:
            self._ann_pending.extend(keys)
            if self._ann_thread is None or not self._ann_thread.is_alive():
                self._ann_thread = threading.Thread(
                    target=self._announce_loop,
                    daemon=True,
                    name="fabric-announce",
                )
                self._ann_thread.start()
            self._ann_cv.notify()

    def _announce_loop(self) -> None:
        while True:
            with self._ann_cv:
                while not self._ann_pending and not self._ann_stop:
                    self._ann_cv.wait(timeout=1.0)
                if self._ann_stop and not self._ann_pending:
                    return
                batch, self._ann_pending = self._ann_pending, []
            generation = getattr(self.local, "generation", 0)
            body = json.dumps({
                "advertise": self.advertise,
                "keys": [k.hex() for k in batch],
                "generation": generation,
            }).encode()
            with self._lock:
                addrs = list(self._peers)
            for addr in addrs:
                req = urllib.request.Request(
                    f"http://{addr}/fabric/publish",
                    data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                try:
                    with urllib.request.urlopen(
                        req, timeout=self.announce_timeout
                    ):
                        pass
                except OSError:
                    pass  # best effort: pull-side index reads recover


# ---------------------------------------------------------------------------
# the server tier
# ---------------------------------------------------------------------------


class FabricServer:
    """Per-pod HTTP exporter of the local prefix fabric (the
    ``PodTelemetryServer`` pattern: threaded stdlib server, silenced
    logs, ``port``/``url`` properties, ``start``/``stop``).

    ``fabric`` may be a bare ``PrefixFabric`` or a :class:`FleetFabric`
    (whose ``.local`` store is served, and whose ``handle_publish``
    receives announcements).  ``faults`` is an optional chaos hook
    duck-typed on ``decide(method, raw_path)`` — the PR 1
    ``backend/kubesim.FaultInjector`` plugs in directly, so chaos tests
    can reset the socket mid-pull or 404 a block on schedule."""

    def __init__(
        self,
        fabric,
        host: str = "127.0.0.1",
        port: int = 0,
        faults=None,
    ):
        self.fabric = fabric
        self.local = getattr(fabric, "local", fabric)
        self.faults = faults
        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = "tpu-kv-fabric/1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _send_json(self, code: int, obj) -> None:
                self._send(
                    code, json.dumps(obj).encode(), "application/json"
                )

            def _inject(self) -> bool:
                """True = a fault consumed the request (chaos leg)."""

                if outer.faults is None:
                    return False
                decision = outer.faults.decide(self.command, self.path)
                if decision is None:
                    return False
                if decision[0] == "latency":
                    time.sleep(decision[1])
                    return False
                if decision[0] == "error":
                    _, status, retry_after = decision
                    self.send_response(int(status))
                    if retry_after is not None:
                        self.send_header("Retry-After", str(retry_after))
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return True
                # "reset": SO_LINGER(1, 0) + hard shutdown → the client
                # sees ECONNRESET mid-read, the peer-died-mid-pull case
                try:
                    self.connection.setsockopt(
                        socket.SOL_SOCKET,
                        socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                except OSError:
                    pass
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return True

            def do_GET(self):
                if self._inject():
                    return
                route = self.path.split("?")[0]
                try:
                    if route == "/healthz":
                        return self._send(200, b"ok\n", "text/plain")
                    if route == "/fabric/index":
                        return self._send_json(200, outer.index())
                    if route.startswith("/fabric/blocks/"):
                        hexkey = route[len("/fabric/blocks/"):]
                        try:
                            key = bytes.fromhex(hexkey)
                        except ValueError:
                            return self._send_json(
                                400, {"error": "bad chain key"}
                            )
                        # pinned across the encode so eviction can't
                        # race the serialisation (the PIN guard, wire
                        # edition)
                        rec = outer.local.get(key, pin=True)
                        if rec is None:
                            return self._send_json(
                                404, {"error": "unknown chain key"}
                            )
                        try:
                            body = encode_block(key, rec)
                        finally:
                            outer.local.unpin(key)
                        return self._send(
                            200, body, "application/octet-stream"
                        )
                    return self._send_json(404, {"error": "not found"})
                except Exception as exc:  # noqa: BLE001 - HTTP boundary
                    return self._send_json(
                        500, {"error": f"{type(exc).__name__}: {exc}"}
                    )

            def do_POST(self):
                if self._inject():
                    return
                route = self.path.split("?")[0]
                try:
                    if route == "/fabric/publish":
                        n = int(self.headers.get("Content-Length") or 0)
                        raw = self.rfile.read(n) if n else b"{}"
                        try:
                            payload = json.loads(raw or b"{}")
                        except ValueError:
                            return self._send_json(
                                400, {"error": "bad announcement"}
                            )
                        handle = getattr(
                            outer.fabric, "handle_publish", None
                        )
                        if handle is not None:
                            handle(payload)
                        return self._send_json(200, {"ok": True})
                    return self._send_json(404, {"error": "not found"})
                except Exception as exc:  # noqa: BLE001 - HTTP boundary
                    return self._send_json(
                        500, {"error": f"{type(exc).__name__}: {exc}"}
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    def index(self) -> dict:
        """The /fabric/index document: every local chain key (hex) +
        the store's generation stamp, so clients can cheap-poll for
        change."""

        if hasattr(self.local, "index_keys"):
            keys, generation = self.local.index_keys()
        else:  # a duck-typed store without the stamp
            keys, generation = list(getattr(self.local, "_entries", {})), 0
        return {
            "v": WIRE_VERSION,
            "model": getattr(self.local, "model_label", ""),
            "advertise": getattr(self.fabric, "advertise", ""),
            "generation": int(generation),
            "keys": [k.hex() for k in keys],
        }

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def addr(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "FabricServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                daemon=True,
                name="kv-fabric",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
