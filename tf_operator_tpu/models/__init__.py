"""Model zoo for the framework's examples and benchmarks.

Covers the reference's target workloads (BASELINE.md configs): the
dist-mnist CNN, ResNet-50 (MultiWorkerMirrored / Horovod configs), and
the transformer family (BERT-base pretrain, T5-base) — all flax.linen,
bfloat16 compute / float32 params, written for pjit sharding over the
named mesh in tf_operator_tpu.parallel.
"""

from tf_operator_tpu.models.mnist import MnistCNN
from tf_operator_tpu.models.resnet import ResNet, resnet18, resnet50

__all__ = ["MnistCNN", "ResNet", "resnet18", "resnet50"]
