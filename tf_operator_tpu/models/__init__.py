"""Model zoo for the framework's examples and benchmarks.

Covers the reference's target workloads (BASELINE.md configs): the
dist-mnist CNN, ResNet-50 (MultiWorkerMirrored / Horovod configs), and
the transformer family (BERT-base pretrain, T5-base) — all flax.linen,
bfloat16 compute / float32 params, written for pjit sharding over the
named mesh in tf_operator_tpu.parallel.
"""

from tf_operator_tpu.models.bert import Bert, BertForPretraining, bert_base, bert_tiny, mlm_loss
from tf_operator_tpu.models.gpt import CausalLM, gpt_small, gpt_tiny, lm_loss
from tf_operator_tpu.models.batching import (
    ContinuousBatchingDecoder,
    PagedContinuousBatchingDecoder,
)
from tf_operator_tpu.models.pool_router import PoolRouter
from tf_operator_tpu.models.speculative import SpeculativeDecoder
from tf_operator_tpu.models.decode import (
    ChunkedServingDecoder,
    generate,
    init_cache,
)
from tf_operator_tpu.models.llama import (
    LlamaLM,
    llama_7b_shape,
    llama_loss,
    llama_loss_chunked,
    llama_tiny,
)
from tf_operator_tpu.models.mnist import MnistCNN
from tf_operator_tpu.models.pipelined_lm import PipelinedLM, lm_reference_apply
from tf_operator_tpu.models.moe import MoeConfig, MoeLM, moe_lm_loss, moe_tiny
from tf_operator_tpu.models.resnet import (
    FusedBatchNorm,
    ResNet,
    fold_batchnorm,
    resnet18,
    resnet50,
)
from tf_operator_tpu.models.vit import ViT, vit_b16, vit_loss, vit_tiny
from tf_operator_tpu.models.t5 import T5, seq2seq_loss, t5_base, t5_tiny
from tf_operator_tpu.models.transformer import TransformerConfig

__all__ = [
    "Bert",
    "BertForPretraining",
    "ChunkedServingDecoder",
    "ContinuousBatchingDecoder",
    "PagedContinuousBatchingDecoder",
    "PoolRouter",
    "SpeculativeDecoder",
    "generate",
    "init_cache",
    "bert_base",
    "bert_tiny",
    "mlm_loss",
    "CausalLM",
    "gpt_small",
    "gpt_tiny",
    "lm_loss",
    "lm_reference_apply",
    "MnistCNN",
    "MoeConfig",
    "PipelinedLM",
    "MoeLM",
    "moe_lm_loss",
    "moe_tiny",
    "FusedBatchNorm",
    "ResNet",
    "fold_batchnorm",
    "resnet18",
    "resnet50",
    "ViT",
    "vit_b16",
    "vit_loss",
    "vit_tiny",
    "T5",
    "seq2seq_loss",
    "t5_base",
    "t5_tiny",
    "TransformerConfig",
]
