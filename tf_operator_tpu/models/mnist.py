"""MNIST CNN — the dist-mnist workload, TPU-native.

Parity: the reference ships `examples/v1/dist-mnist/dist_mnist.py` (TF1
between-graph replication over TF_CONFIG; SURVEY.md §2 "Examples:
dist-mnist", §3.3) as its canonical e2e workload.  This is the same-size
model as a flax module; data parallelism comes from the mesh sharding in
parallel/trainer.py instead of PS/worker gRPC.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    """conv32-pool-conv64-pool-dense1024-dropout-dense10 (the classic
    dist_mnist topology)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1024, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
