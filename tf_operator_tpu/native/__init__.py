"""ctypes bindings for the native (C++) job-controller runtime.

`NativeWorkQueue` / `NativeExpectations` are drop-in replacements for
`controller.workqueue.WorkQueue` / `controller.expectations.Expectations`
(same method surface; tests/test_native.py runs both through one contract
suite).  `gen_tf_config_native` is the native twin of
`bootstrap.cluster_spec.gen_tf_config` for the DNS-resolver path.

`available()` reports whether the library could be built/loaded on this
box; callers fall back to the Python twins when it can't (the contract
suites keep the two in lockstep, so either backs the controller).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

from tf_operator_tpu.controller.expectations import EXPECTATION_TIMEOUT_S
from tf_operator_tpu.native import build as _build

_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[Exception] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    if os.environ.get("TPU_OPERATOR_NO_NATIVE") == "1":
        _load_error = RuntimeError("disabled via TPU_OPERATOR_NO_NATIVE=1")
        return None
    try:
        path = _build.build()
        lib = ctypes.CDLL(path)
    except Exception as e:  # noqa: BLE001 - any failure => Python fallback
        _load_error = e
        return None
    # -- signatures --------------------------------------------------------
    lib.tpuop_wq_new.restype = ctypes.c_void_p
    lib.tpuop_wq_new.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.tpuop_wq_free.argtypes = [ctypes.c_void_p]
    lib.tpuop_wq_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_wq_get.restype = ctypes.c_int
    lib.tpuop_wq_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_double,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tpuop_wq_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_wq_add_after.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_double,
    ]
    lib.tpuop_wq_add_rate_limited.restype = ctypes.c_double
    lib.tpuop_wq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_wq_forget.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_wq_num_requeues.restype = ctypes.c_int
    lib.tpuop_wq_num_requeues.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_wq_len.restype = ctypes.c_int
    lib.tpuop_wq_len.argtypes = [ctypes.c_void_p]
    lib.tpuop_wq_drop_front.restype = ctypes.c_int
    lib.tpuop_wq_drop_front.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tpuop_wq_shutdown.argtypes = [ctypes.c_void_p]

    lib.tpuop_exp_new.restype = ctypes.c_void_p
    lib.tpuop_exp_new.argtypes = [ctypes.c_double]
    lib.tpuop_exp_free.argtypes = [ctypes.c_void_p]
    for fn in (lib.tpuop_exp_expect_creations, lib.tpuop_exp_expect_deletions):
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    for fn in (
        lib.tpuop_exp_creation_observed,
        lib.tpuop_exp_deletion_observed,
        lib.tpuop_exp_delete,
    ):
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_exp_satisfied.restype = ctypes.c_int
    lib.tpuop_exp_satisfied.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuop_exp_pending.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
    ]

    lib.tpuop_gen_tf_config.restype = ctypes.c_int
    lib.tpuop_gen_tf_config.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def load_error() -> Optional[Exception]:
    _load()
    return _load_error


class NativeWorkQueue:
    """Drop-in twin of controller.workqueue.WorkQueue backed by C++."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 60.0):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_load_error}")
        self._lib = lib
        self._h = lib.tpuop_wq_new(base_delay, max_delay)
        self.base_delay = base_delay
        self.max_delay = max_delay

    def add(self, key: str) -> None:
        self._lib.tpuop_wq_add(self._h, key.encode())

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        import time

        buf = ctypes.create_string_buffer(4096)
        # deadline once, remaining time per retry: corrupt-key drops
        # must not restart the caller's timeout window
        deadline = None if timeout is None else time.monotonic() + float(timeout)
        while True:
            t = (
                -1.0
                if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            n = self._lib.tpuop_wq_get(self._h, t, buf, len(buf))
            if n != -2:
                return None if n < 0 else buf.value.decode()
            # front key exceeds the buffer — keys are "<ns>/<name>" so
            # this is corrupt input upstream.  Drop it (guarded: only if
            # still oversized, so concurrent workers can't race a valid
            # key off) and keep serving — raising here would kill the
            # caller's worker thread (controller.py gets outside its
            # try block).
            dropped = self._lib.tpuop_wq_drop_front(self._h, len(buf) - 1)
            if dropped > 0:
                import logging

                logging.getLogger("tpu_operator.native").error(
                    "dropped corrupt %d-byte work-queue key (max 4095)", dropped
                )

    def done(self, key: str) -> None:
        self._lib.tpuop_wq_done(self._h, key.encode())

    def add_after(self, key: str, delay: float) -> None:
        self._lib.tpuop_wq_add_after(self._h, key.encode(), float(delay))

    def add_rate_limited(self, key: str) -> float:
        return self._lib.tpuop_wq_add_rate_limited(self._h, key.encode())

    def forget(self, key: str) -> None:
        self._lib.tpuop_wq_forget(self._h, key.encode())

    def num_requeues(self, key: str) -> int:
        return self._lib.tpuop_wq_num_requeues(self._h, key.encode())

    def shutdown(self) -> None:
        self._lib.tpuop_wq_shutdown(self._h)

    def __len__(self) -> int:
        return self._lib.tpuop_wq_len(self._h)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.tpuop_wq_free(h)


class NativeExpectations:
    """Drop-in twin of controller.expectations.Expectations backed by C++."""

    def __init__(self, timeout_s: float = EXPECTATION_TIMEOUT_S):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_load_error}")
        self._lib = lib
        self._h = lib.tpuop_exp_new(timeout_s)
        self.timeout_s = timeout_s

    def expect_creations(self, key: str, n: int) -> None:
        self._lib.tpuop_exp_expect_creations(self._h, key.encode(), n)

    def expect_deletions(self, key: str, n: int) -> None:
        self._lib.tpuop_exp_expect_deletions(self._h, key.encode(), n)

    def creation_observed(self, key: str) -> None:
        self._lib.tpuop_exp_creation_observed(self._h, key.encode())

    def deletion_observed(self, key: str) -> None:
        self._lib.tpuop_exp_deletion_observed(self._h, key.encode())

    def satisfied(self, key: str) -> bool:
        return bool(self._lib.tpuop_exp_satisfied(self._h, key.encode()))

    def delete(self, key: str) -> None:
        self._lib.tpuop_exp_delete(self._h, key.encode())

    def pending(self, key: str) -> Tuple[int, int]:
        adds = ctypes.c_int()
        dels = ctypes.c_int()
        self._lib.tpuop_exp_pending(
            self._h, key.encode(), ctypes.byref(adds), ctypes.byref(dels)
        )
        return adds.value, dels.value

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.tpuop_exp_free(h)


def gen_tf_config_native(
    job_name: str,
    namespace: str,
    replicas: str,
    task_type: str,
    index: int,
    sparse: bool = False,
) -> str:
    """Native TF_CONFIG; ``replicas`` is "type=count:port,..." ordered."""

    lib = _load()
    if lib is None:
        raise RuntimeError(f"native runtime unavailable: {_load_error}")
    # size from the input: one "<job>-<role>-<idx>.<ns>.svc:<port>" per
    # replica plus JSON framing — avoids a giant zero-filled buffer on
    # the per-pod bootstrap path
    est = 256
    for item in replicas.split(","):
        if "=" in item and ":" in item:
            role, _, rest = item.partition("=")
            count = rest.partition(":")[0]
            n_rep = int(count) if count.isdigit() else 0
            est += n_rep * (len(job_name) + len(role) + len(namespace) + 32)
    buf = ctypes.create_string_buffer(est)
    n = lib.tpuop_gen_tf_config(
        job_name.encode(),
        namespace.encode(),
        replicas.encode(),
        task_type.encode(),
        index,
        1 if sparse else 0,
        buf,
        len(buf),
    )
    if n < 0:
        raise ValueError(
            f"native tf_config generation failed for {job_name}/{task_type}[{index}]"
        )
    return buf.value.decode()
