"""Build the native runtime (_libtpuop.so) from the C++ sources.

The library is compiled on demand (first import) and cached; a rebuild
triggers whenever any source is newer than the .so.  Kept as a plain
g++ invocation — the native tier is deliberately dependency-free
(no pybind11 in this image; the ABI is C, consumed via ctypes).
"""

from __future__ import annotations

import os
import subprocess
import threading

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "_libtpuop.so")
_SOURCES = (
    "workqueue.cc",
    "expectations.cc",
    "clusterspec.cc",
    "planner.cc",
    "syncdecide.cc",
)
_HEADERS = ("tpuop.h", "plan_core.h")
_lock = threading.Lock()


def lib_path() -> str:
    return _LIB_PATH


def needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    paths = [os.path.join(_SRC_DIR, s) for s in _SOURCES + _HEADERS]
    return any(os.path.getmtime(p) > lib_mtime for p in paths)


def build(force: bool = False) -> str:
    """Compile (if stale) and return the .so path; raises on failure."""

    with _lock:
        if not force and not needs_build():
            return _LIB_PATH
        # PID-suffixed tmp: concurrent builds from separate processes each
        # write their own file; os.replace makes the install atomic
        tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
        cmd = [
            "g++",
            "-std=c++17",
            "-O2",
            "-fPIC",
            "-shared",
            "-pthread",
            "-Wall",
            "-o",
            tmp,
        ] + [os.path.join(_SRC_DIR, s) for s in _SOURCES]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB_PATH)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return _LIB_PATH


if __name__ == "__main__":
    print(build(force=True))
