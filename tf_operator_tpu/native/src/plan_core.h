// Shared decision core — ONE implementation of the replica plan and the
// success-policy truth table, consumed by two ABIs:
//   planner.cc     — string ABI (kept for the per-call contract tests)
//   syncdecide.cc  — packed-int32 batch ABI (one call per reconcile sync)
//
// Mirrors the Python twins in controller/plan.py and controller/status.py;
// tests/test_plan.py property-tests the equivalence.

#pragma once

#include <map>
#include <utility>
#include <vector>

namespace tpuop {

// phase codes: 0=Pending 1=Running 2=Succeeded 3=Failed 4=Unknown
enum Phase { kPending = 0, kRunning, kSucceeded, kFailed, kUnknown };
// restart policies: 0=Never 1=Always 2=OnFailure 3=ExitCode
enum Policy { kNever = 0, kAlways, kOnFailure, kExitCode };
// replica types (api.types.REPLICA_TYPE_ORDER ids, fixed ABI)
enum TypeId { kChief = 0, kMaster, kPS, kWorker, kEvaluator, kTPUSlice };
// success policy: 0=Default 1=AllWorkers
enum SuccessPolicy { kDefault = 0, kAllWorkers };
// success reason codes (Python side maps back to strings)
enum Reason {
  kNotDone = 0,
  kChiefSucceeded,
  kMasterSucceeded,
  kAllReplicasSucceeded,
  kAllWorkersSucceeded,
  kAllSliceSucceeded,
  kSliceAndWorker0Succeeded,
  kWorker0Succeeded,
};

// exit-code semantics parity: utils/train_util.is_retryable_exit_code
inline bool retryable(long exit_code) { return exit_code > 127; }

struct PodObs {
  long index;
  int phase;
  long exit_code;  // -1 = unknown
};

struct Plan {
  std::vector<long> create;
  std::vector<long> scale_in;  // duplicates preserved, as observed
  std::vector<std::pair<long, long>> restart;  // (index, exit_code)
  std::vector<std::pair<long, long>> fatal;
  bool backoff = false;
};

inline Plan plan_replica(long want, int policy, bool has_limit, long limit,
                         long restarts, const std::vector<PodObs> &observed) {
  Plan plan;
  std::map<long, PodObs> by_index;  // first pod per index wins (slot[0])
  for (const PodObs &obs : observed) {
    if (obs.index >= want) {
      plan.scale_in.push_back(obs.index);
    } else if (!by_index.count(obs.index)) {
      by_index[obs.index] = obs;
    }
  }
  long count = restarts;
  for (long idx = 0; idx < want; ++idx) {
    auto it = by_index.find(idx);
    if (it == by_index.end()) {
      plan.create.push_back(idx);
      continue;
    }
    if (it->second.phase != kFailed) continue;
    const long exit_code = it->second.exit_code >= 0 ? it->second.exit_code : 1;
    const bool should_restart =
        policy == kAlways || policy == kOnFailure ||
        (policy == kExitCode && retryable(exit_code));
    if (!should_restart) {
      plan.fatal.emplace_back(idx, exit_code);
      continue;
    }
    // budget check precedes the increment (Python parity: exhaustion
    // aborts the remaining indices of this sync)
    if (has_limit && count >= limit) {
      plan.backoff = true;
      break;
    }
    ++count;
    plan.restart.emplace_back(idx, exit_code);
  }
  return plan;
}

struct TypeObs {
  long want = 0, npods = 0, nsucc = 0;
  bool pod0succ = false;
};

// Returns a Reason code; kNotDone = job not (yet) succeeded.
inline int eval_success(int policy, const std::map<int, TypeObs> &types) {
  // chief-like decides alone (CHIEF_LIKE order: Chief, Master)
  for (int chief : {kChief, kMaster}) {
    auto it = types.find(chief);
    if (it != types.end()) {
      if (it->second.pod0succ)
        return chief == kChief ? kChiefSucceeded : kMasterSucceeded;
      return kNotDone;
    }
  }

  const auto worker = types.find(kWorker);
  const auto slice = types.find(kTPUSlice);
  const bool has_worker = worker != types.end() && worker->second.want > 0;
  const bool has_slice = slice != types.end() && slice->second.want > 0;

  if (!has_worker && !has_slice) {
    long npods = 0, nsucc = 0;
    for (const auto &kv : types) {
      npods += kv.second.npods;
      nsucc += kv.second.nsucc;
    }
    return (npods > 0 && nsucc == npods) ? kAllReplicasSucceeded : kNotDone;
  }

  if (policy == kAllWorkers) {
    if (has_worker && worker->second.nsucc < worker->second.want)
      return kNotDone;
    if (has_slice && slice->second.nsucc < slice->second.want) return kNotDone;
    return kAllWorkersSucceeded;
  }

  if (has_slice) {
    if (slice->second.nsucc < slice->second.want) return kNotDone;
    if (!has_worker) return kAllSliceSucceeded;
    return worker->second.pod0succ ? kSliceAndWorker0Succeeded : kNotDone;
  }

  if (worker != types.end() && worker->second.pod0succ)
    return kWorker0Succeeded;
  return kNotDone;
}

}  // namespace tpuop
