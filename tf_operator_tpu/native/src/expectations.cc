// ControllerExpectations parity (SURVEY.md §2 "Generic job-controller
// runtime", §5 "Race detection") — the informer-race bookkeeping that
// prevents duplicate creates while the cache lags a just-issued write.
// Mirrors controller/expectations.py.

#include "tpuop.h"

#include <chrono>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

using Clock = std::chrono::steady_clock;

struct Entry {
  int adds = 0;
  int deletes = 0;
  Clock::time_point ts = Clock::now();
};

struct Expectations {
  std::mutex mu;
  std::unordered_map<std::string, Entry> by_key;
  double timeout_s;
};

Expectations *as_exp(void *p) { return static_cast<Expectations *>(p); }

}  // namespace

extern "C" {

void *tpuop_exp_new(double timeout_s) {
  auto *e = new Expectations();
  e->timeout_s = timeout_s;
  return e;
}

void tpuop_exp_free(void *e) { delete as_exp(e); }

void tpuop_exp_expect_creations(void *e, const char *key, int n) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  auto &ent = x->by_key[key];
  ent.adds += n;
  ent.ts = Clock::now();
}

void tpuop_exp_expect_deletions(void *e, const char *key, int n) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  auto &ent = x->by_key[key];
  ent.deletes += n;
  ent.ts = Clock::now();
}

void tpuop_exp_creation_observed(void *e, const char *key) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  auto it = x->by_key.find(key);
  if (it != x->by_key.end() && it->second.adds > 0) it->second.adds--;
}

void tpuop_exp_deletion_observed(void *e, const char *key) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  auto it = x->by_key.find(key);
  if (it != x->by_key.end() && it->second.deletes > 0) it->second.deletes--;
}

int tpuop_exp_satisfied(void *e, const char *key) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  auto it = x->by_key.find(key);
  if (it == x->by_key.end()) return 1;
  const Entry &ent = it->second;
  if (ent.adds <= 0 && ent.deletes <= 0) return 1;
  const double age =
      std::chrono::duration<double>(Clock::now() - ent.ts).count();
  // expired: assume the watch events were lost; resync from observed state
  if (age > x->timeout_s) return 1;
  return 0;
}

void tpuop_exp_delete(void *e, const char *key) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  x->by_key.erase(key);
}

void tpuop_exp_pending(void *e, const char *key, int *adds, int *deletes) {
  auto *x = as_exp(e);
  std::lock_guard<std::mutex> lk(x->mu);
  auto it = x->by_key.find(key);
  if (it == x->by_key.end()) {
    *adds = 0;
    *deletes = 0;
  } else {
    *adds = it->second.adds;
    *deletes = it->second.deletes;
  }
}

}  // extern "C"
