/* tpuop — native (C++) job-controller runtime for tf_operator_tpu.
 *
 * Parity target: the reference operator's native tier (SURVEY.md §2a).
 * The reference is a single Go binary whose hot path is client-go's
 * rate-limited workqueue + ControllerExpectations + the reconcile loop;
 * Go is absent from this toolchain so the native tier is C++ (task rule).
 *
 * Exposed as a tiny C ABI consumed from Python via ctypes
 * (tf_operator_tpu/native/__init__.py).  Each family mirrors a Python
 * twin behind the same pytest contract (tests/test_native.py):
 *
 *   tpuop_wq_*   <->  controller/workqueue.py  (client-go workqueue parity)
 *   tpuop_exp_*  <->  controller/expectations.py (ControllerExpectations)
 *   tpuop_gen_*  <->  bootstrap/cluster_spec.py (genTFConfigJSONStr)
 */
#ifndef TPUOP_H_
#define TPUOP_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- rate-limited deduplicating work queue ---- */

void *tpuop_wq_new(double base_delay, double max_delay);
void tpuop_wq_free(void *wq);
void tpuop_wq_add(void *wq, const char *key);
/* Blocks up to timeout seconds (timeout < 0: wait forever).  Writes the
 * next key into buf; returns its length, or -1 on timeout/shutdown. */
int tpuop_wq_get(void *wq, double timeout, char *buf, int cap);
void tpuop_wq_done(void *wq, const char *key);
void tpuop_wq_add_after(void *wq, const char *key, double delay);
double tpuop_wq_add_rate_limited(void *wq, const char *key);
void tpuop_wq_forget(void *wq, const char *key);
int tpuop_wq_num_requeues(void *wq, const char *key);
int tpuop_wq_drop_front(void *wq, int max_len);
int tpuop_wq_len(void *wq);
void tpuop_wq_shutdown(void *wq);

/* ---- expectations (informer-race bookkeeping) ---- */

void *tpuop_exp_new(double timeout_s);
void tpuop_exp_free(void *e);
void tpuop_exp_expect_creations(void *e, const char *key, int n);
void tpuop_exp_expect_deletions(void *e, const char *key, int n);
void tpuop_exp_creation_observed(void *e, const char *key);
void tpuop_exp_deletion_observed(void *e, const char *key);
int tpuop_exp_satisfied(void *e, const char *key);
void tpuop_exp_delete(void *e, const char *key);
void tpuop_exp_pending(void *e, const char *key, int *adds, int *deletes);

/* ---- TF_CONFIG / cluster-spec generation ----
 *
 * replicas: ordered "type=count:port" pairs joined by ',', e.g.
 *   "chief=1:2222,ps=2:2222,worker=4:2222"
 * Emits byte-identical JSON to bootstrap.cluster_spec.gen_tf_config
 * with the DNS resolver (json.dumps sort_keys=True formatting).
 * Returns output length, or -1 if cap is too small / inputs invalid. */
int tpuop_gen_tf_config(const char *job, const char *ns,
                        const char *replicas, const char *task_type,
                        int index, int sparse, char *buf, int cap);

/* ---- reconcile decision core (planner.cc) ----
 * String protocols documented at the top of planner.cc.  Both return
 * output length, or -1 on malformed input / small buffer. */

int tpuop_plan_replica(const char *desc, char *buf, int cap);
int tpuop_eval_success(const char *desc, char *buf, int cap);

/* ---- batch sync decision (syncdecide.cc) ----
 * ONE call per reconcile sync: success evaluation + replica plans for
 * every replica type, packed-int32 protocol documented at the top of
 * syncdecide.cc.  Returns int32s written, -1 on malformed input, -2 if
 * cap is too small. */

int tpuop_sync_decide(const int32_t *in, int in_len, int32_t *out, int cap);

#ifdef __cplusplus
}
#endif

#endif /* TPUOP_H_ */
