// One-call batch decision ABI — the per-sync native hot path promised
// by plan_core.h.  A reconcile sync makes exactly ONE call here: the
// success-policy evaluation plus the replica plans for every replica
// type, with the job-global restart budget threaded across types in
// spec order (matching the Python executor's sequential semantics).
//
// Packed-int32 protocol (no strings, no parsing on the hot path):
//
// Input:
//   [0] version           must be 1
//   [1] success_policy    0=Default 1=AllWorkers
//   [2] restart_count     restarts already consumed (job-global)
//   [3] has_limit         0/1
//   [4] limit             backoff limit (ignored when has_limit=0)
//   [5] n_types
//   then per type, in job.spec.ordered_types() order:
//     [type_id, want, policy, n_pods]
//     then per pod: [index (-1 = unindexed), phase, exit_code (-1 = unknown)]
//
// Output (returns int32s written; -1 malformed input; -2 cap too small):
//   [0] succeeded 0/1
//   [1] reason    tpuop::Reason code (Python maps back to strings)
//   [2] n_types
//   then per type:
//     [type_id, backoff 0/1, n_create, n_scale_in, n_restart, n_fatal]
//     create idx..., scale_in idx..., (restart idx,exit)..., (fatal idx,exit)...
//
// Unindexed pods (index -1) are excluded from planning but count toward
// the success evaluation's npods/nsucc, mirroring controller/plan.py.

#include <cstdint>
#include <map>
#include <vector>

#include "plan_core.h"
#include "tpuop.h"

namespace {

struct Writer {
  int32_t *out;
  int cap;
  int n = 0;
  bool overflow = false;

  void put(int32_t v) {
    if (n >= cap) {
      overflow = true;
      return;
    }
    out[n++] = v;
  }
};

}  // namespace

extern "C" {

int tpuop_sync_decide(const int32_t *in, int in_len, int32_t *out, int cap) {
  if (!in || !out || in_len < 6) return -1;
  if (in[0] != 1) return -1;
  const int success_policy = in[1];
  if (success_policy != tpuop::kDefault && success_policy != tpuop::kAllWorkers)
    return -1;
  long count = in[2];
  const bool has_limit = in[3] != 0;
  const long limit = in[4];
  const int n_types = in[5];
  if (count < 0 || n_types < 0 || (has_limit && limit < 0)) return -1;

  int pos = 6;
  std::map<int, tpuop::TypeObs> type_obs;
  std::vector<int> type_ids;
  std::vector<tpuop::Plan> plans;
  type_ids.reserve(n_types);
  plans.reserve(n_types);

  for (int t = 0; t < n_types; ++t) {
    if (pos + 4 > in_len) return -1;
    const int type_id = in[pos];
    const long want = in[pos + 1];
    const int policy = in[pos + 2];
    const int n_pods = in[pos + 3];
    pos += 4;
    if (type_id < tpuop::kChief || type_id > tpuop::kTPUSlice) return -1;
    if (want < 0 || n_pods < 0 || policy < tpuop::kNever ||
        policy > tpuop::kExitCode)
      return -1;
    if (pos + 3 * n_pods > in_len) return -1;

    std::vector<tpuop::PodObs> observed;
    observed.reserve(n_pods);
    tpuop::TypeObs obs;
    obs.want = want;
    obs.npods = n_pods;
    bool pod0_seen = false;
    for (int p = 0; p < n_pods; ++p) {
      const long index = in[pos];
      const int phase = in[pos + 1];
      const long exit_code = in[pos + 2];
      pos += 3;
      if (phase < tpuop::kPending || phase > tpuop::kUnknown) return -1;
      if (phase == tpuop::kSucceeded) ++obs.nsucc;
      if (index == 0 && !pod0_seen) {
        pod0_seen = true;  // first index-0 pod wins (Python _find parity)
        obs.pod0succ = phase == tpuop::kSucceeded;
      }
      if (index >= 0) observed.push_back({index, phase, exit_code});
    }
    type_obs[type_id] = obs;
    type_ids.push_back(type_id);
    plans.push_back(
        tpuop::plan_replica(want, policy, has_limit, limit, count, observed));
    count += static_cast<long>(plans.back().restart.size());
  }
  if (pos != in_len) return -1;

  const int reason = tpuop::eval_success(success_policy, type_obs);

  Writer w{out, cap};
  w.put(reason != tpuop::kNotDone ? 1 : 0);
  w.put(reason);
  w.put(n_types);
  for (int t = 0; t < n_types; ++t) {
    const tpuop::Plan &plan = plans[t];
    w.put(type_ids[t]);
    w.put(plan.backoff ? 1 : 0);
    w.put(static_cast<int32_t>(plan.create.size()));
    w.put(static_cast<int32_t>(plan.scale_in.size()));
    w.put(static_cast<int32_t>(plan.restart.size()));
    w.put(static_cast<int32_t>(plan.fatal.size()));
    for (long idx : plan.create) w.put(static_cast<int32_t>(idx));
    for (long idx : plan.scale_in) w.put(static_cast<int32_t>(idx));
    for (const auto &r : plan.restart) {
      w.put(static_cast<int32_t>(r.first));
      w.put(static_cast<int32_t>(r.second));
    }
    for (const auto &f : plan.fatal) {
      w.put(static_cast<int32_t>(f.first));
      w.put(static_cast<int32_t>(f.second));
    }
  }
  if (w.overflow) return -2;
  return w.n;
}

}  // extern "C"
