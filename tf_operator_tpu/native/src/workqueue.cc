// Rate-limited deduplicating work queue — client-go workqueue parity
// (SURVEY.md §2 "TFJob controller core" hot loop).  Semantics mirror
// controller/workqueue.py exactly; tests/test_native.py runs both
// implementations through one contract suite.

#include "tpuop.h"

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// Seconds-to-ticks conversion overflows steady_clock's range for huge
// finite delays (e.g. a job spec's ttl of 1e10 s); clamp to ~31 years,
// which is "forever" for a queue wakeup but converts safely.
constexpr double kMaxDelayS = 1e9;

Clock::time_point after(double seconds) {
  const double s = std::min(seconds, kMaxDelayS);
  return Clock::now() + std::chrono::duration_cast<Clock::duration>(
                            std::chrono::duration<double>(s));
}

struct Delayed {
  Clock::time_point when;
  long seq;
  std::string key;
  bool operator>(const Delayed &o) const {
    if (when != o.when) return when > o.when;
    return seq > o.seq;
  }
};

struct WorkQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> queue;
  std::unordered_set<std::string> queued;
  std::unordered_set<std::string> processing;
  std::unordered_set<std::string> dirty;
  std::unordered_map<std::string, int> failures;
  std::priority_queue<Delayed, std::vector<Delayed>, std::greater<Delayed>> delayed;
  long seq = 0;
  bool shutdown = false;
  double base_delay;
  double max_delay;

  // requires mu held
  void enqueue_locked(const std::string &key) {
    if (queued.insert(key).second) {
      queue.push_back(key);
      cv.notify_one();
    }
  }

  // requires mu held
  void drain_delayed_locked() {
    const auto now = Clock::now();
    while (!delayed.empty() && delayed.top().when <= now) {
      std::string key = delayed.top().key;
      delayed.pop();
      if (processing.count(key)) {
        dirty.insert(key);
      } else {
        enqueue_locked(key);
      }
    }
  }

  void add(const std::string &key) {
    std::lock_guard<std::mutex> lk(mu);
    if (shutdown) return;
    if (processing.count(key)) {
      dirty.insert(key);
      return;
    }
    enqueue_locked(key);
  }

  // timeout < 0 => wait forever.  Returns 0 on success, -1 on
  // timeout/shutdown, -2 when the next key exceeds max_len (the key is
  // left queued so it is never silently lost).
  int get(double timeout, size_t max_len, std::string *out) {
    const bool bounded = timeout >= 0;
    const auto deadline = after(bounded ? timeout : 0);
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      drain_delayed_locked();
      if (!queue.empty()) {
        if (queue.front().size() > max_len) return -2;
        *out = queue.front();
        queue.pop_front();
        queued.erase(*out);
        processing.insert(*out);
        return 0;
      }
      if (shutdown) return -1;
      if (bounded && Clock::now() >= deadline) return -1;
      // wake at the earliest of: next delayed item, caller deadline
      auto until = Clock::time_point::max();
      if (!delayed.empty()) until = delayed.top().when;
      if (bounded) until = std::min(until, deadline);
      if (until == Clock::time_point::max()) {
        cv.wait(lk);
      } else {
        cv.wait_until(lk, until);
      }
    }
  }

  void done(const std::string &key) {
    std::lock_guard<std::mutex> lk(mu);
    processing.erase(key);
    if (dirty.erase(key)) enqueue_locked(key);
  }

  void add_after(const std::string &key, double delay_s) {
    if (delay_s <= 0) {
      add(key);
      return;
    }
    std::lock_guard<std::mutex> lk(mu);
    if (shutdown) return;
    delayed.push({after(delay_s), ++seq, key});
    cv.notify_one();
  }

  double add_rate_limited(const std::string &key) {
    int n;
    {
      std::lock_guard<std::mutex> lk(mu);
      n = failures[key]++;
    }
    double delay = base_delay;
    for (int i = 0; i < n && delay < max_delay; ++i) delay *= 2;
    delay = std::min(delay, max_delay);
    add_after(key, delay);
    return delay;
  }

  int size() {
    std::lock_guard<std::mutex> lk(mu);
    return static_cast<int>(queue.size() + delayed.size());
  }

  void stop() {
    std::lock_guard<std::mutex> lk(mu);
    shutdown = true;
    cv.notify_all();
  }
};

WorkQueue *as_wq(void *p) { return static_cast<WorkQueue *>(p); }

}  // namespace

extern "C" {

void *tpuop_wq_new(double base_delay, double max_delay) {
  auto *wq = new WorkQueue();
  wq->base_delay = base_delay;
  wq->max_delay = max_delay;
  return wq;
}

void tpuop_wq_free(void *wq) { delete as_wq(wq); }

void tpuop_wq_add(void *wq, const char *key) { as_wq(wq)->add(key); }

int tpuop_wq_get(void *wq, double timeout, char *buf, int cap) {
  std::string out;
  if (cap <= 0) return -2;
  const int rc = as_wq(wq)->get(timeout, static_cast<size_t>(cap) - 1, &out);
  if (rc < 0) return rc;
  std::memcpy(buf, out.c_str(), out.size() + 1);
  return static_cast<int>(out.size());
}

void tpuop_wq_done(void *wq, const char *key) { as_wq(wq)->done(key); }

void tpuop_wq_add_after(void *wq, const char *key, double delay) {
  as_wq(wq)->add_after(key, delay);
}

double tpuop_wq_add_rate_limited(void *wq, const char *key) {
  return as_wq(wq)->add_rate_limited(key);
}

void tpuop_wq_forget(void *wq, const char *key) {
  auto *q = as_wq(wq);
  std::lock_guard<std::mutex> lk(q->mu);
  q->failures.erase(key);
}

int tpuop_wq_num_requeues(void *wq, const char *key) {
  auto *q = as_wq(wq);
  std::lock_guard<std::mutex> lk(q->mu);
  auto it = q->failures.find(key);
  return it == q->failures.end() ? 0 : it->second;
}

// get() returning -2 leaves the oversized key at the queue head; this
// discards it so the queue cannot livelock on a corrupt key.  Pops ONLY
// when the front actually exceeds max_len — two workers that both saw
// -2 must not race a valid key off the queue.  Returns the dropped
// key's length, 0 if the front was valid (someone else already dropped),
// or -1 if the queue was empty.
int tpuop_wq_drop_front(void *wq, int max_len) {
  auto *q = as_wq(wq);
  std::lock_guard<std::mutex> lk(q->mu);
  if (q->queue.empty()) return -1;
  if (max_len >= 0 &&
      q->queue.front().size() <= static_cast<size_t>(max_len))
    return 0;
  const std::string key = q->queue.front();
  q->queue.pop_front();
  q->queued.erase(key);
  return static_cast<int>(key.size());
}

int tpuop_wq_len(void *wq) { return as_wq(wq)->size(); }

void tpuop_wq_shutdown(void *wq) { as_wq(wq)->stop(); }

}  // extern "C"
