// Native reconcile decision core (SURVEY.md §2a item 1: "the reconcile
// engine ... the single binary's hot path").  Two pure functions mirror
// the Python twins in controller/plan.py behind one contract test suite
// (tests/test_plan.py, incl. property-based equivalence):
//
//   tpuop_plan_replica — the per-replica-type pod diff: which indices
//     to create, scale in, restart (with restart budget), or declare
//     fatal.  Mirrors Reconciler._reconcile_pods' decisions.
//   tpuop_eval_success — the success-policy truth table.  Mirrors
//     controller/status.evaluate_success.
//
// String ABI (no JSON dependency):
//   plan:  "want=N;policy=Never|Always|OnFailure|ExitCode;limit=N|-;
//           restarts=N;pods=idx:phase:exit,..."   phase in {P,R,S,F,U},
//           exit "-" when unknown.
//   out:   "create=i,..;scalein=i,..;restart=i:exit,..;fatal=i:exit,..;
//           backoff=0|1"
//
//   eval:  "policy=Default|AllWorkers;types=Name:want:npods:nsucc:p0s,.."
//           Name is the ReplicaType value (Chief/Master/PS/Worker/
//           Evaluator/TPUSlice); p0s = 1 iff the index-0 pod SUCCEEDED.
//   out:   "1:<reason>" or "0:"

#include "tpuop.h"

#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "plan_core.h"

namespace {

std::vector<std::string> split(const std::string &s, char sep) {
  std::vector<std::string> out;
  size_t pos = 0;
  while (pos <= s.size()) {
    size_t next = s.find(sep, pos);
    if (next == std::string::npos) next = s.size();
    out.push_back(s.substr(pos, next - pos));
    pos = next + 1;
  }
  return out;
}

// "k=v;k=v" -> map (value may contain ':' and ',')
bool parse_fields(const std::string &s, std::map<std::string, std::string> *out) {
  for (const std::string &item : split(s, ';')) {
    if (item.empty()) continue;
    size_t eq = item.find('=');
    if (eq == std::string::npos) return false;
    (*out)[item.substr(0, eq)] = item.substr(eq + 1);
  }
  return true;
}

bool to_int(const std::string &s, long *out) {
  if (s.empty()) return false;
  try {
    size_t pos = 0;
    *out = std::stol(s, &pos);
    return pos == s.size();
  } catch (...) {
    return false;
  }
}

int write_out(const std::string &s, char *buf, int cap) {
  const int n = static_cast<int>(s.size());
  if (n + 1 > cap) return -1;
  std::memcpy(buf, s.c_str(), n + 1);
  return n;
}

int phase_code(char c) {
  switch (c) {
    case 'P': return tpuop::kPending;
    case 'R': return tpuop::kRunning;
    case 'S': return tpuop::kSucceeded;
    case 'F': return tpuop::kFailed;
    case 'U': return tpuop::kUnknown;
    default: return -1;
  }
}

}  // namespace

extern "C" {

int tpuop_plan_replica(const char *desc, char *buf, int cap) {
  if (!desc) return -1;
  std::map<std::string, std::string> f;
  if (!parse_fields(desc, &f)) return -1;
  long want = 0, restarts = 0, limit = -1;
  if (!to_int(f["want"], &want) || want < 0) return -1;
  if (!to_int(f["restarts"], &restarts) || restarts < 0) return -1;
  const std::string limit_s = f.count("limit") ? f["limit"] : "-";
  const bool has_limit = limit_s != "-";
  if (has_limit && (!to_int(limit_s, &limit) || limit < 0)) return -1;
  const std::string policy_s = f.count("policy") ? f["policy"] : "Never";
  int policy;
  if (policy_s == "Never") policy = tpuop::kNever;
  else if (policy_s == "Always") policy = tpuop::kAlways;
  else if (policy_s == "OnFailure") policy = tpuop::kOnFailure;
  else if (policy_s == "ExitCode") policy = tpuop::kExitCode;
  else return -1;

  std::vector<tpuop::PodObs> observed;
  if (!f["pods"].empty()) {
    for (const std::string &p : split(f["pods"], ',')) {
      if (p.empty()) continue;
      std::vector<std::string> parts = split(p, ':');
      if (parts.size() != 3) return -1;
      tpuop::PodObs obs;
      if (!to_int(parts[0], &obs.index) || obs.index < 0) return -1;
      if (parts[1].size() != 1) return -1;
      obs.phase = phase_code(parts[1][0]);
      if (obs.phase < 0) return -1;
      obs.exit_code = -1;
      if (parts[2] != "-" && !to_int(parts[2], &obs.exit_code)) return -1;
      observed.push_back(obs);
    }
  }

  // decision logic lives in plan_core.h (shared with syncdecide.cc)
  tpuop::Plan plan =
      tpuop::plan_replica(want, policy, has_limit, limit, restarts, observed);

  std::string create, si, restart, fatal;
  for (size_t i = 0; i < plan.create.size(); ++i) {
    if (i) create += ",";
    create += std::to_string(plan.create[i]);
  }
  for (size_t i = 0; i < plan.scale_in.size(); ++i) {
    if (i) si += ",";
    si += std::to_string(plan.scale_in[i]);
  }
  for (size_t i = 0; i < plan.restart.size(); ++i) {
    if (i) restart += ",";
    restart += std::to_string(plan.restart[i].first) + ":" +
               std::to_string(plan.restart[i].second);
  }
  for (size_t i = 0; i < plan.fatal.size(); ++i) {
    if (i) fatal += ",";
    fatal += std::to_string(plan.fatal[i].first) + ":" +
             std::to_string(plan.fatal[i].second);
  }
  std::string out = "create=" + create + ";scalein=" + si + ";restart=" +
                    restart + ";fatal=" + fatal +
                    ";backoff=" + (plan.backoff ? "1" : "0");
  return write_out(out, buf, cap);
}

int tpuop_eval_success(const char *desc, char *buf, int cap) {
  if (!desc) return -1;
  std::map<std::string, std::string> f;
  if (!parse_fields(desc, &f)) return -1;
  const std::string policy_s = f.count("policy") ? f["policy"] : "Default";
  int policy;
  if (policy_s == "Default") policy = tpuop::kDefault;
  else if (policy_s == "AllWorkers") policy = tpuop::kAllWorkers;
  else return -1;

  // map type names onto plan_core ids; unknown names get fresh negative
  // ids so they still participate in the all-replicas-succeeded sums
  // without colliding with a known role
  auto type_id = [](const std::string &name) {
    if (name == "Chief") return static_cast<int>(tpuop::kChief);
    if (name == "Master") return static_cast<int>(tpuop::kMaster);
    if (name == "PS") return static_cast<int>(tpuop::kPS);
    if (name == "Worker") return static_cast<int>(tpuop::kWorker);
    if (name == "Evaluator") return static_cast<int>(tpuop::kEvaluator);
    if (name == "TPUSlice") return static_cast<int>(tpuop::kTPUSlice);
    return -1;
  };

  std::map<int, tpuop::TypeObs> types;
  int next_unknown = -1;
  if (!f["types"].empty()) {
    for (const std::string &t : split(f["types"], ',')) {
      if (t.empty()) continue;
      std::vector<std::string> parts = split(t, ':');
      if (parts.size() != 5) return -1;
      tpuop::TypeObs obs;
      long p0;
      if (!to_int(parts[1], &obs.want) || !to_int(parts[2], &obs.npods) ||
          !to_int(parts[3], &obs.nsucc) || !to_int(parts[4], &p0))
        return -1;
      obs.pod0succ = p0 != 0;
      int id = type_id(parts[0]);
      if (id < 0) id = --next_unknown;
      types[id] = obs;
    }
  }

  // truth table lives in plan_core.h (shared with syncdecide.cc)
  const int reason = tpuop::eval_success(policy, types);
  static const char *kReasonText[] = {
      "",                                        // kNotDone
      "Chief replica succeeded",                 // kChiefSucceeded
      "Master replica succeeded",                // kMasterSucceeded
      "all replicas succeeded",                  // kAllReplicasSucceeded
      "all workers succeeded",                   // kAllWorkersSucceeded
      "all slice members succeeded",             // kAllSliceSucceeded
      "all slice members and worker 0 succeeded",// kSliceAndWorker0Succeeded
      "worker 0 succeeded",                      // kWorker0Succeeded
  };
  if (reason == tpuop::kNotDone) return write_out("0:", buf, cap);
  return write_out(std::string("1:") + kReasonText[reason], buf, cap);
}

}  // extern "C"
