// Native reconcile decision core (SURVEY.md §2a item 1: "the reconcile
// engine ... the single binary's hot path").  Two pure functions mirror
// the Python twins in controller/plan.py behind one contract test suite
// (tests/test_plan.py, incl. property-based equivalence):
//
//   tpuop_plan_replica — the per-replica-type pod diff: which indices
//     to create, scale in, restart (with restart budget), or declare
//     fatal.  Mirrors Reconciler._reconcile_pods' decisions.
//   tpuop_eval_success — the success-policy truth table.  Mirrors
//     controller/status.evaluate_success.
//
// String ABI (no JSON dependency):
//   plan:  "want=N;policy=Never|Always|OnFailure|ExitCode;limit=N|-;
//           restarts=N;pods=idx:phase:exit,..."   phase in {P,R,S,F,U},
//           exit "-" when unknown.
//   out:   "create=i,..;scalein=i,..;restart=i:exit,..;fatal=i:exit,..;
//           backoff=0|1"
//
//   eval:  "policy=Default|AllWorkers;types=Name:want:npods:nsucc:p0s,.."
//           Name is the ReplicaType value (Chief/Master/PS/Worker/
//           Evaluator/TPUSlice); p0s = 1 iff the index-0 pod SUCCEEDED.
//   out:   "1:<reason>" or "0:"

#include "tpuop.h"

#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

std::vector<std::string> split(const std::string &s, char sep) {
  std::vector<std::string> out;
  size_t pos = 0;
  while (pos <= s.size()) {
    size_t next = s.find(sep, pos);
    if (next == std::string::npos) next = s.size();
    out.push_back(s.substr(pos, next - pos));
    pos = next + 1;
  }
  return out;
}

// "k=v;k=v" -> map (value may contain ':' and ',')
bool parse_fields(const std::string &s, std::map<std::string, std::string> *out) {
  for (const std::string &item : split(s, ';')) {
    if (item.empty()) continue;
    size_t eq = item.find('=');
    if (eq == std::string::npos) return false;
    (*out)[item.substr(0, eq)] = item.substr(eq + 1);
  }
  return true;
}

bool to_int(const std::string &s, long *out) {
  if (s.empty()) return false;
  try {
    size_t pos = 0;
    *out = std::stol(s, &pos);
    return pos == s.size();
  } catch (...) {
    return false;
  }
}

int write_out(const std::string &s, char *buf, int cap) {
  const int n = static_cast<int>(s.size());
  if (n + 1 > cap) return -1;
  std::memcpy(buf, s.c_str(), n + 1);
  return n;
}

// exit-code semantics parity: utils/train_util.is_retryable_exit_code
bool retryable(long exit_code) { return exit_code > 127; }

struct PodObs {
  long index;
  char phase;  // P R S F U
  long exit_code;  // -1 = unknown
};

}  // namespace

extern "C" {

int tpuop_plan_replica(const char *desc, char *buf, int cap) {
  if (!desc) return -1;
  std::map<std::string, std::string> f;
  if (!parse_fields(desc, &f)) return -1;
  long want = 0, restarts = 0, limit = -1;
  if (!to_int(f["want"], &want) || want < 0) return -1;
  if (!to_int(f["restarts"], &restarts) || restarts < 0) return -1;
  const std::string limit_s = f.count("limit") ? f["limit"] : "-";
  const bool has_limit = limit_s != "-";
  if (has_limit && (!to_int(limit_s, &limit) || limit < 0)) return -1;
  const std::string policy = f.count("policy") ? f["policy"] : "Never";
  if (policy != "Never" && policy != "Always" && policy != "OnFailure" &&
      policy != "ExitCode")
    return -1;

  // bucket: first pod per index wins (Python slot[0] semantics)
  std::map<long, PodObs> by_index;
  std::vector<long> scale_in;  // every observed index >= want, in order
  if (!f["pods"].empty()) {
    for (const std::string &p : split(f["pods"], ',')) {
      if (p.empty()) continue;
      std::vector<std::string> parts = split(p, ':');
      if (parts.size() != 3) return -1;
      PodObs obs;
      if (!to_int(parts[0], &obs.index) || obs.index < 0) return -1;
      if (parts[1].size() != 1 || !strchr("PRSFU", parts[1][0])) return -1;
      obs.phase = parts[1][0];
      obs.exit_code = -1;
      if (parts[2] != "-" && !to_int(parts[2], &obs.exit_code)) return -1;
      if (obs.index >= want) {
        scale_in.push_back(obs.index);
      } else if (!by_index.count(obs.index)) {
        by_index[obs.index] = obs;
      }
    }
  }

  std::string create, restart, fatal;
  bool backoff = false;
  long count = restarts;
  for (long idx = 0; idx < want; ++idx) {
    auto it = by_index.find(idx);
    if (it == by_index.end()) {
      if (!create.empty()) create += ",";
      create += std::to_string(idx);
      continue;
    }
    if (it->second.phase != 'F') continue;
    const long exit_code = it->second.exit_code >= 0 ? it->second.exit_code : 1;
    const bool should_restart =
        policy == "Always" || policy == "OnFailure" ||
        (policy == "ExitCode" && retryable(exit_code));
    if (!should_restart) {
      if (!fatal.empty()) fatal += ",";
      fatal += std::to_string(idx) + ":" + std::to_string(exit_code);
      continue;
    }
    // restart budget check precedes the increment (Python parity:
    // backoff exhaustion aborts the sync's remaining indices)
    if (has_limit && count >= limit) {
      backoff = true;
      break;
    }
    ++count;
    if (!restart.empty()) restart += ",";
    restart += std::to_string(idx) + ":" + std::to_string(exit_code);
  }

  std::string si;
  for (size_t i = 0; i < scale_in.size(); ++i) {
    if (i) si += ",";
    si += std::to_string(scale_in[i]);
  }
  std::string out = "create=" + create + ";scalein=" + si + ";restart=" +
                    restart + ";fatal=" + fatal +
                    ";backoff=" + (backoff ? "1" : "0");
  return write_out(out, buf, cap);
}

int tpuop_eval_success(const char *desc, char *buf, int cap) {
  if (!desc) return -1;
  std::map<std::string, std::string> f;
  if (!parse_fields(desc, &f)) return -1;
  const std::string policy = f.count("policy") ? f["policy"] : "Default";
  if (policy != "Default" && policy != "AllWorkers") return -1;

  struct TypeObs {
    long want = 0, npods = 0, nsucc = 0;
    bool pod0succ = false;
    bool present = false;
  };
  std::map<std::string, TypeObs> types;
  if (!f["types"].empty()) {
    for (const std::string &t : split(f["types"], ',')) {
      if (t.empty()) continue;
      std::vector<std::string> parts = split(t, ':');
      if (parts.size() != 5) return -1;
      TypeObs obs;
      long p0;
      if (!to_int(parts[1], &obs.want) || !to_int(parts[2], &obs.npods) ||
          !to_int(parts[3], &obs.nsucc) || !to_int(parts[4], &p0))
        return -1;
      obs.pod0succ = p0 != 0;
      obs.present = true;
      types[parts[0]] = obs;
    }
  }

  auto fail = [&]() { return write_out("0:", buf, cap); };
  auto ok = [&](const std::string &reason) {
    return write_out("1:" + reason, buf, cap);
  };

  // chief-like decides alone (CHIEF_LIKE order: Chief, Master)
  for (const char *name : {"Chief", "Master"}) {
    if (types.count(name)) {
      if (types[name].pod0succ)
        return ok(std::string(name) + " replica succeeded");
      return fail();
    }
  }

  // worker-like = Worker, TPUSlice with want > 0 (status._worker_like)
  const bool has_worker = types.count("Worker") && types["Worker"].want > 0;
  const bool has_slice = types.count("TPUSlice") && types["TPUSlice"].want > 0;

  if (!has_worker && !has_slice) {
    long npods = 0, nsucc = 0;
    for (const auto &kv : types) {
      npods += kv.second.npods;
      nsucc += kv.second.nsucc;
    }
    if (npods > 0 && nsucc == npods) return ok("all replicas succeeded");
    return fail();
  }

  if (policy == "AllWorkers") {
    if (has_worker && types["Worker"].nsucc < types["Worker"].want)
      return fail();
    if (has_slice && types["TPUSlice"].nsucc < types["TPUSlice"].want)
      return fail();
    return ok("all workers succeeded");
  }

  if (has_slice) {
    if (types["TPUSlice"].nsucc < types["TPUSlice"].want) return fail();
    if (!has_worker) return ok("all slice members succeeded");
    if (types["Worker"].pod0succ)
      return ok("all slice members and worker 0 succeeded");
    return fail();
  }

  if (types.count("Worker") && types["Worker"].pod0succ)
    return ok("worker 0 succeeded");
  return fail();
}

}  // extern "C"
