// TF_CONFIG generation — native twin of bootstrap/cluster_spec.py
// (reference crown jewel: genTFConfigJSONStr/genClusterSpec, SURVEY.md §2).
// Emits byte-identical JSON to Python's json.dumps(..., sort_keys=True)
// for the DNS-resolver path; tests/test_native.py golden-checks equality.

#include "tpuop.h"

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Role {
  std::string name;  // lowercased role, e.g. "worker"
  int count = 0;
  int port = 0;
};

// parse "chief=1:2222,worker=4:2222"; returns false on malformed input
bool parse_replicas(const char *s, std::vector<Role> *out) {
  std::string in(s ? s : "");
  size_t pos = 0;
  while (pos < in.size()) {
    size_t comma = in.find(',', pos);
    if (comma == std::string::npos) comma = in.size();
    std::string item = in.substr(pos, comma - pos);
    pos = comma + 1;
    if (item.empty()) continue;
    size_t eq = item.find('=');
    size_t colon = item.find(':', eq == std::string::npos ? 0 : eq);
    if (eq == std::string::npos || colon == std::string::npos) return false;
    Role r;
    r.name = item.substr(0, eq);
    // strict integer parse: stoi's partial parsing would silently accept
    // garbage like "2x"; require the whole token to be consumed
    const std::string count_s = item.substr(eq + 1, colon - eq - 1);
    const std::string port_s = item.substr(colon + 1);
    try {
      size_t pos = 0;
      r.count = std::stoi(count_s, &pos);
      if (pos != count_s.size()) return false;
      r.port = std::stoi(port_s, &pos);
      if (pos != port_s.size()) return false;
    } catch (...) {
      return false;
    }
    if (r.count < 0 || r.port <= 0 || r.name.empty()) return false;
    out->push_back(std::move(r));
  }
  return true;
}

// JSON is built by concatenation with no escaping, so every interpolated
// string must be JSON-safe; names outside the DNS-safe set are rejected
// (the caller falls back to the Python generator, which escapes).
bool dns_safe(const std::string &s) {
  if (s.empty()) return false;
  for (char c : s) {
    if (!((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
          (c >= '0' && c <= '9') || c == '-' || c == '.'))
      return false;
  }
  return true;
}

std::string address(const std::string &job, const std::string &ns,
                    const std::string &role, int idx, int port) {
  // <job>-<type>-<idx>.<namespace>.svc:<port> — the naming contract
  // shared with the service reconciler (api.types.replica_name)
  return job + "-" + role + "-" + std::to_string(idx) + "." + ns +
         ".svc:" + std::to_string(port);
}

}  // namespace

extern "C" {

int tpuop_gen_tf_config(const char *job, const char *ns, const char *replicas,
                        const char *task_type, int index, int sparse,
                        char *buf, int cap) {
  if (!job || !ns || !task_type || index < 0) return -1;
  if (!dns_safe(job) || !dns_safe(ns) || !dns_safe(task_type)) return -1;
  std::vector<Role> roles;
  if (!parse_replicas(replicas, &roles)) return -1;
  for (const Role &r : roles)
    if (!dns_safe(r.name)) return -1;
  // json.dumps(sort_keys=True): cluster roles alphabetical
  std::sort(roles.begin(), roles.end(),
            [](const Role &a, const Role &b) { return a.name < b.name; });

  const std::string ttype(task_type);
  const bool sparse_role =
      sparse && (ttype == "worker" || ttype == "evaluator");
  int task_index = sparse_role ? 0 : index;

  std::string out = "{\"cluster\": {";
  bool first = true;
  for (const Role &r : roles) {
    if (!first) out += ", ";
    first = false;
    out += "\"" + r.name + "\": [";
    if (sparse_role && r.name == ttype) {
      if (index >= r.count) return -1;
      out += "\"" + address(job, ns, r.name, index, r.port) + "\"";
    } else {
      for (int i = 0; i < r.count; ++i) {
        if (i) out += ", ";
        out += "\"" + address(job, ns, r.name, i, r.port) + "\"";
      }
    }
    out += "]";
  }
  out += "}, \"environment\": \"cloud\", \"task\": {\"index\": " +
         std::to_string(task_index) + ", \"type\": \"" + ttype + "\"}}";

  const int n = static_cast<int>(out.size());
  if (n + 1 > cap) return -1;
  std::memcpy(buf, out.c_str(), n + 1);
  return n;
}

}  // extern "C"
