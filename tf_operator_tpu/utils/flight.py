"""Black-box flight recorder: what was this process doing just before
it wedged or died?

The tracing/metrics/logging subsystems answer "how is the system doing"
while someone is watching.  This module answers the postmortem
question: bounded ring buffers of the most recent finished spans, log
records, and metric-delta snapshots are kept process-wide at ~zero
cost, and ``dump()`` writes them as one JSONL snapshot at the moment of
failure — wired to SIGTERM, the fatal-exception hook, the stall
watchdog (utils/watchdog.py), and the ``/debug/flightrecorder``
endpoints on the operator monitoring port, the kubesim apiserver, and
serve_lm.

Everything here is host-side bookkeeping (appends to deques under a
lock); nothing touches the device, so the PR-4 no-hot-sync invariant is
untouched by recording from the training loop.

Determinism contract (test-pinned): ``records()``/``dump()`` emit a
single ``meta`` record followed by spans, then logs, then metric
deltas, each oldest-first; two dumps with no intervening activity are
identical except the meta record's wall-clock fields.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


def _ring_log_handler(recorder: "FlightRecorder"):
    """logging.Handler appending formatted records to the recorder's
    log ring (the Handler subclass is defined lazily so importing this
    module does not import logging config)."""

    import logging

    class Handler(logging.Handler):
        def emit(self, record):
            try:
                recorder.record_log(
                    level=record.levelname,
                    logger=record.name,
                    message=record.getMessage(),
                    fields=getattr(record, "fields", None),
                )
            except Exception:  # a recorder bug must never kill logging
                # counted, not logged: logging from a failing log
                # handler would recurse
                recorder._count_ring_errors()

    return Handler()


class FlightRecorder:
    """Bounded rings of recent spans / logs / metric deltas + dump().

    Attach points (all optional, all chainable):
      - ``attach_tracer(tracer)``: chains onto ``tracer.on_finish`` so
        every finished span's dict lands in the span ring;
      - ``attach_logger(logger)``: adds a ring handler to a stdlib
        logger (default: the ``tpujob`` root);
      - ``attach_metrics(metrics)``: remembers the registry so
        ``snapshot_metrics()`` can record counter/gauge deltas.
    """

    def __init__(
        self,
        max_spans: int = 256,
        max_logs: int = 512,
        max_snapshots: int = 32,
        max_requests: int = 16,
        max_arena_samples: int = 64,
    ):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max_spans)
        self._logs: deque = deque(maxlen=max_logs)
        self._snapshots: deque = deque(maxlen=max_snapshots)
        self._metrics = None
        self._last_counters: Dict[str, float] = {}
        self._dumps = 0
        #: ISSUE 11: serving-plane attach points — request autopsies
        #: (models/batching.RequestLog) and arena occupancy timelines
        #: (models/kv_blocks.ArenaTimeline).  Bounded deques: tests
        #: build handlers by the dozen against the process-global
        #: recorder, so stale sources age out instead of accumulating.
        self.max_requests = int(max_requests)
        self.max_arena_samples = int(max_arena_samples)
        self._request_logs: deque = deque(maxlen=8)
        self._arena_timelines: deque = deque(maxlen=8)
        #: recorder-internal failures (ring-handler emit errors, dump
        #: source errors) — surfaced in the dump meta record rather
        #: than swallowed.  Counted through _count_ring_errors so two
        #: concurrent dumps cannot lose an increment.
        self.ring_errors = 0

    def _count_ring_errors(self, n: int = 1) -> None:
        with self._lock:
            self.ring_errors += n

    # -- recording ----------------------------------------------------------

    def record_span(self, span) -> None:
        """Append one finished span (a Span or its dict)."""

        d = span.to_dict() if hasattr(span, "to_dict") else dict(span)
        with self._lock:
            self._spans.append(d)

    def record_log(
        self,
        level: str,
        logger: str,
        message: str,
        fields: Optional[Dict[str, Any]] = None,
    ) -> None:
        rec = {"level": level, "logger": logger, "message": message,
               "unix": time.time()}
        if fields:
            rec["fields"] = dict(fields)
        with self._lock:
            self._logs.append(rec)

    def snapshot_metrics(self, label: str = "") -> Dict[str, float]:
        """Record the delta of every counter/gauge since the previous
        snapshot (first call records absolute values).  Returns the
        delta dict.  No-op ({}) without an attached registry."""

        if self._metrics is None:
            return {}
        now = self._metrics.counters_snapshot()
        with self._lock:
            delta = {
                k: round(v - self._last_counters.get(k, 0.0), 6)
                for k, v in now.items()
                if v != self._last_counters.get(k, 0.0)
            }
            self._last_counters = now
            self._snapshots.append(
                {"label": label, "unix": time.time(), "delta": delta}
            )
        return delta

    # -- attach points ------------------------------------------------------

    def attach_tracer(self, tracer) -> None:
        prev = tracer.on_finish

        def chained(span):
            self.record_span(span)
            if prev is not None:
                prev(span)

        tracer.on_finish = chained

    def attach_logger(self, logger=None) -> None:
        import logging

        if logger is None:
            logger = logging.getLogger("tpujob")
        logger.addHandler(_ring_log_handler(self))

    def attach_metrics(self, metrics) -> None:
        self._metrics = metrics

    def attach_request_log(self, log) -> None:
        """Register a serving RequestLog: every dump carries its
        last-K request autopsies, so a post-mortem names the requests
        in flight when the episode fired (ISSUE 11 bugfix)."""

        with self._lock:
            self._request_logs.append(log)

    def attach_arena_timeline(self, timeline) -> None:
        """Register a KV-arena occupancy timeline: every dump carries
        its sample tail — the pressure history leading into the
        failure, not just the final gauge value."""

        with self._lock:
            self._arena_timelines.append(timeline)

    # -- export -------------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """meta + spans + logs + metric snapshots + request autopsies
        + arena timelines, oldest-first within each section — the
        exact dump order (determinism contract).  The serving sections
        appear only when sources are attached and non-empty."""

        with self._lock:
            spans = list(self._spans)
            logs = list(self._logs)
            snaps = list(self._snapshots)
            dumps = self._dumps
            request_logs = list(self._request_logs)
            timelines = list(self._arena_timelines)
        source_errors = 0
        requests: List[Dict[str, Any]] = []
        for log in request_logs if self.max_requests > 0 else []:
            try:
                requests.extend(log.recent(self.max_requests))
            except Exception:  # a source bug must never kill a dump
                source_errors += 1
        # time-merge across logs BEFORE truncating: a plain per-log
        # concatenation would let the last-attached replica's entries
        # crowd every other replica out of the K-slot tail
        requests.sort(key=lambda e: e.get("submit_unix", 0.0))
        requests = requests[-self.max_requests:] if requests else []
        arenas: List[Dict[str, Any]] = []
        for tl in timelines if self.max_arena_samples > 0 else []:
            try:
                snap = tl.snapshot(self.max_arena_samples)
                if snap["samples"]:
                    arenas.append(snap)
            except Exception:
                source_errors += 1
        if source_errors:
            self._count_ring_errors(source_errors)
        meta = {
            "type": "meta",
            "pid": os.getpid(),
            "unix": time.time(),
            "spans": len(spans),
            "logs": len(logs),
            "metricSnapshots": len(snaps),
            "requests": len(requests),
            "arenaTimelines": len(arenas),
            "priorDumps": dumps,
            "ringErrors": self.ring_errors,
        }
        out: List[Dict[str, Any]] = [meta]
        out.extend({"type": "span", **s} for s in spans)
        out.extend({"type": "log", **r} for r in logs)
        out.extend({"type": "metrics", **s} for s in snaps)
        out.extend({"type": "request", **r} for r in requests)
        out.extend({"type": "arena", **a} for a in arenas)
        return out

    def dump(self, fileobj=None, path: Optional[str] = None, reason: str = "") -> str:
        """Write the JSONL snapshot.  With ``path`` (or neither arg) a
        file under ``$TPUJOB_FLIGHT_DIR`` (default /tmp) is created and
        its path returned; with ``fileobj`` the lines stream there and
        the return value is "".  Never raises — a dying process calls
        this from signal/excepthook context."""

        try:
            records = self.records()
            if reason:
                records[0]["reason"] = reason
            with self._lock:
                self._dumps += 1
                # the filename seq is THIS dump's increment: reading
                # self._dumps outside the lock would let two concurrent
                # dumps (two worker threads dying at once) compute the
                # same path and overwrite one postmortem
                seq = self._dumps
            if fileobj is not None:
                for r in records:
                    fileobj.write(json.dumps(r) + "\n")
                return ""
            if path is None:
                d = os.environ.get("TPUJOB_FLIGHT_DIR", "/tmp")
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d,
                    f"flight-{os.getpid()}-{seq}"
                    f"{'-' + reason if reason else ''}.jsonl",
                )
            with open(path, "w") as f:
                for r in records:
                    f.write(json.dumps(r) + "\n")
            return path
        except Exception:  # noqa: BLE001 - crash-path best effort
            return ""

    def dump_text(self) -> str:
        """The JSONL snapshot as one string (the HTTP endpoints)."""

        return "\n".join(json.dumps(r) for r in self.records()) + "\n"


#: process-global default (mirrors metrics/tracer defaults): the HTTP
#: debug endpoints and the watchdog read this instance
default_recorder = FlightRecorder()

_installed: Optional[FlightRecorder] = None
_install_lock = threading.Lock()


def install(
    recorder: Optional[FlightRecorder] = None,
    tracer=None,
    metrics=None,
    logger=None,
    signals: bool = True,
    excepthook: bool = True,
) -> FlightRecorder:
    """Register the recorder process-wide: tracer + logger + metrics
    attach, SIGTERM chains a dump before the previous handler runs,
    and a fatal (uncaught) exception dumps from sys.excepthook.
    Idempotent — a second install is a no-op that returns whichever
    recorder was ACTUALLY wired first (never an unwired argument)."""

    global _installed
    rec = recorder if recorder is not None else default_recorder
    with _install_lock:
        if _installed is not None:
            return _installed
        # wire UNDER the lock and publish only on success: a concurrent
        # install() must never be handed a recorder whose attaches
        # haven't run yet, and a wiring failure (e.g. signal.signal in
        # a restricted environment) must leave the slot free instead of
        # pinning a half-wired recorder forever
        _wire(rec, tracer, metrics, logger, signals, excepthook)
        _installed = rec
    return rec


def _wire(rec, tracer, metrics, logger, signals, excepthook) -> None:
    # the FALLIBLE wiring (signal.signal can raise in restricted
    # environments) runs FIRST so a failed install leaves nothing
    # attached — a retry then cannot chain on_finish / ring handlers
    # twice; the attaches at the bottom are plain assignments
    if signals and threading.current_thread() is threading.main_thread():
        prev_term = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            rec.dump(reason="sigterm")
            if callable(prev_term):
                prev_term(signum, frame)
            elif prev_term == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, on_term)

    if excepthook:
        prev_hook = sys.excepthook

        def on_fatal(exc_type, exc, tb):
            rec.record_log(
                "FATAL", "excepthook", f"{exc_type.__name__}: {exc}"
            )
            rec.dump(reason="fatal")
            prev_hook(exc_type, exc, tb)

        sys.excepthook = on_fatal

        # most of this process's work runs on THREADS (watch loops,
        # kubelet sim, reconcile workers) — sys.excepthook never fires
        # for those; threading.excepthook does
        prev_thread_hook = threading.excepthook

        def on_thread_fatal(args):
            rec.record_log(
                "FATAL", "threading.excepthook",
                f"{args.exc_type.__name__}: {args.exc_value} "
                f"(thread {getattr(args.thread, 'name', '?')})",
            )
            rec.dump(reason="fatal-thread")
            prev_thread_hook(args)

        threading.excepthook = on_thread_fatal

    from tf_operator_tpu.utils.metrics import default_metrics
    from tf_operator_tpu.utils.trace import default_tracer

    rec.attach_tracer(tracer if tracer is not None else default_tracer)
    rec.attach_logger(logger)
    rec.attach_metrics(metrics if metrics is not None else default_metrics)
