"""Version-compat shims for jax API churn — ONE copy, shared.

jax >= 0.8 moved shard_map to the top level and renamed the
replication-check kwarg (check_rep -> check_vma); older jax has the
experimental path.  Every shard_map call site in the repo goes through
``shard_map_unchecked`` so the next rename is a one-line fix.
"""

from __future__ import annotations

try:  # jax >= 0.8
    from jax import shard_map as _shard_map

    _CHECK_KW = {"check_vma": False}
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = {"check_rep": False}


def shard_map_unchecked(f, *, mesh, in_specs, out_specs):
    """shard_map with replication checking off (collective outputs the
    checker cannot prove replicated — psum-broadcast results etc.)."""

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **_CHECK_KW
    )


def shard_map_partial_auto(f, *, mesh, in_specs, out_specs, auto):
    """Partial-auto shard_map: manual only over the axes the specs
    name, ``auto`` axes keep global (GSPMD) semantics inside the body —
    sharding constraints over auto axes are legal there, collectives
    only over the manual ones.  The multi-slice grad sync
    (parallel/collectives.py) is manual over the DCN axis and auto over
    every intra-slice axis.  Replication checking off, like
    ``shard_map_unchecked`` (psum outputs the checker cannot prove)."""

    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        auto=frozenset(auto),
        **_CHECK_KW,
    )
