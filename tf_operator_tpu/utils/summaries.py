"""Step-series training metrics — the mnist_with_summaries analogue.

Parity: the reference's `examples/v1/mnist_with_summaries` writes
TensorBoard summaries (SURVEY.md §2 row "Examples: mnist_with_summaries");
the TPU-native equivalent is a dependency-free JSON-lines series the
Trainer emits and the operator surfaces (`tpujob describe --metrics`,
dashboard detail pane, `/apis/.../metrics` endpoint).

Format: one file per process, `metrics-<process_id>.jsonl`, one JSON
object per line: `{"step": N, "time": <unix>, "loss": ..., ...}`.
Scalars only; values are floats.  The writing process appends + flushes
per line so a reader (the operator, a plotting script) can tail live.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional

#: job annotation naming the summary directory; the operator's job API
#: serves the series from here (trust note: the submitter controls this
#: path and the operator reads it — same trust domain as pod commands,
#: see docs/TRUST.md)
ANNOTATION_SUMMARY_DIR = "tpujob.dist/summary-dir"


class SummaryWriter:
    """Append-only JSON-lines scalar series for one process."""

    def __init__(self, directory: str, process_id: int = 0):
        self.directory = directory
        self.process_id = process_id
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"metrics-{process_id}.jsonl")
        self._f = open(self.path, "a", buffering=1)  # line-buffered

    def write(self, step: int, **scalars: float) -> None:
        rec: Dict[str, float] = {"step": int(step), "time": time.time()}
        for k, v in scalars.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                continue  # non-scalar metric: skip, never crash training
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def latest_checkpoint_time(
    directory: str, series: Optional[List[dict]] = None
) -> Optional[float]:
    """Newest ``checkpoint_time_unix`` value in the series, or None.

    This is how a POD-scope durability stamp crosses the process
    boundary: the checkpointer stamps ``checkpoint_last_success_unix``
    on its own process registry (parallel/checkpoint.py), the trainer
    republishes it into the summary series at each summary interval,
    and the operator — a different process — reads it here for the
    health rollup's ``lastCheckpointAgeSeconds`` and the autoscaler's
    resize safety gate (closing the process-scope gap documented in
    docs/ARCHITECTURE.md).  Pass ``series`` (an already-read
    ``read_series`` tail) to reuse one disk read across consumers —
    the health rollup reads the tail once for this AND throughput."""

    if series is None:
        series = read_series(directory, limit=50)
    for rec in reversed(series):
        v = rec.get("checkpoint_time_unix")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def read_series(directory: str, limit: Optional[int] = None) -> List[dict]:
    """Merge every process's series, ordered by (step, time).

    Malformed lines (a writer crashed mid-line) are skipped.  ``limit``
    keeps only the most recent N records after merging.
    """

    records: List[dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "metrics-*.jsonl"))):
        try:
            with open(path, "rb") as f:
                if limit is not None:
                    # bounded read: tail enough bytes for `limit` records
                    # (~300 B/record) instead of parsing the whole file
                    # on every dashboard poll
                    budget = max(4096, 400 * limit)
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - budget))
                    data = f.read()
                    if size > budget:
                        # drop the first, possibly partial, line
                        data = data.split(b"\n", 1)[-1]
                else:
                    data = f.read()
            for line in data.decode(errors="replace").splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "step" in rec:
                    records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("step", 0), r.get("time", 0.0)))
    if limit is not None and len(records) > limit:
        records = records[-limit:]
    return records
