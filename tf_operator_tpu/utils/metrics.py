"""Metrics registry.

Parity: the reference's Prometheus counters (jobs created/succeeded/
failed/restarted) + the driver-defined job-startup-latency metric
(SURVEY.md §5, §6).  In-proc counters/histograms with a Prometheus-style
text exposition (servable later; no network dependency here).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = defaultdict(float)
        self._observations: Dict[str, List[float]] = defaultdict(list)

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._observations[name].append(value)

    def counter(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def summary(self, name: str) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._observations.get(name, []))
        if not vals:
            return {"count": 0}
        return {
            "count": len(vals),
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "p50": vals[len(vals) // 2],
            "p99": vals[min(len(vals) - 1, int(len(vals) * 0.99))],
        }

    def exposition(self) -> str:
        """Prometheus text format."""

        lines = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                label_s = ",".join(f'{k}="{v2}"' for k, v2 in labels)
                lines.append(f"{name}{{{label_s}}} {v}" if label_s else f"{name} {v}")
            for name, vals in sorted(self._observations.items()):
                lines.append(f"{name}_count {len(vals)}")
                lines.append(f"{name}_sum {sum(vals)}")
        return "\n".join(lines) + "\n"


#: process-global default registry (controller accepts an override)
default_metrics = Metrics()
