"""Metrics registry.

Parity: the reference's Prometheus counters (jobs created/succeeded/
failed/restarted) + the driver-defined job-startup-latency metric
(SURVEY.md §5, §6).  In-proc counters/histograms with a Prometheus-style
text exposition (servable later; no network dependency here).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple


#: default histogram buckets (seconds) — sync/span durations
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = defaultdict(float)
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._observations: Dict[str, List[float]] = defaultdict(list)
        #: name -> (buckets, counts[len(buckets)+1], sum, count)
        self._histograms: Dict[str, list] = {}
        #: name -> trace id of the most recent exemplar-carrying inc —
        #: the counter→trace link (OpenMetrics-exemplar-style): "this
        #: client has 14 errors" becomes "...and HERE is one of them"
        self._exemplars: Dict[str, str] = {}

    def inc(
        self, name: str, value: float = 1.0, *,
        exemplar: "str | None" = None, **labels: str,
    ) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value
            if exemplar:
                self._exemplars[name] = str(exemplar)

    def exemplar(self, name: str) -> "str | None":
        """Trace id recorded with the most recent increment of ``name``
        (None when no exemplar-carrying inc has happened)."""

        with self._lock:
            return self._exemplars.get(name)

    def set(self, name: str, value: float, **labels: str) -> None:
        """Gauge write (last-value-wins) — e.g. the API clients' last-
        error timestamps (backend/retry.py)."""

        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = float(value)

    def gauge(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key, 0.0)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._observations[name].append(value)

    def observe_histogram(
        self, name: str, value: float, buckets: Tuple[float, ...] = DEFAULT_BUCKETS
    ) -> None:
        """Bounded-memory histogram (Prometheus bucket semantics) — use
        for unbounded-cardinality series like per-sync durations, where
        the raw-observation list of ``observe`` would leak."""

        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = [buckets, [0] * (len(buckets) + 1), 0.0, 0]
                self._histograms[name] = h
            bks, counts, _, _ = h
            i = 0
            while i < len(bks) and value > bks[i]:
                i += 1
            counts[i] += 1
            h[2] += value
            h[3] += 1

    def histogram(self, name: str) -> Dict[str, float]:
        """Summary view of a histogram: count, sum, approx p50/p99
        (upper bucket bounds)."""

        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                return {"count": 0}
            bks, counts, total, n = h[0], list(h[1]), h[2], h[3]

        def quantile(q: float) -> float:
            target = q * n
            acc = 0
            for i, c in enumerate(counts):
                acc += c
                if acc >= target:
                    return bks[i] if i < len(bks) else float("inf")
            return float("inf")

        return {
            "count": n,
            "sum": total,
            "mean": total / n if n else 0.0,
            "p50_le": quantile(0.5),
            "p99_le": quantile(0.99),
        }

    def counter(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def total(self, name: str) -> float:
        """Sum of one counter across all of its label sets (e.g. every
        client's api_client_retries_total)."""

        with self._lock:
            return sum(
                v for (n, _), v in self._counters.items() if n == name
            )

    def summary(self, name: str) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._observations.get(name, []))
        if not vals:
            return {"count": 0}
        return {
            "count": len(vals),
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "p50": vals[len(vals) // 2],
            "p99": vals[min(len(vals) - 1, int(len(vals) * 0.99))],
        }

    def exposition(self) -> str:
        """Prometheus text format."""

        lines = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                label_s = ",".join(f'{k}="{v2}"' for k, v2 in labels)
                lines.append(f"{name}{{{label_s}}} {v}" if label_s else f"{name} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                label_s = ",".join(f'{k}="{v2}"' for k, v2 in labels)
                lines.append(f"{name}{{{label_s}}} {v}" if label_s else f"{name} {v}")
            for name, vals in sorted(self._observations.items()):
                lines.append(f"{name}_count {len(vals)}")
                lines.append(f"{name}_sum {sum(vals)}")
            for name, (bks, counts, total, n) in sorted(self._histograms.items()):
                acc = 0
                for i, b in enumerate(bks):
                    acc += counts[i]
                    lines.append(f'{name}_bucket{{le="{b}"}} {acc}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {n}')
                lines.append(f"{name}_sum {total}")
                lines.append(f"{name}_count {n}")
            # exemplar links as comments: Prometheus text parsers skip
            # them, the dashboard reads them to deep-link error
            # counters to their trace waterfalls
            for name, tid in sorted(self._exemplars.items()):
                lines.append(f'# exemplar {name} trace_id="{tid}"')
        return "\n".join(lines) + "\n"


class DispatchLedger:
    """Device-dispatch accounting for the serving hot path.

    On this box every device call rides a network tunnel whose
    host↔device round trip (~66 ms, measured — benchmarks/PROFILE.md
    "r5 serving") dwarfs the device math it orchestrates, so serving
    walls decompose as ``dispatch count × RTT + device time``.  The
    ledger turns that claim into an auditable number: every serving
    decoder wraps each compiled-program call in ``dispatch(phase)``,
    which counts it and measures the wall time of dispatch + any
    in-block host fetch.  Dispatch COUNTS are platform-independent
    (the same program structure runs everywhere); the measured
    per-dispatch seconds are this box's RTT+device share.

    Phases are free-form strings; the serving convention is
    ``admission`` (the pool's fused prefill+sample+seat program),
    ``prefill`` / ``scatter`` (the pool's legacy rolling-window path
    and the chunked decoder's prompt chunks), ``step`` (the pool's
    K-step sync), ``decode`` (the chunked decoder's budget loop),
    ``generate`` (speculative's fused whole-generation program),
    ``round`` / ``chunk`` (speculative's host-driven and scan
    drivers).

    Optional sinks, both None-safe:
      - ``metrics``: every dispatch increments
        ``serving_dispatch_total{phase=...}`` and observes
        ``serving_dispatch_seconds_<phase>`` (bounded histogram), so
        ``/metrics`` exports the ledger live;
      - ``tracer``: when the calling thread is inside a trace (e.g. a
        serve_lm request span), each dispatch records a child span
        ``dispatch.<phase>`` — the per-request waterfall shows where
        the round trips went.  Pool dispatches run on the driver
        thread, outside any request context; they carry their request
        id as a span attribute instead (see docs/ARCHITECTURE.md
        "serving dispatch accounting").
    """

    def __init__(
        self,
        metrics: "Metrics | None" = None,
        tracer=None,
        prefix: str = "serving_dispatch",
    ):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = defaultdict(int)
        self._seconds: Dict[str, float] = defaultdict(float)
        self.metrics = metrics
        self.tracer = tracer
        self.prefix = prefix

    def record(self, phase: str, seconds: float, n: int = 1) -> None:
        with self._lock:
            self._counts[phase] += n
            self._seconds[phase] += seconds
        if self.metrics is not None:
            self.metrics.inc(f"{self.prefix}_total", float(n), phase=phase)
            self.metrics.observe_histogram(
                f"{self.prefix}_seconds_{phase}", seconds
            )

    @contextlib.contextmanager
    def dispatch(self, phase: str, **attrs: Any):
        """``with ledger.dispatch("step"): fn(...)`` — count one device
        dispatch and time the block (include the host fetch of any
        value you need, so the measured seconds cover the full round
        trip, not just the async enqueue)."""

        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                f"dispatch.{phase}", kind="client", attributes=attrs or None
            )
            span.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as exc:
            # a failed device call must show as a FAILED span — error
            # status is what tail sampling protects; closing it ok
            # would get the one trace worth keeping evicted
            if span is not None:
                span.__exit__(type(exc), exc, exc.__traceback__)
                span = None
            raise
        finally:
            dt = time.perf_counter() - t0
            if span is not None:
                span.__exit__(None, None, None)
            self.record(phase, dt)

    # -- reads -------------------------------------------------------------

    def count(self, phase: Optional[str] = None) -> int:
        with self._lock:
            if phase is not None:
                return self._counts.get(phase, 0)
            return sum(self._counts.values())

    def seconds(self, phase: Optional[str] = None) -> float:
        with self._lock:
            if phase is not None:
                return self._seconds.get(phase, 0.0)
            return sum(self._seconds.values())

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{phase: {count, seconds, mean_ms}} — the machine-readable
        ledger benchmarks embed in their JSON output."""

        with self._lock:
            return {
                phase: {
                    "count": n,
                    "seconds": round(self._seconds[phase], 4),
                    "mean_ms": round(self._seconds[phase] / n * 1e3, 2),
                }
                for phase, n in sorted(self._counts.items())
                if n
            }

    def table(self, wall: Optional[float] = None) -> str:
        """Markdown ledger table: phase | dispatches | mean RTT | total.
        With ``wall``, appends the accounting row — dispatch seconds vs
        wall, i.e. how much of the wall the round trips explain."""

        lines = [
            "| phase | dispatches | mean ms/dispatch | total s |",
            "|---|---|---|---|",
        ]
        snap = self.snapshot()
        for phase, row in snap.items():
            lines.append(
                f"| {phase} | {row['count']} | {row['mean_ms']} "
                f"| {row['seconds']} |"
            )
        total_n = sum(r["count"] for r in snap.values())
        total_s = sum(r["seconds"] for r in snap.values())
        tail = f"| **all** | {total_n} | — | {round(total_s, 4)} |"
        if wall is not None and wall > 0:
            tail = (
                f"| **all** | {total_n} | — | {round(total_s, 4)} "
                f"(= {total_s / wall:.0%} of {round(wall, 3)} s wall) |"
            )
        lines.append(tail)
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._seconds.clear()


#: process-global default registry (controller accepts an override)
default_metrics = Metrics()
