"""Metrics registry.

Parity: the reference's Prometheus counters (jobs created/succeeded/
failed/restarted) + the driver-defined job-startup-latency metric
(SURVEY.md §5, §6).  In-proc counters/histograms with a Prometheus-style
text exposition (servable later; no network dependency here).
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple


#: default histogram buckets (seconds) — sync/span durations
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: serving-SLO buckets (seconds): user-facing latencies stretch past the
#: sync-duration range (a 256-token generate on a tunneled chip is tens
#: of seconds), so the SLO families get a longer tail
SLO_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: the serving DispatchLedger phase taxonomy — the closed set of
#: literal phases the serving decoders pass to ``ledger.dispatch()``.
#: Every phase here lowers to a ``dispatch.<phase>`` trace span (the
#: ledger derives the span name), and the request-autopsy /
#: waterfall layers key on those literal names, so a renamed phase
#: would silently orphan them.  tests/test_alert_rules_lint.py walks
#: the package AST and pins the emitted literals against this tuple
#: in BOTH directions (ISSUE 11 satellite).
DISPATCH_PHASES = (
    "admission",  # pool fused prefill+sample+seat (one program)
    "prefill",    # legacy/chunked prompt chunks; speculative prefills
    "sample",     # legacy pool first-token sample
    "scatter",    # legacy pool seating scatter
    "step",       # pool K-step decode window; speculative host driver
    "retire",     # paged pool batched device-state reset
    "swap_out",   # preemption: victim block gather + rng fetch (ISSUE 12)
    "swap_in",    # resume: swapped-block upload + device-row restore
    "migrate_out",  # prefill replica: prompt-block gather → fabric (ISSUE 13)
    "migrate_in",   # decode replica: fabric-block upload into its arena
    "decode",     # chunked decoder budget loop
    "generate",   # speculative fused whole-generation program
    "round",      # speculative host-driven round loop
    "chunk",      # speculative scan driver
    "draft",      # paged speculative: draft prefill + K+1-step draft scan
    "verify",     # paged speculative: one multi-query target dispatch
)


def finite_summary(summary: Dict[str, float]) -> Dict[str, Any]:
    """JSON-safe histogram summary for the /slo endpoints: a quantile
    landing in the overflow bucket is ``float('inf')``, which
    ``json.dumps`` would emit as the non-JSON token ``Infinity`` and
    break strict parsers — map non-finite floats to None."""

    return {
        k: (None if isinstance(v, float) and not math.isfinite(v) else v)
        for k, v in summary.items()
    }


def _escape_label(value) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition line is
    unparseable (the strict-parse test enforces this round-trips)."""

    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return ",".join(parts)


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = defaultdict(float)
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._observations: Dict[str, List[float]] = defaultdict(list)
        #: (name, labels) -> [buckets, counts[len(buckets)+1], sum, count]
        self._histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], list] = {}
        #: per-family bucket config (set_buckets): consulted when a
        #: family's first observation arrives without explicit buckets
        self._family_buckets: Dict[str, Tuple[float, ...]] = {}
        #: name -> trace id of the most recent exemplar-carrying inc —
        #: the counter→trace link (OpenMetrics-exemplar-style): "this
        #: client has 14 errors" becomes "...and HERE is one of them"
        self._exemplars: Dict[str, str] = {}
        #: family name -> HELP text (describe()); families without one
        #: get an auto-generated line — the exposition emits # HELP and
        #: # TYPE for EVERY family either way (strict-parse pinned)
        self._help: Dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        """Attach a ``# HELP`` line to a family.  Optional — families
        never described still get an auto HELP plus the correct
        ``# TYPE`` in the exposition."""

        with self._lock:
            self._help[name] = str(help_text)

    def inc(
        self, name: str, value: float = 1.0, *,
        exemplar: "str | None" = None, **labels: str,
    ) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value
            if exemplar:
                self._exemplars[name] = str(exemplar)

    def exemplar(self, name: str) -> "str | None":
        """Trace id recorded with the most recent increment of ``name``
        (None when no exemplar-carrying inc has happened)."""

        with self._lock:
            return self._exemplars.get(name)

    def set(self, name: str, value: float, **labels: str) -> None:
        """Gauge write (last-value-wins) — e.g. the API clients' last-
        error timestamps (backend/retry.py)."""

        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = float(value)

    def gauge(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key, 0.0)

    def clear_gauge(self, name: str, **labels: str) -> None:
        """Drop every series of gauge ``name`` whose labels contain
        ``labels`` (subset match; no labels = the whole family).
        Per-object gauges (autoscaler_desired_replicas{job=}) must not
        outlive their object — a deleted job exporting a stale desired
        count forever is a lie, and per-object label sets otherwise
        grow monotonically across churn."""

        with self._lock:
            for key in [
                k
                for k in self._gauges
                if k[0] == name
                and all(dict(k[1]).get(n) == str(v) for n, v in labels.items())
            ]:
                del self._gauges[key]

    def clear_counter(self, name: str, **labels: str) -> None:
        """Drop every series of counter ``name`` whose labels contain
        ``labels`` (subset match; no labels = the whole family) — the
        counter twin of ``clear_gauge``.  Exists for FEDERATED series
        (controller/telemetry.py): a counter mirrored from a pod that
        died must be swept, not exported frozen forever."""

        with self._lock:
            for key in [
                k
                for k in self._counters
                if k[0] == name
                and all(dict(k[1]).get(n) == str(v) for n, v in labels.items())
            ]:
                del self._counters[key]

    def clear_histogram(self, name: str, **labels: str) -> None:
        """``clear_gauge`` semantics for histogram series (federated
        staleness sweep)."""

        with self._lock:
            for key in [
                k
                for k in self._histograms
                if k[0] == name
                and all(dict(k[1]).get(n) == str(v) for n, v in labels.items())
            ]:
                del self._histograms[key]

    def merge_histogram(
        self,
        name: str,
        buckets: Tuple[float, ...],
        counts: List[int],
        sum_delta: float,
        count_delta: int,
        **labels: str,
    ) -> None:
        """Add pre-bucketed observations into one histogram series —
        the federation write (``counts`` has len(buckets)+1 per-bucket
        deltas, NOT cumulative).  Same-bucket series sum elementwise; a
        bucket-boundary mismatch REPLACES the series (the source pod
        restarted with a different config — summing positionally would
        lie, exactly the ``histogram_family_merged`` rule)."""

        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        bks = tuple(buckets)
        with self._lock:
            h = self._histograms.get(key)
            if h is None or h[0] != bks:
                self._histograms[key] = [
                    bks, list(counts), float(sum_delta), int(count_delta),
                ]
                return
            h[1] = [a + b for a, b in zip(h[1], counts)]
            h[2] += float(sum_delta)
            h[3] += int(count_delta)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._observations[name].append(value)

    def set_buckets(self, name: str, buckets: Tuple[float, ...]) -> None:
        """Per-family bucket config: every later observation of
        ``name`` (any label set) that does not pass explicit buckets
        uses these.  Call before the first observation — an existing
        series keeps the buckets it was created with."""

        with self._lock:
            self._family_buckets[name] = tuple(buckets)

    def observe_histogram(
        self,
        name: str,
        value: float,
        buckets: "Tuple[float, ...] | None" = None,
        *,
        exemplar: "str | None" = None,
        **labels: str,
    ) -> None:
        """Bounded-memory histogram (Prometheus bucket semantics) — use
        for unbounded-cardinality series like per-sync durations, where
        the raw-observation list of ``observe`` would leak.  Labeled:
        each label set is its own bucket series within the family
        (``serve_ttft_seconds{model="llama-tiny"}``).  ``exemplar``
        records a trace id against the FAMILY (same store and
        latest-write-wins semantics as ``inc``'s exemplars, surfaced
        as ``# exemplar`` comment lines — deliberately not per label
        set: one freshest reproduction per family is the contract the
        dashboard's deep-links parse) — "p99 TTFT is bad" deep-links
        to a request that lived it (ISSUE 11 satellite)."""

        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            if exemplar:
                self._exemplars[name] = str(exemplar)
            h = self._histograms.get(key)
            if h is None:
                bks = (
                    tuple(buckets)
                    if buckets is not None
                    else self._family_buckets.get(name, DEFAULT_BUCKETS)
                )
                h = [bks, [0] * (len(bks) + 1), 0.0, 0]
                self._histograms[key] = h
            bks, counts, _, _ = h
            i = 0
            while i < len(bks) and value > bks[i]:
                i += 1
            counts[i] += 1
            h[2] += value
            h[3] += 1

    def histogram(self, name: str, **labels: str) -> Dict[str, float]:
        """Summary view of one histogram series: count, sum, approx
        p50/p99 (upper bucket bounds)."""

        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                return {"count": 0}
            bks, counts, total, n = h[0], list(h[1]), h[2], h[3]
        return self._summarize(bks, counts, total, n)

    @staticmethod
    def _summarize(bks, counts, total, n) -> Dict[str, float]:
        def quantile(q: float) -> float:
            target = q * n
            acc = 0
            for i, c in enumerate(counts):
                acc += c
                if acc >= target:
                    return bks[i] if i < len(bks) else float("inf")
            return float("inf")

        return {
            "count": n,
            "sum": total,
            "mean": total / n if n else 0.0,
            "p50_le": quantile(0.5),
            "p99_le": quantile(0.99),
        }

    def histogram_family(self, name: str) -> Dict[Tuple[Tuple[str, str], ...], Dict[str, float]]:
        """Every label set of one histogram family, summarized — the
        /slo endpoint's read (``{(("model","x"),): {count, p50_le, ...}}``)."""

        with self._lock:
            items = [
                (labels, (h[0], list(h[1]), h[2], h[3]))
                for (n, labels), h in self._histograms.items()
                if n == name
            ]
        return {
            labels: self._summarize(bks, counts, total, cnt)
            for labels, (bks, counts, total, cnt) in items
        }

    def histogram_family_merged(
        self, name: str, drop: Tuple[str, ...] = ("replica", "role")
    ) -> Dict[Tuple[Tuple[str, str], ...], Dict[str, float]]:
        """``histogram_family`` with the ``drop`` label keys merged
        away: series differing only in those labels sum their bucket
        counts before summarization.  This is the /slo read under
        multi-replica serving (ISSUE 8 bugfix): N per-replica
        ``serve_ttft_seconds{replica=...}`` series become ONE
        user-facing quantile summary instead of N disjoint ones.
        ``role`` rides in the default drop set (ISSUE 13): a
        disaggregated prefill/decode fleet splits its SLO series by
        phase role on /metrics, but the user still sees ONE p99 TTFT.
        Bucket-boundary mismatches (same family observed with
        different explicit buckets) keep those series separate — a
        positional sum would be a lie."""

        merged: Dict[Tuple[Tuple[str, str], ...], list] = {}
        with self._lock:
            items = [
                (labels, (h[0], list(h[1]), h[2], h[3]))
                for (n, labels), h in self._histograms.items()
                if n == name
            ]
        for labels, (bks, counts, total, cnt) in sorted(items):
            key = tuple((k, v) for k, v in labels if k not in drop)
            have = merged.get(key)
            if have is not None and have[0] == bks:
                have[1] = [a + b for a, b in zip(have[1], counts)]
                have[2] += total
                have[3] += cnt
            elif have is None:
                merged[key] = [bks, counts, total, cnt]
            else:
                # incompatible buckets: keep the series distinct under
                # its full label set rather than mis-merge
                merged[labels] = [bks, counts, total, cnt]
        return {
            labels: self._summarize(bks, counts, total, cnt)
            for labels, (bks, counts, total, cnt) in merged.items()
        }

    def counter(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def counter_series(self, name: str) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Every label set of one counter family with its value — the
        alert engine's windowed-increase read (utils/alerts.py)."""

        with self._lock:
            return {
                labels: v
                for (n, labels), v in self._counters.items()
                if n == name
            }

    def gauge_series(self, name: str) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Every label set of one gauge family with its value."""

        with self._lock:
            return {
                labels: v
                for (n, labels), v in self._gauges.items()
                if n == name
            }

    def histogram_raw(
        self, name: str
    ) -> Dict[Tuple[Tuple[str, str], ...], Tuple[Tuple[float, ...], List[int], float, int]]:
        """Raw (buckets, counts, sum, count) per label set of one
        histogram family — the burn-rate evaluator needs cumulative
        bucket counts, not the summarized quantiles."""

        with self._lock:
            return {
                labels: (h[0], list(h[1]), h[2], h[3])
                for (n, labels), h in self._histograms.items()
                if n == name
            }

    def total(self, name: str) -> float:
        """Sum of one counter across all of its label sets (e.g. every
        client's api_client_retries_total)."""

        with self._lock:
            return sum(
                v for (n, _), v in self._counters.items() if n == name
            )

    def summary(self, name: str) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._observations.get(name, []))
        if not vals:
            return {"count": 0}
        return {
            "count": len(vals),
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "p50": vals[len(vals) // 2],
            "p99": vals[min(len(vals) - 1, int(len(vals) * 0.99))],
        }

    def _header(self, lines: List[str], emitted: set, name: str, kind: str) -> None:
        """# HELP + # TYPE once per family, immediately before its
        first sample (Prometheus requires family samples contiguous
        after their metadata; each section is name-sorted so they are).
        Newlines/backslashes in help text are escaped per the text
        format, keeping the exposition line-parseable."""

        if name in emitted:
            return
        emitted.add(name)
        help_text = self._help.get(name, f"{name} ({kind})")
        help_text = help_text.replace("\\", "\\\\").replace("\n", "\\n")
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")

    def exposition(self, families: "Optional[set]" = None) -> str:
        """Prometheus text format (label values escaped per the text
        exposition rules — see ``_escape_label``).  Every family is
        preceded by its ``# HELP`` / ``# TYPE`` metadata lines.
        ``families`` restricts the output to that name set (the
        /federate read) — ONE renderer serves both surfaces, so the
        formats can never drift."""

        def want(name: str) -> bool:
            return families is None or name in families

        lines = []
        emitted: set = set()
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                if not want(name):
                    continue
                self._header(lines, emitted, name, "counter")
                label_s = _label_str(labels)
                lines.append(f"{name}{{{label_s}}} {v}" if label_s else f"{name} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                if not want(name):
                    continue
                self._header(lines, emitted, name, "gauge")
                label_s = _label_str(labels)
                lines.append(f"{name}{{{label_s}}} {v}" if label_s else f"{name} {v}")
            for name, vals in sorted(self._observations.items()):
                if not want(name):
                    continue
                self._header(lines, emitted, name, "summary")
                lines.append(f"{name}_count {len(vals)}")
                lines.append(f"{name}_sum {sum(vals)}")
            for (name, labels), (bks, counts, total, n) in sorted(
                self._histograms.items()
            ):
                if not want(name):
                    continue
                self._header(lines, emitted, name, "histogram")
                label_s = _label_str(labels)
                suffix = f",{label_s}" if label_s else ""
                acc = 0
                for i, b in enumerate(bks):
                    acc += counts[i]
                    lines.append(f'{name}_bucket{{le="{b}"{suffix}}} {acc}')
                lines.append(f'{name}_bucket{{le="+Inf"{suffix}}} {n}')
                lines.append(
                    f"{name}_sum{{{label_s}}} {total}"
                    if label_s
                    else f"{name}_sum {total}"
                )
                lines.append(
                    f"{name}_count{{{label_s}}} {n}"
                    if label_s
                    else f"{name}_count {n}"
                )
            # exemplar links as comments: Prometheus text parsers skip
            # them, the dashboard reads them to deep-link error
            # counters to their trace waterfalls
            for name, tid in sorted(self._exemplars.items()):
                if not want(name):
                    continue
                lines.append(f'# exemplar {name} trace_id="{tid}"')
        return "\n".join(lines) + "\n"

    def counters_snapshot(self) -> Dict[str, float]:
        """Flat {\"name{labels}\": value} copy of every counter and
        gauge — the flight recorder diffs successive snapshots into
        metric-delta records."""

        out: Dict[str, float] = {}
        with self._lock:
            for (name, labels), v in self._counters.items():
                label_s = _label_str(labels)
                out[f"{name}{{{label_s}}}" if label_s else name] = v
            for (name, labels), v in self._gauges.items():
                label_s = _label_str(labels)
                out[f"{name}{{{label_s}}}" if label_s else name] = v
        return out


class DispatchLedger:
    """Device-dispatch accounting for the serving hot path.

    On this box every device call rides a network tunnel whose
    host↔device round trip (~66 ms, measured — benchmarks/PROFILE.md
    "r5 serving") dwarfs the device math it orchestrates, so serving
    walls decompose as ``dispatch count × RTT + device time``.  The
    ledger turns that claim into an auditable number: every serving
    decoder wraps each compiled-program call in ``dispatch(phase)``,
    which counts it and measures the wall time of dispatch + any
    in-block host fetch.  Dispatch COUNTS are platform-independent
    (the same program structure runs everywhere); the measured
    per-dispatch seconds are this box's RTT+device share.

    Phases are strings from the CLOSED ``DISPATCH_PHASES`` taxonomy
    (above — the single source of truth, one line of intent per
    phase): each lowers to a ``dispatch.<phase>`` span that the
    request-autopsy/waterfall layers key on, and the lint in
    tests/test_alert_rules_lint.py pins every literal phase in the
    code against the taxonomy BOTH ways — adding or renaming a phase
    means updating DISPATCH_PHASES in the same change.

    Optional sinks, both None-safe:
      - ``metrics``: every dispatch increments
        ``serving_dispatch_total{phase=...}`` and observes the labeled
        ``serving_dispatch_seconds{phase=...}`` histogram family, so
        ``/metrics`` exports the ledger live;
      - ``tracer``: when the calling thread is inside a trace (e.g. a
        serve_lm request span), each dispatch records a child span
        ``dispatch.<phase>`` — the per-request waterfall shows where
        the round trips went.  Pool dispatches run on the driver
        thread, outside any request context; they carry their request
        id as a span attribute instead (see docs/ARCHITECTURE.md
        "serving dispatch accounting").
    """

    def __init__(
        self,
        metrics: "Metrics | None" = None,
        tracer=None,
        prefix: str = "serving_dispatch",
        span_prefix: str = "dispatch",
    ):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = defaultdict(int)
        self._seconds: Dict[str, float] = defaultdict(float)
        self.metrics = metrics
        self.tracer = tracer
        self.prefix = prefix
        self.span_prefix = span_prefix

    def record(self, phase: str, seconds: float, n: int = 1) -> None:
        with self._lock:
            self._counts[phase] += n
            self._seconds[phase] += seconds
        if self.metrics is not None:
            self.metrics.inc(f"{self.prefix}_total", float(n), phase=phase)
            # ONE labeled family per ledger (``serving_dispatch_seconds
            # {phase="step"}`` / ``train_sync_seconds{phase="window"}``)
            # — training and serving share the exposition shape the
            # SLO panel reads, instead of a name-mangled family per
            # phase
            self.metrics.observe_histogram(
                f"{self.prefix}_seconds", seconds, phase=phase
            )

    @contextlib.contextmanager
    def dispatch(self, phase: str, **attrs: Any):
        """``with ledger.dispatch("step"): fn(...)`` — count one device
        dispatch and time the block (include the host fetch of any
        value you need, so the measured seconds cover the full round
        trip, not just the async enqueue)."""

        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                f"{self.span_prefix}.{phase}", kind="client",
                attributes=attrs or None,
            )
            span.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as exc:
            # a failed device call must show as a FAILED span — error
            # status is what tail sampling protects; closing it ok
            # would get the one trace worth keeping evicted
            if span is not None:
                span.__exit__(type(exc), exc, exc.__traceback__)
                span = None
            raise
        finally:
            dt = time.perf_counter() - t0
            if span is not None:
                span.__exit__(None, None, None)
            self.record(phase, dt)

    # -- reads -------------------------------------------------------------

    def count(self, phase: Optional[str] = None) -> int:
        with self._lock:
            if phase is not None:
                return self._counts.get(phase, 0)
            return sum(self._counts.values())

    def seconds(self, phase: Optional[str] = None) -> float:
        with self._lock:
            if phase is not None:
                return self._seconds.get(phase, 0.0)
            return sum(self._seconds.values())

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{phase: {count, seconds, mean_ms}} — the machine-readable
        ledger benchmarks embed in their JSON output."""

        with self._lock:
            return {
                phase: {
                    "count": n,
                    "seconds": round(self._seconds[phase], 4),
                    "mean_ms": round(self._seconds[phase] / n * 1e3, 2),
                }
                for phase, n in sorted(self._counts.items())
                if n
            }

    def table(self, wall: Optional[float] = None) -> str:
        """Markdown ledger table: phase | dispatches | mean RTT | total.
        With ``wall``, appends the accounting row — dispatch seconds vs
        wall, i.e. how much of the wall the round trips explain."""

        lines = [
            "| phase | dispatches | mean ms/dispatch | total s |",
            "|---|---|---|---|",
        ]
        # subclasses may add "_"-prefixed meta rows (e.g. the sync
        # ledger's _steps summary) that are not dispatch phases
        snap = {
            k: v for k, v in self.snapshot().items() if not k.startswith("_")
        }
        for phase, row in snap.items():
            lines.append(
                f"| {phase} | {row['count']} | {row['mean_ms']} "
                f"| {row['seconds']} |"
            )
        total_n = sum(r["count"] for r in snap.values())
        total_s = sum(r["seconds"] for r in snap.values())
        tail = f"| **all** | {total_n} | — | {round(total_s, 4)} |"
        if wall is not None and wall > 0:
            tail = (
                f"| **all** | {total_n} | — | {round(total_s, 4)} "
                f"(= {total_s / wall:.0%} of {round(wall, 3)} s wall) |"
            )
        lines.append(tail)
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._seconds.clear()


class StepSyncLedger(DispatchLedger):
    """Blocking host↔device sync accounting for the TRAINING hot path —
    the training-side generalization of the serving DispatchLedger.

    Serving's disease was dispatch count × RTT; training's is the dual:
    a single ``float(metrics["loss"])`` per step serializes host
    dispatch against device compute, so the step loop runs at one RTT
    per step regardless of model FLOPs.  This ledger turns "the step
    loop never waits on the device" into an auditable number: every
    value that crosses device→host in the training loop must go through
    :meth:`resolve`, which counts it, times it, and records whether the
    host actually had to WAIT (the arrays were not yet ready — a true
    blocking sync) or merely fetched finished results.

    Phase convention (see docs/ARCHITECTURE.md "training sync
    accounting"):
      ``step``    — a per-step resolve (the K=1 legacy/debug path; any
                    count here during steady state is the bug this
                    ledger exists to catch);
      ``window``  — the deferred every-K-steps resolve of the PREVIOUS
                    metrics window (steady state: the only fetches);
      ``final``   — the end-of-run resolve of the last window;
      ``summary`` — interval summary-writer scalar conversions;
      ``checkpoint`` — waits attributable to checkpoint save budgets.

    The steady-state invariant tests pin (the training twin of "1
    dispatch per request"): **count("step") == 0** for every
    steps_per_sync > 1 run — zero blocking syncs per steady-state step.

    Sinks mirror DispatchLedger: counters ``train_sync_total{phase=}``
    (+ ``train_sync_blocked_total`` when the host provably waited),
    the labeled ``train_sync_seconds{phase=}`` histogram family, and
    ``sync.<phase>`` trace spans.
    """

    def __init__(
        self,
        metrics: "Metrics | None" = None,
        tracer=None,
        prefix: str = "train_sync",
    ):
        super().__init__(
            metrics=metrics, tracer=tracer, prefix=prefix, span_prefix="sync"
        )
        self._blocked: Dict[str, int] = defaultdict(int)
        self._steps = 0

    def step(self, n: int = 1) -> None:
        """Mark ``n`` training steps dispatched (host-side counter — a
        device read here would be the very sync this ledger forbids)."""

        with self._lock:
            self._steps += n

    @property
    def steps(self) -> int:
        with self._lock:
            return self._steps

    def resolve(self, phase: str, tree):
        """THE sanctioned device→host fetch: returns host (numpy)
        values for ``tree``'s leaves.  Counted under ``phase``; if any
        leaf was still computing when the fetch started, the resolve is
        additionally counted as BLOCKED (the host waited on the device,
        not just on the wire).  The static lint gate
        (tests/test_lint_no_hot_sync.py) forbids raw ``float()`` /
        ``device_get`` in the step-loop bodies precisely so every sync
        funnels through here."""

        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        ready = all(
            getattr(x, "is_ready", lambda: True)() for x in leaves
        )
        with self.dispatch(phase, blocked=not ready):
            out = jax.device_get(tree)
        if not ready:
            with self._lock:
                self._blocked[phase] += 1
            if self.metrics is not None:
                self.metrics.inc(
                    f"{self.prefix}_blocked_total", 1.0, phase=phase
                )
        return out

    def blocked(self, phase: Optional[str] = None) -> int:
        """Resolves where the host provably WAITED on device compute
        (leaves not ready at fetch start).  Indicative, not pinned: on
        fast hosts a window's arrays often finish before the deferred
        resolve arrives, so blocked <= count by design."""

        with self._lock:
            if phase is not None:
                return self._blocked.get(phase, 0)
            return sum(self._blocked.values())

    def per_step(self, phase: Optional[str] = None) -> float:
        """Syncs per dispatched training step (count/steps; 0 when no
        steps were marked)."""

        n = self.count(phase)
        with self._lock:
            return n / self._steps if self._steps else 0.0

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """DispatchLedger's {phase: {count, seconds, mean_ms}} plus
        per-phase ``blocked`` and a ``_steps`` summary row — the shape
        measure.py embeds in the K-sweep artifact."""

        snap = super().snapshot()
        with self._lock:
            for phase, row in snap.items():
                row["blocked"] = self._blocked.get(phase, 0)
            steps = self._steps
        total = sum(r["count"] for r in snap.values())
        snap["_steps"] = {
            "count": steps,
            "syncs_per_step": round(total / steps, 4) if steps else 0.0,
        }
        return snap

    def reset(self) -> None:
        super().reset()
        with self._lock:
            self._blocked.clear()
            self._steps = 0


#: process-global default registry (controller accepts an override)
default_metrics = Metrics()
