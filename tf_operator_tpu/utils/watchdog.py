"""Stall watchdog: a stalled train loop or reconcile worker must
produce a diagnosis, not silence.

Hot loops register a :class:`Heartbeat` and call ``beat()`` once per
iteration (a host-side ``time.monotonic`` write — nothing here touches
the device, so the PR-4 no-hot-sync gate is unaffected).  A started
:class:`Watchdog` checks every heartbeat against its deadline on a
background thread; the first missed deadline of a stall episode

  - increments ``watchdog_stall_total{heartbeat=...}``,
  - warn-logs the stall WITH the trace id the heartbeat last carried
    (exemplar linkage: the log names the waterfall that was in flight),
  - dumps every thread's stack plus the flight recorder's rings
    (utils/flight.py) to one JSONL postmortem file.

A later beat ends the episode (and logs recovery), so a slow-but-alive
loop produces one diagnosis per stall, not a log storm.

Registration is always cheap and safe: heartbeats are plain objects;
nothing fires unless a watchdog was started (``start()`` — opt-in, the
operator/serving binaries start one when ``TPUJOB_WATCHDOG=1``).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Optional

from tf_operator_tpu.utils.logging import FieldLogger, _root
from tf_operator_tpu.utils.trace import current_trace_id


class Heartbeat:
    """One monitored loop.  ``beat()`` per iteration; ``deadline``
    seconds without a beat = stalled."""

    __slots__ = ("name", "deadline", "last", "beats", "trace_id", "stalled")

    def __init__(self, name: str, deadline: float):
        self.name = name
        self.deadline = float(deadline)
        self.last = time.monotonic()
        self.beats = 0
        self.trace_id: Optional[str] = None
        self.stalled = False

    def beat(self) -> None:
        # capture BEFORE stamping the time: the id names the work the
        # loop was doing when it last checked in
        self.trace_id = current_trace_id() or self.trace_id
        self.last = time.monotonic()
        self.beats += 1


def thread_stacks() -> str:
    """Plain-text dump of every thread's current stack (the same shape
    the operator's /debug/stacks serves)."""

    import sys

    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for tid, frame in sys._current_frames().items():
        chunks.append(
            f"--- thread {names.get(tid, '?')} (id {tid}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(chunks)


class Watchdog:
    def __init__(
        self,
        metrics=None,
        recorder=None,
        check_interval: float = 1.0,
        default_deadline: float = 60.0,
    ):
        self._lock = threading.Lock()
        self._beats: Dict[str, Heartbeat] = {}
        self._metrics = metrics
        self._recorder = recorder
        self.check_interval = float(check_interval)
        self.default_deadline = float(default_deadline)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = FieldLogger(_root, component="watchdog")
        #: paths of postmortem dumps written (newest last; tests read it)
        self.dumps: List[str] = []

    # -- registration -------------------------------------------------------

    def register(self, name: str, deadline: Optional[float] = None) -> Heartbeat:
        """Create (or replace) the named heartbeat.  Replacing resets
        the clock — re-registration after a crash-restart is a fresh
        episode, not an instant stall."""

        hb = Heartbeat(name, deadline if deadline is not None else self.default_deadline)
        with self._lock:
            self._beats[name] = hb
        return hb

    def unregister(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)

    def heartbeats(self) -> Dict[str, Heartbeat]:
        with self._lock:
            return dict(self._beats)

    # -- monitoring ---------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Watchdog":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="stall-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 - the watchdog must outlive bugs
                self._log.error("watchdog sweep failed: %s: %s",
                                type(e).__name__, e)

    def check_once(self, now: Optional[float] = None) -> List[str]:
        """One deadline sweep (pure-ish, tests drive it directly).
        Returns the names that newly entered a stall this sweep."""

        now = time.monotonic() if now is None else now
        newly_stalled: List[str] = []
        with self._lock:
            beats = list(self._beats.values())
        for hb in beats:
            overdue = now - hb.last
            if overdue > hb.deadline:
                if not hb.stalled:
                    hb.stalled = True
                    newly_stalled.append(hb.name)
                    self._on_stall(hb, overdue)
            elif hb.stalled:
                hb.stalled = False
                self._log.info(
                    "heartbeat %s recovered after stall", hb.name
                )
        return newly_stalled

    def _on_stall(self, hb: Heartbeat, overdue: float) -> None:
        if self._metrics is not None:
            self._metrics.inc("watchdog_stall_total", heartbeat=hb.name)
        self._log.warning(
            "STALL: heartbeat %s silent %.1fs (deadline %.1fs, beats=%d) "
            "[trace=%s]",
            hb.name, overdue, hb.deadline, hb.beats, hb.trace_id or "-",
        )
        recorder = self._recorder
        if recorder is None:
            from tf_operator_tpu.utils.flight import default_recorder

            recorder = default_recorder
        # the postmortem: metric deltas since the last snapshot, every
        # thread's stack (as a log record so it rides the same dump),
        # then the rings
        recorder.snapshot_metrics(label=f"stall:{hb.name}")
        recorder.record_log(
            "WARNING", "watchdog", f"thread stacks at stall of {hb.name}",
            fields={"stacks": thread_stacks(), "trace": hb.trace_id},
        )
        path = recorder.dump(reason=f"stall-{hb.name.replace('/', '_')}")
        if path:
            self.dumps.append(path)
            self._log.warning("flight recorder dumped to %s", path)


#: process-global default (mirrors metrics/tracer/flight defaults).
#: NOT started: registration is free; monitoring is opt-in via
#: ``default_watchdog.start()`` or TPUJOB_WATCHDOG=1 in the binaries.
default_watchdog = Watchdog()


def maybe_start_from_env(metrics=None) -> Optional[Watchdog]:
    """Start the default watchdog when TPUJOB_WATCHDOG=1 (deadline
    override via TPUJOB_WATCHDOG_DEADLINE seconds).  The binaries call
    this once at boot."""

    import os

    if os.environ.get("TPUJOB_WATCHDOG") != "1":
        return None
    if metrics is not None:
        default_watchdog._metrics = metrics
    elif default_watchdog._metrics is None:
        from tf_operator_tpu.utils.metrics import default_metrics

        default_watchdog._metrics = default_metrics
    import math

    dl = os.environ.get("TPUJOB_WATCHDOG_DEADLINE")
    if dl:
        # a typo in an opt-in diagnostics knob must not take the
        # binary down at boot, and nan/inf/<=0 would silently disarm
        # the watchdog (or stall-storm every heartbeat) — warn and
        # keep the default for anything but a finite positive float
        try:
            parsed = float(dl)
        except ValueError:
            parsed = None
        if parsed is not None and math.isfinite(parsed) and parsed > 0:
            default_watchdog.default_deadline = parsed
        else:
            default_watchdog._log.warning(
                "ignoring malformed TPUJOB_WATCHDOG_DEADLINE=%r "
                "(want seconds as a finite positive float); keeping %.0fs",
                dl, default_watchdog.default_deadline,
            )
    return default_watchdog.start()
